# Top-level build/test fan-out (reference parity: components/Makefile:1-46
# fans docker-build over every component; here the components share one
# python package, so the fan-out is test tiers + image builds).

# NOTE: no PYTHONPATH export — on TPU hosts it can break accelerator
# plugin registration. Targets run from the repo root and use `-m`, so
# the cwd lands on sys.path instead.
PYTHON ?= python

.PHONY: all test test-unit test-manifests lint sanitize chaos durability explore fleetbench replicabench partitionbench overloadbench zonedrill usagebench warmbench obs loadtest images bench dryrun platform serve spawn-latency suspend-bench webbench native kind-smoke conformance

all: lint test

test: test-unit

test-unit:
	$(PYTHON) -m pytest tests/ -q

test-manifests:
	$(PYTHON) -m pytest tests/test_manifests.py -q

# one continuous capability sequence certifying the platform contract:
# register -> spawn -> ready -> share -> quota-reject -> cull ->
# restart -> preempt -> gang-restart -> elastic-resume -> delete
conformance:
	$(PYTHON) -m odh_kubeflow_tpu.conformance

# syntax check + graftlint: per-file AST invariant rules PLUS the
# whole-program call-graph rules (lock-order-cycle,
# blocking-reachable-under-lock, await-holding-lock) and the
# exception-flow rules (error-contract, handler-masks-fencing,
# dead-except) — see docs/GUIDE.md "Static analysis & concurrency
# discipline" and "Error contracts". Exit-code gated; fails only on
# findings NOT in analysis/baseline.json. The knob-registry lint
# cross-checks every os.environ knob against analysis/knobs.json,
# GUIDE.md, and manifest env stanzas.
lint:
	$(PYTHON) -m compileall -q odh_kubeflow_tpu tests loadtest bench.py __graft_entry__.py
	$(PYTHON) -m odh_kubeflow_tpu.analysis
	$(PYTHON) -m odh_kubeflow_tpu.analysis.knobs
	$(PYTHON) -m odh_kubeflow_tpu.analysis.protocol

# deterministic schedule explorer (docs/GUIDE.md "Deterministic
# schedule exploration"): seeded one-runnable-at-a-time interleavings
# of the group-commit pipeline (writers x committer x snapshot cut),
# lease-fencing handover, and informer heal-vs-read — plus the
# reverted historical races (rate-limiter sleep-under-lock, store
# apply-before-fsync) the explorer must re-find and replay from their
# printed seeds. GRAFT_SCHED=<n> multiplies the schedule budgets: the
# CI pyramid runs 1x, CI's dedicated explore step 3x; crank it for
# deeper local sweeps (`make explore GRAFT_SCHED=8`).
GRAFT_SCHED ?= 1
explore:
	GRAFT_SCHED=$(GRAFT_SCHED) $(PYTHON) -m pytest -q tests/test_schedule.py

# seeded chaos suite: resilience property tests under injected
# conflicts, 429s, 5xx, watch-stream drops, and resourceVersion expiry
# (GRAFT_CHAOS seeds every schedule — reproducible CI runs), with the
# concurrency sanitizer armed so recovery paths are race-probed too
chaos:
	GRAFT_CHAOS=1 GRAFT_SANITIZE=1 $(PYTHON) -m pytest -q \
	  tests/test_chaos.py tests/test_leader.py \
	  tests/test_sessions.py::test_property_random_suspend_resume_under_chaos \
	  tests/test_warmup.py::test_singleflight_dedups_concurrent_compiles \
	  tests/test_warmup.py::test_concurrent_claims_hand_out_exactly_one_standby

# crash/failover drills (docs/GUIDE.md "Durability & failover"): WAL
# kill-point sweep (process death at every commit point), disk-fault
# schedules (torn write / failed fsync / short read), fencing-token
# regression, and the sharded-manager failover drill — all under the
# sanitizer and a seeded chaos schedule — then the recovery axis of
# the control-plane bench (cold-recovery time + failover p99; writes
# to a scratch copy so the committed BENCH numbers change only when
# refreshed deliberately)
durability:
	GRAFT_SANITIZE=1 GRAFT_CHAOS=7 $(PYTHON) -m pytest -q \
	  tests/test_durability.py tests/test_leader.py \
	  tests/test_warmup.py::test_claim_kill_point_sweep_no_double_handout \
	  tests/test_warmup.py::test_cache_entries_survive_wal_failover
	cp BENCH_control_plane.json /tmp/durability_bench.json
	$(PYTHON) loadtest/control_plane_bench.py --recovery-only \
	  --recovery-counts 500,2000 --failover-reps 6 \
	  --out /tmp/durability_bench.json

# fleet-scale smoke (ISSUE 10): the 25k-notebook axis scaled down to
# N=2000 with the SAME gates — group-commit ingest >=5x the
# fsync-per-record baseline under 12 concurrent writers, paginated
# list p99 bounded with no page over the limit, watch fanout +
# admission-wait + cold-recovery recorded. Writes to a scratch copy so
# the committed BENCH numbers change only when refreshed deliberately
# (full run: `python loadtest/control_plane_bench.py --fleet
# --notebooks 25000`).
fleetbench:
	cp BENCH_control_plane.json /tmp/fleetbench.json
	$(PYTHON) loadtest/control_plane_bench.py --fleet --notebooks 2000 \
	  --fleet-watchers 50 --out /tmp/fleetbench.json
	$(PYTHON) -m pytest -q tests/test_fleet.py

# read-replica smoke (ISSUE 13): the 100k-notebook / 1000-stream axis
# scaled down to N=2000 with 2 followers and 100 streams, SAME gates —
# shipping must tax leader ingest <10%, follower state bit-identical,
# replica-served list p99 within the PR-10 leader-only bounds, sharded
# watch fanout p99 within the PR-10 26ms bound, staleness p99 <250ms
# under write load. Writes to a scratch copy (full run: `python
# loadtest/control_plane_bench.py --replica --notebooks 100000`).
replicabench:
	cp BENCH_control_plane.json /tmp/replicabench.json
	$(PYTHON) loadtest/control_plane_bench.py --replica --notebooks 2000 \
	  --replica-streams 100 --out /tmp/replicabench.json
	$(PYTHON) -m pytest -q tests/test_replica.py

# partitioned write path (ISSUE 18, docs/GUIDE.md "Partitioned write
# path"): the N=1M x 4-partition axis scaled down to N=2000 — real
# leader PROCESSES behind client-side HRW routing, SAME correctness
# gates (per-leader counts sum to N, merged limit/continue walk with
# composite tokens has zero order/duplicate violations, cluster-
# spanning merged watch delivers a post-ingest burst exactly once).
# The >=5x aggregate-ingest speedup gate only binds on hosts with
# >= 4 CPUs (leader compute cannot overlap on fewer cores); the
# measured ratio is always recorded. Writes to a scratch copy (full
# run: `python loadtest/control_plane_bench.py --partition
# --notebooks 1000000`).
partitionbench:
	cp BENCH_control_plane.json /tmp/partitionbench.json
	$(PYTHON) loadtest/control_plane_bench.py --partition --notebooks 2000 \
	  --partitions 4 --out /tmp/partitionbench.json
	$(PYTHON) -m pytest -q tests/test_partition.py

# overload-defense axis (docs/GUIDE.md "Overload defense"): the seeded
# metastable-failure drill — a 4x-capacity burst with one
# latency-poisoned partition — gated on burst goodput (>= 70% of
# baseline), retry amplification (<= 1.3x), system-traffic p99 under
# flood, recovery within 10s of burst end, and seed-exact replay;
# then the deadline/budget/breaker/priority unit suite under the
# sanitizer. Writes to a scratch copy of the bench JSON.
overloadbench:
	cp BENCH_control_plane.json /tmp/overloadbench.json
	$(PYTHON) loadtest/control_plane_bench.py --overload \
	  --out /tmp/overloadbench.json
	GRAFT_SANITIZE=1 $(PYTHON) -m pytest -q tests/test_overload.py

# zone failure-domain drills (docs/GUIDE.md "Zones & failure
# domains"): replicated-checkpoint write-all/heal, zone-spread
# placement, drain_zone checkpoint-then-migrate, NodeLost-storm
# escalation, the seeded zone-kill drill (one zone's checkpoint stores
# + nodes die mid-session; every suspended session resumes in the
# surviving zone bit-identical) and the promotion watchdog's hands-off
# failover — all under the sanitizer + a seeded chaos schedule, then
# the end-to-end two-act drill script
zonedrill:
	GRAFT_SANITIZE=1 GRAFT_CHAOS=17 $(PYTHON) -m pytest -q tests/test_zones.py
	GRAFT_SANITIZE=1 $(PYTHON) -m loadtest.zone_drill

# chip-hour metering drills (docs/GUIDE.md "Usage metering &
# showback"): the meter's unit invariants + activity-agent probe
# robustness under sanitizer + seeded chaos, the seeded
# accounting-exactness drill (lifecycle churn + wedged agent + WAL
# failover, ledger reconciled against a straight-line accountant to
# ε), then the metering-overhead axis of the control-plane bench
# (meter CPU per sampling window ≤2% of one core; writes to a scratch
# copy so committed BENCH numbers change only when refreshed
# deliberately)
# warm-start drills (docs/GUIDE.md "Compilation cache & warm pools"):
# the full warmup suite under the sanitizer (singleflight, corrupt
# artifact, TTL/LRU GC, zone fail/heal, WAL failover, claim race +
# kill-point sweep, zone-kill drain+backfill, JWA warm handout), then
# the gated cold-vs-warm bench — warm spawn must beat the cold spawn
# inside ONE sim run and the cache-service compile roundtrip must
# land the warm compile under 1s
warmbench:
	GRAFT_SANITIZE=1 $(PYTHON) -m pytest -q tests/test_warmup.py
	GRAFT_SANITIZE=1 $(PYTHON) -m loadtest.spawn_latency --warm-only

usagebench:
	GRAFT_SANITIZE=1 GRAFT_CHAOS=20591 $(PYTHON) -m pytest -q \
	  tests/test_usage.py tests/test_culler.py
	GRAFT_SANITIZE=1 $(PYTHON) -m loadtest.usage_drill
	cp BENCH_control_plane.json /tmp/usagebench.json
	$(PYTHON) loadtest/control_plane_bench.py --usage \
	  --out /tmp/usagebench.json

# the randomized property suites re-run as race probes: sanitized
# locks record acquisition order, re-entry, and blocking-under-lock
sanitize:
	GRAFT_SANITIZE=1 $(PYTHON) -m pytest -q \
	  tests/test_analysis.py \
	  tests/test_cache.py::test_cache_coherence_property_randomized_crud \
	  tests/test_scheduling.py::test_property_random_admit_preempt_node_loss_sequences \
	  tests/test_sessions.py::test_property_random_suspend_resume_oversubscribed

# observability smoke (docs/GUIDE.md "Tracing, zpages & SLOs"): spawn
# one notebook under a client trace against the sim platform and gate
# the whole surface — ONE assembled trace with the
# admission/gang-bind/container-start spans, OpenMetrics + trace-id
# exemplars under content negotiation (plain exposition byte-stable),
# SLO burn rates on /api/slo + slo_burn_rate gauges, /debug zpages
obs:
	$(PYTHON) -m loadtest.obs_smoke

# platform load test against the embedded apiserver + sim kubelet
# (loadtest/start_notebooks.py; reference notebook-controller/loadtest)
loadtest:
	$(PYTHON) -m loadtest.start_notebooks --count 20 --tpu

spawn-latency:
	$(PYTHON) -m loadtest.spawn_latency --record

# suspend → reopen → ready warm-resume gate (sessions/ subsystem): the
# cold platform spawn vs the checkpoint-backed resume, state verified
# bit-identical; runs on the sim kubelet, no accelerator needed
suspend-bench:
	$(PYTHON) -m loadtest.spawn_latency --suspend-only

# web-tier concurrency axis of the control-plane bench: thread-per-
# request + stdlib json baseline vs event loop + native serializer +
# bytes cache, over real sockets (gates >=10x concurrent req/s and no
# serial p99 regression; see docs/GUIDE.md "Async web tier")
webbench:
	$(PYTHON) loadtest/control_plane_bench.py

# C++ host-side components (input-pipeline packer + jsontree
# deepcopy/dumps); lazy-built on first import too — this target just
# front-loads the compiles
native:
	$(PYTHON) -c "from odh_kubeflow_tpu import native; so = native.build(force=True); \
	  import sys; print(so) if so else sys.exit('no C++ compiler found')"
	$(PYTHON) -c "from odh_kubeflow_tpu import native; import sys; \
	  ok = native.jsontree_deepcopy() and native.jsontree_dumps(); \
	  print('jsontree: deepcopy+dumps built') if ok else sys.exit('jsontree build failed')"

images:
	$(MAKE) -C images build

bench:
	$(PYTHON) bench.py

# all-in-one platform with the sim kubelet (see docs/GUIDE.md)
platform:
	$(PYTHON) -m odh_kubeflow_tpu.platform --sim

# completion server in demo mode on the attached accelerator
serve:
	$(PYTHON) -m odh_kubeflow_tpu.models.serve --config llama3_1b --int8

# real-cluster smoke: build the platform container, load into KinD,
# apply manifests, require Notebook -> StatefulSet (needs docker+kind;
# CI runs the same flow in nb_controller_kind_test.yaml)
kind-smoke:
	kind create cluster --name kubeflow-tpu || true
	docker build -t odh-kubeflow-tpu/platform:latest -f images/platform/Dockerfile .
	kind load docker-image odh-kubeflow-tpu/platform:latest --name kubeflow-tpu
	kubectl create namespace kubeflow --dry-run=client -o yaml | kubectl apply -f -
	kubectl apply -f manifests/crds/ -f manifests/cluster-roles/ -f manifests/notebook-controller/
	kubectl -n kubeflow rollout status deployment/notebook-controller --timeout=180s

# multi-chip sharding compile check on a virtual 8-device CPU mesh
dryrun:
	XLA_FLAGS="--xla_force_host_platform_device_count=8" $(PYTHON) -c \
	  "import importlib.util; \
	   s = importlib.util.spec_from_file_location('g', '__graft_entry__.py'); \
	   m = importlib.util.module_from_spec(s); s.loader.exec_module(m); \
	   m.dryrun_multichip(8)"
