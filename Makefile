# Top-level build/test fan-out (reference parity: components/Makefile:1-46
# fans docker-build over every component; here the components share one
# python package, so the fan-out is test tiers + image builds).

PYTHON ?= python
export PYTHONPATH := $(CURDIR)$(if $(PYTHONPATH),:$(PYTHONPATH))

.PHONY: all test test-unit test-manifests lint loadtest images bench dryrun

all: lint test

test: test-unit

test-unit:
	$(PYTHON) -m pytest tests/ -q

test-manifests:
	$(PYTHON) -m pytest tests/test_manifests.py -q

lint:
	$(PYTHON) -m compileall -q odh_kubeflow_tpu tests loadtest bench.py __graft_entry__.py

# platform load test against the embedded apiserver + sim kubelet
# (loadtest/start_notebooks.py; reference notebook-controller/loadtest)
loadtest:
	$(PYTHON) loadtest/start_notebooks.py --count 20 --tpu

images:
	$(MAKE) -C images build

bench:
	$(PYTHON) bench.py

# multi-chip sharding compile check on a virtual 8-device CPU mesh
dryrun:
	XLA_FLAGS="--xla_force_host_platform_device_count=8" $(PYTHON) -c \
	  "import importlib.util; \
	   s = importlib.util.spec_from_file_location('g', '__graft_entry__.py'); \
	   m = importlib.util.module_from_spec(s); s.loader.exec_module(m); \
	   m.dryrun_multichip(8)"
