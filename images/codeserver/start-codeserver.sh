#!/bin/bash
# Launch code-server under the platform's 8888/$NB_PREFIX contract.
# TPU variants additionally ship tpu-activity-agent — without it a
# busy-but-quiet training session would be culled (the culler's only
# activity signal for non-Jupyter servers is the TPU duty cycle).
set -euo pipefail

if command -v tpu-init >/dev/null 2>&1; then
  tpu-init || echo "tpu-init failed; continuing (CPU fallback)" >&2
fi

if command -v tpu-activity-agent >/dev/null 2>&1; then
  tpu-activity-agent &
fi

exec code-server --bind-addr 0.0.0.0:8888 --auth none --disable-telemetry "${HOME}"
