#!/bin/bash
# Launch JupyterLab under the platform's path-prefix contract.
# NB_PREFIX is injected by the notebook controller
# (odh_kubeflow_tpu/controllers/notebook.py; reference
# notebook_controller.go:402-416). tpu-init is a no-op on CPU images /
# single-host slices.
set -euo pipefail

if command -v tpu-init >/dev/null 2>&1; then
  tpu-init || echo "tpu-init failed; continuing (CPU fallback)" >&2
fi

# TPU variants ship the activity agent the culler probes on :8890
if command -v tpu-activity-agent >/dev/null 2>&1; then
  tpu-activity-agent &
fi

# Seed the IPython kernel-startup hook that auto-starts the JAX
# profiler server (TensorBoard "capture profile" against this
# notebook; odh_kubeflow_tpu.utils.profiling). HOME is the user's
# PVC, so only seed when absent — the user may edit or remove it.
STARTUP_DIR="${HOME}/.ipython/profile_default/startup"
if [ ! -f "${STARTUP_DIR}/00-tpu-profiler.py" ]; then
  mkdir -p "${STARTUP_DIR}"
  python - <<'PYEOF' > "${STARTUP_DIR}/00-tpu-profiler.py" 2>/dev/null || true
try:
    from odh_kubeflow_tpu.utils.profiling import kernel_startup_snippet
    print(kernel_startup_snippet())
except Exception:
    pass
PYEOF
fi

exec jupyter lab \
  --notebook-dir="${HOME}" \
  --ip=0.0.0.0 \
  --port=8888 \
  --no-browser \
  --ServerApp.base_url="${NB_PREFIX}" \
  --ServerApp.token='' \
  --ServerApp.password='' \
  --ServerApp.allow_origin='*' \
  --ServerApp.authenticate_prometheus=False
