"""Per-component device-time budget for the 1B@16k step (VERDICT r4 item 4)."""
import json, tempfile, collections
import jax, jax.numpy as jnp
from odh_kubeflow_tpu.models import LlamaConfig, LoraConfig
from odh_kubeflow_tpu.parallel.mesh import MeshConfig, build_mesh
from odh_kubeflow_tpu.train import TrainConfig, Trainer
from odh_kubeflow_tpu.utils import profiling

cfg = LlamaConfig.llama3_1b(dtype=jnp.bfloat16, remat_policy="attn_mlp")
tr = Trainer(cfg, TrainConfig(warmup_steps=2, total_steps=100), lora_cfg=LoraConfig(rank=16),
             mesh=build_mesh(MeshConfig(fsdp=1), jax.devices()))
batch = tr.make_fake_batch(1, 16384)
for _ in range(2):
    m = tr.train_step(batch)
float(m["loss"])
logdir = tempfile.mkdtemp(prefix="prof_")
with jax.profiler.trace(logdir):
    m = tr.train_step(batch)
    float(m["loss"])
events = profiling.latest_trace_events(logdir)
proc, thr = {}, {}
for e in events:
    if e.get("ph") == "M" and e.get("name") == "process_name":
        proc[e["pid"]] = e["args"].get("name", "")
    if e.get("ph") == "M" and e.get("name") == "thread_name":
        thr[(e["pid"], e.get("tid"))] = e["args"].get("name", "")
dpids = {p for p, n in proc.items() if "TPU" in n or "xla" in n.lower() or "/device" in n.lower()}
lanes = collections.defaultdict(list)
for e in events:
    if e.get("ph") != "X" or e.get("pid") not in dpids: continue
    t = thr.get((e["pid"], e.get("tid")), "").lower()
    if "step" in t or "module" in t: continue
    lanes[(e["pid"], e.get("tid"))].append(e)

def cat(e):
    n = e.get("name", "")
    ln = e.get("args", {}).get("long_name", "") or n
    if "custom-call" in ln or n.startswith(("checkpoint", "closed_call")):
        return "flash_kernels"
    if "128256" in ln:
        return "ce_head"
    if "8192" in ln:
        return "mlp_matmuls"
    if "32,64" in ln or "16384,32" in ln or "16384,8," in ln or ",8,16384" in ln:
        return "attn_proj_rope"
    if n.startswith(("copy", "bitcast")) and "fusion" not in n:
        return "copies"
    return "elementwise_other"

by = collections.Counter(); total = 0.0
for lane in lanes.values():
    lane.sort(key=lambda e: (e["ts"], -e.get("dur", 0)))
    stack, recs = [], []
    for e in lane:
        ts, dur = e["ts"], e.get("dur", 0)
        while stack and ts >= stack[-1][0]: stack.pop()
        rec = [e, dur, 0.0]
        if stack: recs[stack[-1][1]][2] += dur
        recs.append(rec); stack.append((ts + dur, len(recs) - 1))
    for e, dur, child in recs:
        st = max(dur - child, 0.0)
        by[cat(e)] += st; total += st
print(json.dumps({"total_ms": round(total/1e3, 1),
    **{k: round(v/1e3, 1) for k, v in by.most_common()}}))
