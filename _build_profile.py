"""Cold trainer-build phase profile on the real chip."""
import time, json, os
t00 = time.monotonic()
import jax, jax.numpy as jnp
from functools import partial
from odh_kubeflow_tpu.models import LlamaConfig, LoraConfig
from odh_kubeflow_tpu.models import llama
from odh_kubeflow_tpu.models import lora as lora_lib
from odh_kubeflow_tpu.parallel.mesh import MeshConfig, build_mesh
from odh_kubeflow_tpu.train import TrainConfig, Trainer
from odh_kubeflow_tpu.train.trainer import _make_optimizer
from jax.sharding import NamedSharding, PartitionSpec as P
devices = jax.devices()
t_imp = time.monotonic() - t00

cfg = LlamaConfig.llama3_1b(dtype=jnp.bfloat16)
mesh = build_mesh(MeshConfig(fsdp=len(devices)), devices)
lcfg = LoraConfig(rank=16)
tcfg = TrainConfig(warmup_steps=2, total_steps=100)
opt = _make_optimizer(tcfg)
sh = lambda specs: jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), specs, is_leaf=lambda s: isinstance(s, P))

out = {"import_s": round(t_imp, 2)}
with jax.set_mesh(mesh):
    t0 = time.monotonic()
    p_specs = llama.param_specs(cfg)
    init_fn = jax.jit(partial(llama.init_params, cfg=cfg, dtype=cfg.dtype), out_shardings=sh(p_specs))
    params = init_fn(jax.random.key(0))
    jax.block_until_ready(params)  # no-op on relay; sync via fetch below
    float(params["final_norm"][0])
    out["param_init_s"] = round(time.monotonic() - t0, 2)

    t0 = time.monotonic()
    l_specs = lora_lib.lora_specs(cfg, lcfg)
    lora_init = jax.jit(partial(lora_lib.init_lora_params, cfg=cfg, lora=lcfg), out_shardings=sh(l_specs))
    lp = lora_init(jax.random.key(1))
    float(jax.tree_util.tree_leaves(lp)[0].ravel()[0])
    out["lora_init_s"] = round(time.monotonic() - t0, 2)

    t0 = time.monotonic()
    import optax
    shapes = jax.eval_shape(opt.init, lp)
    o_specs = optax.tree_map_params(opt, lambda _l, s: s, shapes, l_specs, transform_non_params=lambda _l: P())
    out["opt_spec_s"] = round(time.monotonic() - t0, 2)
    t0 = time.monotonic()
    opt_init = jax.jit(opt.init, out_shardings=sh(o_specs))
    ost = opt_init(lp)
    float(jax.tree_util.tree_leaves(ost)[0].ravel()[0] if jax.tree_util.tree_leaves(ost) else 0.0)
    out["opt_init_s"] = round(time.monotonic() - t0, 2)

print(json.dumps(out))
t0 = time.monotonic()
tr = Trainer(cfg, tcfg, lora_cfg=lcfg, mesh=mesh)
print(json.dumps({"full_trainer_build_again_s": round(time.monotonic() - t0, 2)}))
