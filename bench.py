"""Benchmark: Llama LoRA fine-tune MFU on the attached TPU.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The reference platform publishes no perf numbers (BASELINE.md); the
north star from BASELINE.json is >=50% MFU on a Llama LoRA fine-tune
from a notebook, so ``vs_baseline`` is measured MFU / 0.50.

Model is the Llama-3.2-1B shape (fits one v5e chip with optimizer state
for LoRA adapters only); MFU accounting uses 3x forward matmul FLOPs
and the chip's bf16 peak from ``utils/tpu.py``.
"""

from __future__ import annotations

import json
import os
import sys


def main() -> None:
    os.environ.setdefault("JAX_TRACEBACK_FILTERING", "off")
    import jax
    import jax.numpy as jnp

    from odh_kubeflow_tpu.models import LlamaConfig, LoraConfig
    from odh_kubeflow_tpu.parallel.mesh import MeshConfig, build_mesh
    from odh_kubeflow_tpu.train import TrainConfig, Trainer
    from odh_kubeflow_tpu.utils.tpu import peak_flops_per_chip

    devices = jax.devices()
    n = len(devices)
    peak = peak_flops_per_chip(devices[0]) * n

    batch_size = int(os.environ.get("BENCH_BATCH", "8"))
    seq_len = int(os.environ.get("BENCH_SEQ", "1024"))
    steps = int(os.environ.get("BENCH_STEPS", "10"))
    # batch must tile the data-parallel extent (= all devices here)
    batch_size = -(-max(batch_size, n) // n) * n

    cfg = LlamaConfig.llama3_1b(dtype=jnp.bfloat16)
    trainer = Trainer(
        cfg,
        TrainConfig(warmup_steps=2, total_steps=100),
        lora_cfg=LoraConfig(rank=16),
        mesh=build_mesh(MeshConfig(fsdp=n), devices),
    )
    stats = trainer.benchmark(batch_size, seq_len, steps=steps, warmup=2)

    if peak > 0:
        value = stats["flops_per_s"] / peak
        metric, unit = "llama1b_lora_train_mfu", "mfu"
        vs_baseline = value / 0.50  # north-star: 50% MFU
    else:
        value = stats["tokens_per_s"]
        metric, unit = "llama1b_lora_train_tokens_per_s", "tokens/s"
        vs_baseline = 0.0

    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(value, 4),
                "unit": unit,
                "vs_baseline": round(vs_baseline, 4),
                "detail": {
                    "devices": n,
                    "device_kind": getattr(devices[0], "device_kind", "cpu"),
                    "batch": batch_size,
                    "seq": seq_len,
                    "step_time_s": round(stats["step_time_s"], 4),
                    "tokens_per_s": round(stats["tokens_per_s"], 1),
                    "loss": round(stats["loss"], 4),
                },
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
