"""Benchmark: Llama LoRA fine-tune MFU on the attached TPU.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "detail": {...}}

The reference platform publishes no perf numbers (BASELINE.md); the
north star from BASELINE.json is >=50% MFU on a Llama-3-**8B** LoRA
fine-tune from a notebook, so ``vs_baseline`` is measured MFU / 0.50.

Headline: **Llama-3-8B QLoRA** (int8 frozen base + LoRA r16, seq 4096)
on the attached chip — the north-star model itself, which bf16 cannot
even load on one v5e. The value is **strict MFU**: useful FLOPs only,
where frozen matmuls credit 2× forward (their dW is never computed)
and attention credits 3× (its backward is required to reach the
adapters) — see Trainer.benchmark. The laxer 6ND/3× figure most
published "LoRA MFU" numbers use is reported alongside as
``mfu_train_equiv_3x``. Falls back to the 1B headline (metric name
``llama1b_lora_train_mfu``) if the 8B path fails, or when
BENCH_HEADLINE=1b.

Also measured, budget-permitting (VERDICT r1 asked for the hard
regimes to be captured numbers, not commit messages):
- Llama-3.2-1B LoRA at seq 1024 — round-1/2 continuity numbers;
- long context: 1B at seq 16384, where attention dominates and the
  pallas flash kernel (ops/pallas_attention.py, causal block skip) is
  the difference between running and OOM;
- dense-vs-flash attention op at seq 4096;
- KV-cache decode smoke.

MFU accounting counts causally-required attention FLOPs only
(models/llama.py flops_per_token), so block-skipping cannot inflate it.
Set BENCH_FAST=1 to skip everything but the headline (CI smoke).
"""

from __future__ import annotations

import json
import os
import sys
import time


def _attention_op_compare(jax, jnp, seq: int = 4096):
    """Dense vs flash attention step time at the 1B model's head shape.

    The op runs inside a ``lax.scan`` (8 iterations per dispatch) so the
    relay backend's per-call dispatch latency — tens of ms, comparable
    to the op itself — amortizes out; a bare timing loop here measures
    the tunnel, not the kernel."""
    from jax import lax

    from odh_kubeflow_tpu.ops.attention import dense_attention
    from odh_kubeflow_tpu.ops.pallas_attention import flash_attention

    key = jax.random.PRNGKey(0)
    B, Hq, Hkv, hd = 1, 32, 8, 64
    q = jax.random.normal(key, (B, seq, Hq, hd), jnp.bfloat16)
    k = jax.random.normal(key, (B, seq, Hkv, hd), jnp.bfloat16)
    v = jax.random.normal(key, (B, seq, Hkv, hd), jnp.bfloat16)
    N = 8
    out = {}
    for name, fn in (
        ("dense", lambda q, k, v: dense_attention(q, k, v, causal=True)),
        ("flash", lambda q, k, v: flash_attention(q, k, v, causal=True)),
    ):
        def scanned(q, k, v, fn=fn):
            def body(c, _):
                o = fn(c, k, v)
                return o * 1e-3 + c * 0.999, None
            return lax.scan(body, q, None, length=N)[0]

        jf = jax.jit(scanned)
        float(jf(q, k, v).sum())  # compile + warm (host transfer = sync)
        best = None
        for _ in range(2):
            t0 = time.time()
            float(jf(q, k, v).sum())
            dt = (time.time() - t0) / N
            best = dt if best is None else min(best, dt)
        out[name] = round(best * 1e3, 2)
    return out


def _generate_smoke(jax, jnp, trainer):
    """KV-cache decode on the real chip (models/generate.py): prefill a
    prompt, decode 32 tokens, report decode tokens/s — the notebook
    fine-tune→try-it loop's serving half."""
    from odh_kubeflow_tpu.models.generate import GenerateConfig, generate

    gen_cfg = GenerateConfig(max_new_tokens=32, temperature=0.0)
    B, S = 4, 128
    prompt = jnp.ones((B, S), jnp.int32)
    run = jax.jit(
        lambda params, prompt: generate(params, prompt, trainer.model_cfg, gen_cfg)
    )
    t0 = time.time()
    out = run(trainer.params, prompt)
    int(out["lengths"][0])  # host transfer = sync (compile incl.)
    compile_s = time.time() - t0
    t0 = time.time()
    out = run(trainer.params, prompt)
    int(out["lengths"][0])
    steady_s = time.time() - t0
    return {
        "batch": B,
        "prompt_len": S,
        "new_tokens": gen_cfg.max_new_tokens,
        "compile_s": round(compile_s, 2),
        "decode_tokens_per_s": round(B * gen_cfg.max_new_tokens / steady_s, 1),
    }


def main() -> None:
    os.environ.setdefault("JAX_TRACEBACK_FILTERING", "off")
    import jax
    import jax.numpy as jnp

    from odh_kubeflow_tpu.models import LlamaConfig, LoraConfig
    from odh_kubeflow_tpu.models.llama import resolved_attention_impl
    from odh_kubeflow_tpu.parallel.mesh import MeshConfig, build_mesh
    from odh_kubeflow_tpu.train import TrainConfig, Trainer
    from odh_kubeflow_tpu.utils.tpu import peak_flops_per_chip

    devices = jax.devices()
    n = len(devices)
    peak = peak_flops_per_chip(devices[0]) * n
    fast = os.environ.get("BENCH_FAST", "").lower() in ("1", "true")
    # soft wall-clock budget: the headline number must always make it
    # out even if cold compiles eat the driver's timeout — extras are
    # skipped once the budget is spent
    t_start = time.time()
    budget_s = float(os.environ.get("BENCH_BUDGET", "540"))

    def over_budget() -> bool:
        return time.time() - t_start > budget_s

    batch_size = int(os.environ.get("BENCH_BATCH", "8"))
    seq_len = int(os.environ.get("BENCH_SEQ", "1024"))
    steps = int(os.environ.get("BENCH_STEPS", "10"))
    # batch must tile the data-parallel extent (= all devices here)
    batch_size = -(-max(batch_size, n) // n) * n

    cfg = LlamaConfig.llama3_1b(dtype=jnp.bfloat16)
    impl = resolved_attention_impl(cfg)
    mesh = build_mesh(MeshConfig(fsdp=n), devices)
    detail = {
        "devices": n,
        "device_kind": getattr(devices[0], "device_kind", "cpu"),
        "attention_impl": impl,
    }

    # -- headline: 8B QLoRA (north-star model), single chip or mesh ----
    headline = None  # (metric, value, vs_baseline)
    is_tpu = peak > 0
    want_8b = is_tpu and os.environ.get("BENCH_HEADLINE", "8b") != "1b"
    if want_8b:
        try:
            cfg8 = LlamaConfig.llama3_8b(dtype=jnp.bfloat16, remat_policy="attn")
            t8 = Trainer(
                cfg8,
                TrainConfig(warmup_steps=2, total_steps=100),
                lora_cfg=LoraConfig(rank=16),
                mesh=mesh,
                quantize_base=True,
            )
            s8 = t8.benchmark(
                max(2, n) if n > 1 else 2, 4096, steps=3, warmup=1
            )
            mfu8 = s8["flops_per_s"] / peak
            detail["headline_8b_qlora"] = {
                "batch": max(2, n) if n > 1 else 2,
                "seq": 4096,
                "lora_rank": 16,
                "int8_base": True,
                "step_time_s": round(s8["step_time_s"], 4),
                "tokens_per_s": round(s8["tokens_per_s"], 1),
                "mfu_strict": round(mfu8, 4),
                "mfu_train_equiv_3x": round(
                    s8["train_equiv_flops_per_s"] / peak, 4
                ),
                "loss": round(s8["loss"], 4),
            }
            headline = ("llama8b_qlora_train_mfu", mfu8, mfu8 / 0.50)
            del t8
        except Exception as e:  # noqa: BLE001 — fall back to the 1B headline
            detail["headline_8b_qlora"] = {"error": str(e)[:200]}

    # -- 1B LoRA (round-1/2 continuity regime) -------------------------
    trainer = Trainer(
        cfg,
        TrainConfig(warmup_steps=2, total_steps=100),
        lora_cfg=LoraConfig(rank=16),
        mesh=mesh,
    )
    stats = trainer.benchmark(batch_size, seq_len, steps=steps, warmup=2)

    detail.update(
        {
            "batch": batch_size,
            "seq": seq_len,
            "step_time_s": round(stats["step_time_s"], 4),
            "tokens_per_s": round(stats["tokens_per_s"], 1),
            "loss": round(stats["loss"], 4),
        }
    )
    if peak > 0:
        detail["llama1b_mfu_strict"] = round(stats["flops_per_s"] / peak, 4)
        detail["llama1b_mfu_train_equiv_3x"] = round(
            stats["train_equiv_flops_per_s"] / peak, 4
        )

    if not fast and not over_budget():
        # the hard regime: 16k context, attention-dominant. Needs all
        # three long-context levers at once: the pallas flash kernel
        # (dense logits at 16k OOM), chunked cross-entropy (full
        # [S,V] logits are 8.4GB), and aggressive remat. Primary row:
        # the north-star 8B model itself, QLoRA at 16k on one chip
        # (full remat — the flash-residual "attn" policy's ~4GB of
        # saved residuals doesn't fit next to the int8 base at this
        # length). Secondary row: the 1B continuity config from
        # rounds 1-2, now under the "attn" policy (backward never
        # re-runs the flash forward).
        import dataclasses as _dc

        long_seq = int(os.environ.get("BENCH_LONG_SEQ", "16384"))
        del trainer  # free the headline trainer's param copy first

        def _long_row(trainer_, batch_):
            st = trainer_.benchmark(batch_, long_seq, steps=3, warmup=1)
            row = {
                "seq": long_seq,
                "batch": batch_,
                "attention_impl": impl,
                "step_time_s": round(st["step_time_s"], 4),
                "tokens_per_s": round(st["tokens_per_s"], 1),
            }
            if peak > 0:
                row["mfu_strict"] = round(st["flops_per_s"] / peak, 4)
                row["mfu_train_equiv_3x"] = round(
                    st["train_equiv_flops_per_s"] / peak, 4
                )
            return row

        if want_8b:
            t8l = None
            try:
                t8l = Trainer(
                    LlamaConfig.llama3_8b(
                        dtype=jnp.bfloat16, remat_policy="none"
                    ),
                    TrainConfig(warmup_steps=2, total_steps=100),
                    lora_cfg=LoraConfig(rank=16),
                    mesh=mesh,
                    quantize_base=True,
                )
                detail["long_context"] = {
                    "model": "llama3-8b-qlora-int8", **_long_row(t8l, max(1, n))
                }
            except Exception as e:  # noqa: BLE001 — keep the headline alive
                detail["long_context"] = {"error": str(e)[:200]}
            finally:
                # free the ~8GB int8 base even when benchmark() raised,
                # or every remaining row inherits the OOM; the explicit
                # gc + cache clear matters since r4's "attn_mlp" 1B row
                # pins ~6.5GB of residuals — it only fits if the 8B
                # row's arena actually drained first
                del t8l
                import gc

                gc.collect()
                jax.clear_caches()

        long_trainer = None
        if not over_budget():
            try:
                long_trainer = Trainer(
                    _dc.replace(cfg, remat_policy="attn_mlp"),
                    TrainConfig(warmup_steps=2, total_steps=100),
                    lora_cfg=LoraConfig(rank=16),
                    mesh=mesh,
                )
                row1b = {
                    "model": "llama3.2-1b-lora",
                    **_long_row(long_trainer, max(1, n)),
                }
                detail["long_context_1b"] = row1b
                if "long_context" not in detail or (
                    "error" in detail["long_context"]
                ):
                    # keep the 8B failure visible before falling back
                    if "error" in detail.get("long_context", {}):
                        detail["long_context_8b_error"] = detail[
                            "long_context"
                        ]["error"]
                    detail["long_context"] = row1b
            except Exception as e:  # noqa: BLE001 — keep the headline alive
                detail.setdefault("long_context", {"error": str(e)[:200]})
                detail["long_context_1b"] = {"error": str(e)[:200]}
        skipped = []
        if over_budget():
            skipped.append("attention_op_ms")
        else:
            try:
                detail["attention_op_ms"] = _attention_op_compare(jax, jnp)
            except Exception as e:  # noqa: BLE001 — best-effort
                detail["attention_op_ms"] = {"error": str(e)[:200]}
        if over_budget() or long_trainer is None:
            skipped.append("generate")
        else:
            try:
                detail["generate"] = _generate_smoke(jax, jnp, long_trainer)
            except Exception as e:  # noqa: BLE001 — best-effort
                detail["generate"] = {"error": str(e)[:200]}
        if skipped:
            detail["skipped_for_budget"] = skipped
    elif not fast:
        detail["skipped_for_budget"] = ["long_context", "attention_op_ms", "generate"]

    # BENCH_FULL=1: the Mixtral-class MoE row (8×1B QLoRA, grouped
    # dropless dispatch). Too heavy for the default driver budget
    # (streaming int8 init + fresh compile ≈ 3–4 min), so it is
    # opt-in; loadtest/moe_qlora_8x1b is the standalone command and
    # BASELINE.md pins the measured numbers (incl. the ragged
    # cf=1.25 / cf=1.0 dual accounting).
    if os.environ.get("BENCH_FULL", "") == "1" and peak > 0:
        try:
            import gc

            from odh_kubeflow_tpu.models.moe import MoeConfig

            # the 6.7GB int8 MoE base + pins need a drained arena
            try:
                del long_trainer
            except NameError:
                pass
            try:
                del trainer
            except NameError:
                pass
            gc.collect()
            jax.clear_caches()

            moe_cfg = MoeConfig.mixtral_8x1b(
                base=LlamaConfig.llama3_1b(
                    dtype=jnp.bfloat16, remat_policy="attn"
                ),
                dispatch="grouped",
                pin_expert_acts=True,
            )
            tm = Trainer(
                moe_cfg,
                TrainConfig(warmup_steps=2, total_steps=100),
                lora_cfg=LoraConfig(rank=16),
                mesh=mesh,
                quantize_base=True,
            )
            sm = tm.benchmark(2, 4096, steps=3, warmup=1)
            detail["moe_8x1b_qlora"] = {
                "dispatch": "grouped-dropless",
                "batch": 2,
                "seq": 4096,
                "step_time_s": round(sm["step_time_s"], 4),
                "tokens_per_s": round(sm["tokens_per_s"], 1),
                "mfu_strict_sparse": round(sm["flops_per_s"] / peak, 4),
                "mfu_train_equiv_3x": round(
                    sm["train_equiv_flops_per_s"] / peak, 4
                ),
            }
            del tm
        except Exception as e:  # noqa: BLE001
            detail["moe_8x1b_qlora"] = {"error": str(e)[:200]}

    if headline is not None:
        metric, value, vs_baseline = headline
        unit = "mfu"
    elif peak > 0:
        # 1B fallback: strict MFU, same convention as the headline
        value = stats["flops_per_s"] / peak
        metric, unit = "llama1b_lora_train_mfu", "mfu"
        vs_baseline = value / 0.50  # north-star: 50% MFU
    else:
        value = stats["tokens_per_s"]
        metric, unit = "llama1b_lora_train_tokens_per_s", "tokens/s"
        vs_baseline = 0.0

    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(value, 4),
                "unit": unit,
                "vs_baseline": round(vs_baseline, 4),
                "detail": detail,
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
