import time, json
import jax, jax.numpy as jnp
from functools import partial
from odh_kubeflow_tpu.models import LlamaConfig, LoraConfig
from odh_kubeflow_tpu.models import llama
from odh_kubeflow_tpu.parallel.mesh import MeshConfig, build_mesh
from jax.sharding import NamedSharding, PartitionSpec as P
devices = jax.devices()
cfg = LlamaConfig.llama3_1b(dtype=jnp.bfloat16)
mesh = build_mesh(MeshConfig(fsdp=len(devices)), devices)
sh = lambda specs: jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), specs, is_leaf=lambda s: isinstance(s, P))
out = {}
with jax.set_mesh(mesh):
    p_specs = llama.param_specs(cfg)
    init_fn = jax.jit(partial(llama.init_params, cfg=cfg, dtype=cfg.dtype), out_shardings=sh(p_specs))
    t0 = time.monotonic(); lowered = init_fn.lower(jax.random.key(0)); out["lower_s"] = round(time.monotonic()-t0, 2)
    t0 = time.monotonic(); compiled = lowered.compile(); out["compile_s"] = round(time.monotonic()-t0, 2)
    t0 = time.monotonic(); params = compiled(jax.random.key(0)); float(params["final_norm"][0]); out["exec_s"] = round(time.monotonic()-t0, 2)
    # zeros-init comparison: how much of compile is the RNG graph?
    def zinit(k):
        shapes = jax.eval_shape(partial(llama.init_params, cfg=cfg, dtype=cfg.dtype), k)
        return jax.tree_util.tree_map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)
    zfn = jax.jit(zinit, out_shardings=sh(p_specs))
    t0 = time.monotonic(); zc = zfn.lower(jax.random.key(0)).compile(); out["zeros_compile_s"] = round(time.monotonic()-t0, 2)
    t0 = time.monotonic(); zp = zc(jax.random.key(0)); float(zp["final_norm"][0]); out["zeros_exec_s"] = round(time.monotonic()-t0, 2)
print(json.dumps(out))
