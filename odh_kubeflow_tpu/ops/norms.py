"""Normalisation ops.

Computed in float32 regardless of activation dtype — RMS statistics in
bfloat16 lose enough precision to visibly hurt long-sequence training,
and XLA fuses the upcast into the surrounding elementwise graph anyway
(no extra HBM traffic).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    normed = x32 * jax.lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(dtype)
