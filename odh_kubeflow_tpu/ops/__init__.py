from odh_kubeflow_tpu.ops.attention import dense_attention  # noqa: F401
from odh_kubeflow_tpu.ops.norms import rms_norm  # noqa: F401
from odh_kubeflow_tpu.ops.rope import apply_rope, rope_angles  # noqa: F401
