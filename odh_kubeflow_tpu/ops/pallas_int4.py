"""Pallas int4→bf16 weight dequantization.

XLA lowers the int4 unpack chain (bit-ops + concat/reshape + group
scaling) into passes that cost ~5× the HBM roofline on the 8B/16k
config (+0.4s/step). This kernel is a pure streaming transform: read a
packed uint8 block, unpack the requested nibble half, apply the
group-wise scales in VMEM, write the bf16 block — one pass at memory
speed. The grid's leading dimension selects the nibble half, matching
``models/quant.py``'s split-halves packing (low nibbles = rows
[0, K/2), high = [K/2, K)), so each output block is contiguous.

Used by ``quant.dequantize_tensor4`` on TPU for shapes the blocking
divides; everything else (CPU tests, tiny shapes) takes the jnp path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BK = 1024  # output rows per block (scale block = 8 sublanes)
DEFAULT_BN = 512


def _dequant_kernel(packed_ref, scale_ref, out_ref, *, group, bk):
    h = pl.program_id(0)
    # i32 lanes: Mosaic has no u8 vector shift (arith.shrui fails to
    # legalize); the widen/narrow is VPU-local
    p = packed_ref[...].astype(jnp.int32)
    nib = jnp.where(h == 0, p & 0xF, (p >> 4) & 0xF)
    v = (nib - 8).astype(jnp.float32)
    rows = bk // group
    vg = v.reshape(rows, group, v.shape[-1])
    vg = vg * scale_ref[...][:, None, :]
    out_ref[...] = vg.reshape(bk, v.shape[-1]).astype(out_ref.dtype)


def int4_dequant(packed, scale, dtype=jnp.bfloat16, *, group=128,
                 bk=DEFAULT_BK, bn=DEFAULT_BN):
    """``packed`` [K//2, N] uint8 (split-halves), ``scale`` [K//group,
    N] f32 → [K, N] ``dtype``. 2-D only — callers vmap leading dims."""
    K2, N = packed.shape
    K = 2 * K2
    bk = min(bk, K2)
    bn = min(bn, N)
    if (
        K2 % bk
        or N % bn
        or bk % group
        or scale.shape != (K // group, N)
    ):
        raise ValueError(f"int4_dequant blocking mismatch: {packed.shape}")
    srows = bk // group
    return pl.pallas_call(
        functools.partial(_dequant_kernel, group=group, bk=bk),
        grid=(2, K2 // bk, N // bn),
        in_specs=[
            pl.BlockSpec((bk, bn), lambda h, i, j: (i, j)),
            pl.BlockSpec(
                (srows, bn),
                lambda h, i, j: (h * (K2 // bk) + i, j),
            ),
        ],
        out_specs=pl.BlockSpec(
            (bk, bn), lambda h, i, j: (h * (K2 // bk) + i, j)
        ),
        out_shape=jax.ShapeDtypeStruct((K, N), dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary"),
        ),
    )(packed, scale)


# ---------------------------------------------------------------------------
# fused-consumer matmul: weights STAY packed int4 in HBM
# ---------------------------------------------------------------------------
#
# x[M, K] · W[K, N] where W lives as {q4 [K/2, N] uint8 (split-halves),
# scale4 [K/group, N] f32}. The r4 finding (BASELINE.md wall list):
# int4-with-in-graph-dequant frees 4GB of HBM but materialising the
# bf16 weight per consumer eats the win. Here the unpack + group scale
# happen on the accumulator in VMEM — weights cross HBM packed (0.5
# byte/weight, 2× less traffic than int8, 4× less than bf16) and no
# dequantized copy ever exists. The per-K-group scales are exactly why
# XLA cannot fuse this itself: they multiply neither operand of a
# single dot (folding them needs a [M, K/group, N] intermediate), but
# they CAN rescale each group's partial product on the f32 accumulator
# — one VPU multiply per (group, tile) step.
#
# Frozen-base training only (QLoRA): differentiable in x (the dlhs
# kernel reads the same packed bank "backwards"), never in the weights.

MM_BM = 512
MM_BN = 512
MM_BK = 1024  # K-chunk per grid step: 8 scale groups (one aligned
# sublane block), one MXU-wide dot



def _unpack_scaled(p_ref, s_ref, lo_half, q, dtype):
    """Shared nibble-select + group-scale dequant for the matmul
    kernels: unpack the requested half's nibbles, apply the q group
    scales row-blockwise, return the bf16 weight block — ONE copy, so
    the fwd and dlhs kernels can never desynchronize their rounding."""
    p = p_ref[...].astype(jnp.int32)
    nib = jnp.where(lo_half, p & 0xF, (p >> 4) & 0xF)
    kb, bn = nib.shape
    sc = s_ref[...]
    return (
        (nib - 8).astype(jnp.float32).reshape(q, kb // q, bn)
        * sc[:, None, :]
    ).reshape(kb, bn).astype(dtype)


def _int4_mm_kernel(x_ref, p_ref, s_ref, out_ref, acc_ref, *, nc, q):
    c = pl.program_id(2)  # k-chunk, innermost
    c2 = nc // 2
    # scale the unpacked weights IN VMEM (bf16, same rounding as the
    # dequantize path) — one wide dot per chunk keeps the MXU fed; the
    # first cut dotted per 128-group and ran at 49 TF/s vs 167 for the
    # dequant path
    w = _unpack_scaled(p_ref, s_ref, c < c2, q, x_ref.dtype)
    d = jax.lax.dot_general(
        x_ref[...], w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(c == 0)
    def _init():
        acc_ref[...] = d

    @pl.when(c > 0)
    def _accum():
        acc_ref[...] = acc_ref[...] + d

    @pl.when(c == nc - 1)
    def _write():
        out_ref[...] = acc_ref[...].astype(out_ref.dtype)


def _int4_mm_impl(x, q4, scale4, *, group, interpret):
    M, K = x.shape
    K2, N = q4.shape
    ng = K // group
    bm = min(MM_BM, M)
    bn = min(MM_BN, N)
    kb = MM_BK
    q = kb // group  # 8 groups: the scale block is one aligned
    # sublane tile — Mosaic cannot prove smaller dynamic slices aligned
    if (
        K != 2 * K2
        or K % (2 * kb)
        or kb % group
        or group > kb
        or scale4.shape != (ng, N)
        or M % bm
        or N % bn
    ):
        raise NotImplementedError(
            f"int4_matmul blocking mismatch: x{x.shape} q4{q4.shape}"
        )
    nc = K // kb
    c2 = nc // 2

    # chunk c < c2 reads packed rows [c*kb, ...) as LOW nibbles;
    # c >= c2 reads rows [(c-c2)*kb, ...) as HIGH nibbles — the
    # split-halves layout of quantize_tensor4
    def p_idx(ni, mi, c):
        return (jnp.where(c < c2, c, c - c2), ni)

    return pl.pallas_call(
        functools.partial(_int4_mm_kernel, nc=nc, q=q),
        grid=(N // bn, M // bm, nc),
        in_specs=[
            pl.BlockSpec((bm, kb), lambda ni, mi, c: (mi, c)),
            pl.BlockSpec((kb, bn), p_idx),
            pl.BlockSpec((q, bn), lambda ni, mi, c: (c, ni)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda ni, mi, c: (mi, ni)),
        out_shape=jax.ShapeDtypeStruct((M, N), x.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(x, q4, scale4)


def _int4_dlhs_kernel(d_ref, p_ref, s_ref, out_ref, acc_ref, *, nn, nc, q):
    ni = pl.program_id(2)  # n-split, innermost
    c = pl.program_id(0)
    c2 = nc // 2
    w = _unpack_scaled(p_ref, s_ref, c < c2, q, d_ref.dtype)
    # dx_c = dout · w_cᵀ (w already carries the group scales)
    acc = jax.lax.dot_general(
        d_ref[...], w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(ni == 0)
    def _init():
        acc_ref[...] = acc

    @pl.when(ni > 0)
    def _accum():
        acc_ref[...] = acc_ref[...] + acc

    @pl.when(ni == nn - 1)
    def _write():
        out_ref[...] = acc_ref[...].astype(out_ref.dtype)


def _int4_dlhs_impl(dout, q4, scale4, *, group, interpret):
    M, N = dout.shape
    K2, N2 = q4.shape
    K = 2 * K2
    ng = K // group
    bm = min(MM_BM, M)
    bn = min(MM_BN, N)
    kb = MM_BK
    q = kb // group
    if (
        N != N2
        or K % (2 * kb)
        or kb % group
        or group > kb
        or M % bm
        or N % bn
        or scale4.shape != (ng, N)
    ):
        raise NotImplementedError(
            f"int4_matmul dlhs blocking mismatch: dout{dout.shape}"
        )
    nc = K // kb
    c2 = nc // 2

    def p_idx(c, mi, ni):
        return (jnp.where(c < c2, c, c - c2), ni)

    return pl.pallas_call(
        functools.partial(
            _int4_dlhs_kernel, nn=N // bn, nc=nc, q=q
        ),
        grid=(nc, M // bm, N // bn),
        in_specs=[
            pl.BlockSpec((bm, bn), lambda c, mi, ni: (mi, ni)),
            pl.BlockSpec((kb, bn), p_idx),
            pl.BlockSpec((q, bn), lambda c, mi, ni: (c, ni)),
        ],
        out_specs=pl.BlockSpec(
            (bm, kb), lambda c, mi, ni: (mi, c)
        ),
        out_shape=jax.ShapeDtypeStruct((M, K), dout.dtype),
        scratch_shapes=[pltpu.VMEM((bm, kb), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(dout, q4, scale4)
def _interpret_default():
    return jax.default_backend() != "tpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def int4_matmul(x, q4, scale4, group=128, interpret=None):
    """``x [M, K] @ dequant(q4, scale4) [K, N]`` with the weights
    staying packed: unpack + group-scale happen on the accumulator in
    VMEM. Differentiable in ``x`` only (frozen banks — QLoRA).
    Raises ``NotImplementedError`` on shapes the blocking doesn't
    divide; callers fall back to the dequantize path."""
    if interpret is None:
        interpret = _interpret_default()
    return _int4_mm_impl(x, q4, scale4, group=group, interpret=interpret)


def _int4_matmul_fwd(x, q4, scale4, group, interpret):
    if interpret is None:
        interpret = _interpret_default()
    out = _int4_mm_impl(x, q4, scale4, group=group, interpret=interpret)
    return out, (q4, scale4)


def _int4_matmul_bwd(group, interpret, res, dout):
    q4, scale4 = res
    if interpret is None:
        interpret = _interpret_default()
    dx = _int4_dlhs_impl(
        dout, q4, scale4, group=group, interpret=interpret
    )
    return dx, None, jnp.zeros_like(scale4)


int4_matmul.defvjp(_int4_matmul_fwd, _int4_matmul_bwd)
