"""Pallas int4→bf16 weight dequantization.

XLA lowers the int4 unpack chain (bit-ops + concat/reshape + group
scaling) into passes that cost ~5× the HBM roofline on the 8B/16k
config (+0.4s/step). This kernel is a pure streaming transform: read a
packed uint8 block, unpack the requested nibble half, apply the
group-wise scales in VMEM, write the bf16 block — one pass at memory
speed. The grid's leading dimension selects the nibble half, matching
``models/quant.py``'s split-halves packing (low nibbles = rows
[0, K/2), high = [K/2, K)), so each output block is contiguous.

Used by ``quant.dequantize_tensor4`` on TPU for shapes the blocking
divides; everything else (CPU tests, tiny shapes) takes the jnp path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BK = 1024  # output rows per block (scale block = 8 sublanes)
DEFAULT_BN = 512


def _dequant_kernel(packed_ref, scale_ref, out_ref, *, group, bk):
    h = pl.program_id(0)
    # i32 lanes: Mosaic has no u8 vector shift (arith.shrui fails to
    # legalize); the widen/narrow is VPU-local
    p = packed_ref[...].astype(jnp.int32)
    nib = jnp.where(h == 0, p & 0xF, (p >> 4) & 0xF)
    v = (nib - 8).astype(jnp.float32)
    rows = bk // group
    vg = v.reshape(rows, group, v.shape[-1])
    vg = vg * scale_ref[...][:, None, :]
    out_ref[...] = vg.reshape(bk, v.shape[-1]).astype(out_ref.dtype)


def int4_dequant(packed, scale, dtype=jnp.bfloat16, *, group=128,
                 bk=DEFAULT_BK, bn=DEFAULT_BN):
    """``packed`` [K//2, N] uint8 (split-halves), ``scale`` [K//group,
    N] f32 → [K, N] ``dtype``. 2-D only — callers vmap leading dims."""
    K2, N = packed.shape
    K = 2 * K2
    bk = min(bk, K2)
    bn = min(bn, N)
    if (
        K2 % bk
        or N % bn
        or bk % group
        or scale.shape != (K // group, N)
    ):
        raise ValueError(f"int4_dequant blocking mismatch: {packed.shape}")
    srows = bk // group
    return pl.pallas_call(
        functools.partial(_dequant_kernel, group=group, bk=bk),
        grid=(2, K2 // bk, N // bn),
        in_specs=[
            pl.BlockSpec((bk, bn), lambda h, i, j: (i, j)),
            pl.BlockSpec(
                (srows, bn),
                lambda h, i, j: (h * (K2 // bk) + i, j),
            ),
        ],
        out_specs=pl.BlockSpec(
            (bk, bn), lambda h, i, j: (h * (K2 // bk) + i, j)
        ),
        out_shape=jax.ShapeDtypeStruct((K, N), dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary"),
        ),
    )(packed, scale)
