"""Attention implementations.

``dense_attention`` is the XLA-fused baseline: one einsum → softmax →
einsum chain that XLA maps straight onto the MXU. GQA is handled by
reshaping queries to [B, S, Hkv, group, hd] rather than materialising
repeated KV heads (saves Hq/Hkv × KV HBM traffic).

Higher-performance paths plug in behind the same signature:
- pallas flash attention (``ops.pallas.flash_attention``) — tiled,
  never materialises the [S, S] score matrix;
- ring attention (``parallel.ring_attention``) — context-parallel over a
  mesh axis via ``ppermute``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def dense_attention(
    q: jnp.ndarray,  # [B, Sq, Hq, hd]
    k: jnp.ndarray,  # [B, Sk, Hkv, hd]
    v: jnp.ndarray,  # [B, Sk, Hkv, hd]
    *,
    causal: bool = True,
    q_offset: int | jnp.ndarray = 0,
    segment_ids: Optional[jnp.ndarray] = None,  # [B, S] same-id attends
    kv_mask: Optional[jnp.ndarray] = None,  # [B, Sk] bool, True = attend
) -> jnp.ndarray:
    """Returns [B, Sq, Hq, hd]. Scores accumulate in float32.

    ``q_offset`` is the absolute position of q[0] relative to k[0]
    (used by the KV-cache decode path and by ring attention blocks).
    ``kv_mask`` marks which cache slots hold real tokens (the KV-cache
    decode path with ragged right-padded prompts leaves invalid slots
    between each prompt's end and the shared write index).
    """
    B, Sq, Hq, hd = q.shape
    _, Sk, Hkv, _ = k.shape
    assert Hq % Hkv == 0, (Hq, Hkv)
    group = Hq // Hkv

    scale = hd**-0.5
    qg = q.reshape(B, Sq, Hkv, group, hd)
    # [B, Hkv, group, Sq, Sk]
    scores = jnp.einsum(
        "bqhgd,bkhd->bhgqk", qg, k, preferred_element_type=jnp.float32
    )
    scores = scores * scale

    mask = None
    if causal:
        if getattr(q_offset, "ndim", 0) == 1:
            # per-row offsets ([B] vector — the continuous-batching
            # engine's slots each sit at their own position)
            q_pos = q_offset[:, None, None] + jnp.arange(Sq)[None, :, None]
            mask = (q_pos >= jnp.arange(Sk)[None, None, :])[
                :, None, None, :, :
            ]  # [B, 1, 1, Sq, Sk]
        else:
            q_pos = jnp.arange(Sq)[:, None] + q_offset
            k_pos = jnp.arange(Sk)[None, :]
            mask = q_pos >= k_pos  # [Sq, Sk]
            mask = mask[None, None, None, :, :]
    if segment_ids is not None:
        # [B, Sq, Sk] → [B, 1, 1, Sq, Sk]
        seg = (
            segment_ids[:, :, None] == segment_ids[:, None, :]
        )[:, None, None, :, :]
        mask = seg if mask is None else jnp.logical_and(mask, seg)
    if kv_mask is not None:
        kvm = kv_mask[:, None, None, None, :]  # [B, 1, 1, 1, Sk]
        mask = kvm if mask is None else jnp.logical_and(mask, kvm)
    if mask is not None:
        scores = jnp.where(mask, scores, jnp.float32(-1e30))

    weights = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", weights, v)
    return out.reshape(B, Sq, Hq, hd)
