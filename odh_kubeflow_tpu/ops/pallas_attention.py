"""Pallas TPU flash attention (forward + backward).

Tiled attention that never materialises the [Sq, Sk] score matrix:
the kernel streams K/V blocks through VMEM and keeps an online-softmax
accumulator (running max ``m``, denominator ``l``, weighted sum ``acc``)
per Q block, so HBM traffic is O(S·d) instead of O(S²). The backward
pass recomputes scores from the saved logsumexp (flash-v2 style) in two
kernels: one accumulating dQ over K blocks, one accumulating dK/dV over
Q blocks.

Drop-in for ``ops.attention.dense_attention`` (same signature; the
reference platform has no attention code at all — SURVEY.md §2.4 — this
is a new TPU-native component). GQA is handled by mapping each Q head's
grid cell onto its KV head (``h // group``) in the K/V index maps, so
KV blocks are fetched once per group from HBM's point of view (Mosaic
caches the revisited block).

Causal masking skips fully-masked K blocks via predication
(``pl.when``), and the MXU sees [block_q, block_k] @ [block_k, hd]
tiles — 128-aligned by construction (inputs are padded).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name as _checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# swept on v5e (fwd, S∈{1k,4k}): 1024×1024 beats 512×1024 by ~10%;
# both clamp to the sequence length for shorter inputs
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024
_NEG_INF = -1e30
# The softmax runs in base-2 end to end: log2(e) folds into the q
# prescale (one [bq, hd] multiply), so the VPU evaluates raw exp2 on
# the [bq, bk] score blocks instead of exp = exp2(x·log2e) — one fewer
# full-block multiply per exponential, and the kernel is VPU-bound at
# small head_dim. The saved logsumexp residual is likewise base-2
# (lse2 = log2e·lse); it never leaves this file.
_LOG2E = 1.4426950408889634


def _ceil_to(x: int, m: int) -> int:
    return -(-x // m) * m


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _pair_tables(*, num_q, num_k, causal, q_offset, sk, block_q, block_k,
                 order, group=1):
    """Static live-(Q-block, K-block) pair tables for the triangular
    grids (scalar-prefetched, like ``pallas_grouped_matmul.span_pairs``
    but fully host-side: liveness depends only on static geometry).

    The old grids ran the full num_q×num_k rectangle and predicated
    dead blocks off — at 16k/1024 causal that is ~half the programs
    dispatched for nothing. Here the grid's last axis walks live pairs
    only.

    order="row": pairs sorted by (qi, ki) — fwd/dq walk, accumulator
    keyed on the Q block. order="col": sorted by (ki, g, qj) with the
    GQA group folded in — the dkv walk, accumulator keyed on the K
    block. An owner with no live partner gets one synthetic masked
    pair so its output block is still initialised and finalised
    (l = 0 ⇒ zero output — the dense semantics fully-masked ring
    shards rely on).

    Returns int32 arrays of length L: ``qi``, ``ki``, ``g`` (0 unless
    order="col"), ``first``/``last`` (accumulator init/flush flags).
    """
    import numpy as np

    def live(qb, kb):
        if kb * block_k >= sk:
            return False
        if not causal:
            return True
        return kb * block_k <= qb * block_q + (block_q - 1) + q_offset

    qi_l, ki_l, g_l, first_l, last_l = [], [], [], [], []

    def emit(items):
        for j, (qi, kb, g) in enumerate(items):
            qi_l.append(qi)
            ki_l.append(kb)
            g_l.append(g)
            first_l.append(int(j == 0))
            last_l.append(int(j == len(items) - 1))

    if order == "row":
        for qi in range(num_q):
            kbs = [kb for kb in range(num_k) if live(qi, kb)]
            emit([(qi, kb, 0) for kb in (kbs or [0])])
    else:
        for kb in range(num_k):
            qjs = [qj for qj in range(num_q) if live(qj, kb)]
            items = [(qj, kb, g) for g in range(group) for qj in qjs]
            emit(items or [(0, kb, 0)])
    return tuple(
        jnp.asarray(np.asarray(a, np.int32))
        for a in (qi_l, ki_l, g_l, first_l, last_l)
    )




def _block_full(qb, ki, *, causal, q_offset, sk, block_q, block_k):
    """True iff EVERY (row, col) pair of the block is live — interior
    causal blocks with no padded K columns, the hot case at long
    context (S=16k, block 1024: 120 of 136 live blocks are full). Full
    blocks skip the iota/compare/select mask arithmetic, which is what
    the VPU otherwise burns time on between the MXU dots. (Liveness
    itself is static now — _pair_tables enumerates live pairs — so
    there is no 'run' predicate anymore.)"""
    full = (ki + 1) * block_k <= sk
    if causal:
        full = jnp.logical_and(
            full, qb * block_q + q_offset >= ki * block_k + (block_k - 1)
        )
    return full


def _dispatch_body(full, has_segments, body):
    """Full/edge split shared by the three kernels: segmented kernels
    always take the masked path (segment walls can cut any block);
    otherwise interior blocks run the mask-free fast path."""
    if has_segments:
        body(masked=True)
    else:

        @pl.when(full)
        def _full():
            body(masked=False)

        @pl.when(jnp.logical_not(full))
        def _edge():
            body(masked=True)


def _block_mask(qb, ki, qseg_ref, kseg_ref, *, causal, q_offset, sk,
                block_q, block_k):
    """[block_q, block_k] live-pair mask for an edge block."""
    q_pos = qb * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    mask = k_pos < sk  # padded K columns never contribute
    if causal:
        mask = jnp.logical_and(mask, q_pos + q_offset >= k_pos)
    if qseg_ref is not None:
        mask = jnp.logical_and(mask, qseg_ref[0] == kseg_ref[0])
    return mask


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(
    qi_ref,  # [L] scalar-prefetch: Q-block of pair i
    ki_ref,  # [L] K-block of pair i
    g_ref,  # [L] unused here (order="row")
    first_ref,  # [L] 1 on the first pair of each Q block
    last_ref,  # [L] 1 on the last pair of each Q block
    q_ref,  # [1, 1, block_q, hd]   (prescaled by scale·log2e in HBM)
    k_ref,  # [1, 1, block_k, hd]
    v_ref,  # [1, 1, block_k, hd+1] when aug (ones column), else hd
    qseg_ref,  # [1, block_q] or None
    kseg_ref,  # [1, block_k] or None
    o_ref,  # [1, 1, block_q, hd]
    lse_ref,  # [1, 1, block_q, 1]
    acc_scr,  # [block_q, hd+1] f32 when aug (last column = l), else hd
    m_scr,  # [block_q, 1] f32
    l_scr,  # [block_q, 1] f32 — used only when not aug
    *,
    causal: bool,
    q_offset: int,
    sk: int,
    block_q: int,
    block_k: int,
    hd: int,
    aug: bool,
):
    i = pl.program_id(2)
    qi = qi_ref[i]
    ki = ki_ref[i]

    @pl.when(first_ref[i] == 1)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        acc_scr[...] = jnp.zeros_like(acc_scr)
        if not aug:
            l_scr[...] = jnp.zeros_like(l_scr)

    geom = dict(
        causal=causal, q_offset=q_offset, sk=sk,
        block_q=block_q, block_k=block_k,
    )
    full = _block_full(qi, ki, **geom)

    def body(masked: bool):
        # Dots take the native (bf16) operands — the MXU runs bf16
        # inputs at full rate — and accumulate in f32 via
        # preferred_element_type. Softmax statistics stay f32.
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]

        s = jax.lax.dot_general(
            q,
            k,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

        mask = None
        if masked:
            mask = _block_mask(qi, ki, qseg_ref, kseg_ref, **geom)
            s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_scr[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp2(m_prev - m_new)
        p = jnp.exp2(s - m_new)
        if masked:
            # Re-mask after the exp: on a row with no live column yet,
            # m_new == _NEG_INF and exp(s - m_new) == 1 for masked
            # entries, which would poison l/acc with phantom mass.
            p = jnp.where(mask, p, 0.0)
        if not aug:
            l_scr[...] = l_scr[...] * alpha + jnp.sum(
                p, axis=1, keepdims=True
            )
        # when aug, v's appended ones column makes the pv dot carry the
        # softmax denominator through the same rescale recurrence as
        # the numerator (l_new = α·l + Σp rides in acc[:, hd]) — the
        # VPU row-sum pass moves onto MXU lanes that were pad at hd=64
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype),
            v,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[...] = m_new

    _dispatch_body(full, qseg_ref is not None, body)

    @pl.when(last_ref[i] == 1)
    def _finalize():
        acc = acc_scr[...]
        l = acc[:, hd:] if aug else l_scr[...]
        l_safe = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows
        o_ref[0, 0] = (acc[:, :hd] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = m_scr[...] + jnp.log2(l_safe)


def _fwd(
    q,  # [B, Hq, Sq, hd]  (padded, head-major)
    k,  # [B, Hkv, Sk, hd]
    v,
    qseg,  # [B, Sq] int32 or None
    kseg,  # [B, Sk] int32 or None
    *,
    scale: float,
    causal: bool,
    q_offset: int,
    sk: int,
    block_q: int,
    block_k: int,
    interpret: bool,
):
    B, Hq, Sq, hd = q.shape
    _, Hkv, Sk, _ = k.shape
    group = Hq // Hkv
    num_q, num_k = Sq // block_q, Sk // block_k
    # operand augmentation rides MXU lanes that are pad at hd=64 — but
    # at 128-aligned head dims it would push every block to the next
    # 128 multiple (hd=128 → 2× dot cost), so gate it
    aug = hd % 128 != 0

    tabs = _pair_tables(
        num_q=num_q, num_k=num_k, causal=causal, q_offset=q_offset,
        sk=sk, block_q=block_q, block_k=block_k, order="row",
    )
    L = tabs[0].shape[0]
    # base-2 softmax fold rides the q prescale, done once in HBM (the
    # in-kernel variant redid the multiply on every (qi, ki) revisit);
    # python-float × bf16 rounds identically either way
    q = q * (scale * _LOG2E)
    if aug:
        # ones column: the pv dot computes numerator AND denominator
        v = jnp.concatenate([v, jnp.ones_like(v[..., :1])], axis=-1)
    hd_v = v.shape[-1]

    qspec = pl.BlockSpec(
        (1, 1, block_q, hd),
        lambda b, h, i, qi, ki, g, fs, ls: (b, h, qi[i], 0),
        memory_space=pltpu.VMEM,
    )
    kspec = pl.BlockSpec(
        (1, 1, block_k, hd),
        lambda b, h, i, qi, ki, g, fs, ls: (b, h // group, ki[i], 0),
        memory_space=pltpu.VMEM,
    )
    vspec = pl.BlockSpec(
        (1, 1, block_k, hd_v),
        lambda b, h, i, qi, ki, g, fs, ls: (b, h // group, ki[i], 0),
        memory_space=pltpu.VMEM,
    )
    in_specs = [qspec, kspec, vspec]
    args = [q, k, v]
    if qseg is not None:
        # qseg rides as a [B, Sq, 1] column, kseg as a [B, 1, Sk] row:
        # both shapes satisfy Mosaic's (8, 128)-or-full tiling rule and
        # broadcast against each other inside the kernel.
        in_specs.append(
            pl.BlockSpec(
                (1, block_q, 1),
                lambda b, h, i, qi, ki, g, fs, ls: (b, qi[i], 0),
                memory_space=pltpu.VMEM,
            )
        )
        in_specs.append(
            pl.BlockSpec(
                (1, 1, block_k),
                lambda b, h, i, qi, ki, g, fs, ls: (b, 0, ki[i]),
                memory_space=pltpu.VMEM,
            )
        )
        args += [qseg, kseg]

    kernel = functools.partial(
        _fwd_kernel,
        causal=causal,
        q_offset=q_offset,
        sk=sk,
        block_q=block_q,
        block_k=block_k,
        hd=hd,
        aug=aug,
    )
    if qseg is None:
        base = kernel

        def kernel(qi_r, ki_r, g_r, fs_r, ls_r,
                   q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m, l):
            return base(qi_r, ki_r, g_r, fs_r, ls_r,
                        q_ref, k_ref, v_ref, None, None,
                        o_ref, lse_ref, acc, m, l)

    out, lse = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=5,
            grid=(B, Hq, L),
            in_specs=in_specs,
            out_specs=(
                pl.BlockSpec(
                    (1, 1, block_q, hd),
                    lambda b, h, i, qi, ki, g, fs, ls: (b, h, qi[i], 0),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    (1, 1, block_q, 1),
                    lambda b, h, i, qi, ki, g, fs, ls: (b, h, qi[i], 0),
                    memory_space=pltpu.VMEM,
                ),
            ),
            scratch_shapes=[
                pltpu.VMEM((block_q, hd_v), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
            ],
        ),
        out_shape=(
            jax.ShapeDtypeStruct((B, Hq, Sq, hd), q.dtype),
            jax.ShapeDtypeStruct((B, Hq, Sq, 1), jnp.float32),
        ),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*tabs, *args)
    return out, lse

# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _dq_kernel(
    qi_ref,  # [L] scalar-prefetch (see _pair_tables, order="row")
    ki_ref,
    g_ref,  # unused (order="row")
    first_ref,
    last_ref,
    q_ref,  # aug: [1,1,bq,hd+2] = [q·scale·log2e | lse_hi | lse_lo];
            # else [1,1,bq,hd] prescaled q
    k_ref,  # aug: [1,1,bk,hd+2] = [k | -1 | -1]; else [1,1,bk,hd]
    v_ref,  # aug: [1,1,bk,hd+2] = [v | -1 | -1]; else [1,1,bk,hd]
    do_ref,  # aug: [1,1,bq,hd+2] = [do | δ_hi | δ_lo]; else [1,1,bq,hd]
    lse_ref,  # [1,1,bq,1] f32 — only when not aug (else folded into q)
    delta_ref,  # [1,1,bq,1] f32 — only when not aug
    qseg_ref,
    kseg_ref,
    dq_ref,  # [1, 1, block_q, hd]
    dq_scr,  # [block_q, operand width] f32
    *,
    scale: float,
    causal: bool,
    q_offset: int,
    sk: int,
    block_q: int,
    block_k: int,
    hd: int,
):
    i = pl.program_id(2)
    qi = qi_ref[i]
    ki = ki_ref[i]

    @pl.when(first_ref[i] == 1)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    geom = dict(
        causal=causal, q_offset=q_offset, sk=sk,
        block_q=block_q, block_k=block_k,
    )
    full = _block_full(qi, ki, **geom)

    def body(masked: bool):
        # Augmented mode (hd not 128-aligned): the row constants ride
        # the contraction instead of the VPU — q's two appended columns
        # carry lse (hi/lo split; one bf16 column would cost ~3 decimal
        # digits on the exponent), k's carry -1, so the s dot lands
        # directly on s·log2e·scale − lse and exp2 applies with no
        # [bq, bk] subtract pass; same for delta via do/v. The extra
        # columns are free — at hd=64 the MXU lanes were pad anyway.
        # At hd % 128 == 0 the same trick would push blocks to the next
        # lane multiple (2× dot cost), so lse/delta arrive as row
        # operands and subtract on the VPU instead.
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if lse_ref is not None:
            s = s - lse_ref[0, 0]
        p = jnp.exp2(s)
        if masked:
            p = jnp.where(
                _block_mask(qi, ki, qseg_ref, kseg_ref, **geom), p, 0.0
            )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if delta_ref is not None:
            dp = dp - delta_ref[0, 0]
        ds = p * dp
        # aug: contracting against k_aug writes junk into dq_scr[:, hd:],
        # sliced off at the finalize
        dq_scr[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    _dispatch_body(full, qseg_ref is not None, body)

    @pl.when(last_ref[i] == 1)
    def _finalize():
        dq_ref[0, 0] = (dq_scr[:, :hd] * scale).astype(dq_ref.dtype)


def _dkv_kernel(
    qi_ref,  # [L] scalar-prefetch (order="col": sorted by K block)
    ki_ref,
    g_ref,  # GQA group member of pair i
    first_ref,
    last_ref,
    q_ref,  # same operand layouts as _dq_kernel (aug vs not)
    k_ref,
    v_ref,
    do_ref,
    lse_ref,  # [1,1,bq,1] f32 — only when not aug
    delta_ref,  # [1,1,bq,1] f32 — only when not aug
    qseg_ref,
    kseg_ref,
    dk_ref,  # [1, 1, block_k, hd]  per-KV-head
    dv_ref,
    dk_scr,  # [block_k, operand width] f32
    dv_scr,
    *,
    scale: float,
    causal: bool,
    q_offset: int,
    sk: int,
    block_q: int,
    block_k: int,
    hd: int,
):
    i = pl.program_id(2)
    qj = qi_ref[i]
    ki = ki_ref[i]

    @pl.when(first_ref[i] == 1)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    geom = dict(
        causal=causal, q_offset=q_offset, sk=sk,
        block_q=block_q, block_k=block_k,
    )
    # full is symmetric in (Q block, K block): same predicate as the
    # forward, evaluated at this pair's qj.
    full = _block_full(qj, ki, **geom)

    def body(masked: bool):
        # Same operand folds as _dq_kernel (see the comment there).
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if lse_ref is not None:
            s = s - lse_ref[0, 0]
        p = jnp.exp2(s)
        if masked:
            p = jnp.where(
                _block_mask(qj, ki, qseg_ref, kseg_ref, **geom), p, 0.0
            )
        # aug: do's δ columns write junk into dv_scr[:, hd:], sliced at
        # the finalize; likewise q's lse columns for dk_scr
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if delta_ref is not None:
            dp = dp - delta_ref[0, 0]
        ds = p * dp
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    _dispatch_body(full, qseg_ref is not None, body)

    @pl.when(last_ref[i] == 1)
    def _finalize():
        # the dk dot contracted against the PRE-SCALED q (·scale·log2e);
        # the raw-s gradient needs ·scale against raw q, so divide the
        # log2e back out
        dk_ref[0, 0] = (dk_scr[:, :hd] * (1.0 / _LOG2E)).astype(
            dk_ref.dtype
        )
        dv_ref[0, 0] = dv_scr[:, :hd].astype(dv_ref.dtype)


def _bwd(
    q,
    k,
    v,
    qseg,
    kseg,
    out,
    lse,
    do,
    *,
    scale: float,
    causal: bool,
    q_offset: int,
    sk: int,
    block_q: int,
    block_k: int,
    interpret: bool,
):
    B, Hq, Sq, hd = q.shape
    _, Hkv, Sk, _ = k.shape
    group = Hq // Hkv
    num_q, num_k = Sq // block_q, Sk // block_k
    # see _fwd: operand augmentation only where the lanes are pad anyway
    aug = hd % 128 != 0

    # delta_i = rowsum(dO_i * O_i): cheap elementwise+reduce, XLA fuses it.
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1,
        keepdims=True,
    )
    q = q * (scale * _LOG2E)  # base-2 fold, once in HBM
    if aug:
        # Row constants fold into the dots via two appended operand
        # columns (hi/lo bf16 split keeps f32-grade precision; one bf16
        # column would cost ~2% on exp2).
        def _hi_lo(x):
            hi = x.astype(k.dtype)
            lo = (x - hi.astype(x.dtype)).astype(k.dtype)
            return hi, lo

        lse_hi, lse_lo = _hi_lo(lse)
        d_hi, d_lo = _hi_lo(delta)
        neg1 = -jnp.ones_like(k[..., :1])
        q = jnp.concatenate([q, lse_hi, lse_lo], -1)
        k = jnp.concatenate([k, neg1, neg1], -1)
        v = jnp.concatenate([v, neg1, neg1], -1)
        do = jnp.concatenate([do, d_hi, d_lo], -1)
    hd2 = q.shape[-1]

    common = dict(
        scale=scale, causal=causal, q_offset=q_offset, sk=sk,
        block_q=block_q, block_k=block_k, hd=hd,
    )

    def row_spec(idx):
        return pl.BlockSpec(
            (1, 1, block_q, 1), idx, memory_space=pltpu.VMEM,
        )

    # --- dQ: grid (B, Hq, live pairs), accumulate over K blocks ------
    dq_tabs = _pair_tables(
        num_q=num_q, num_k=num_k, causal=causal, q_offset=q_offset,
        sk=sk, block_q=block_q, block_k=block_k, order="row",
    )
    qblk = pl.BlockSpec(
        (1, 1, block_q, hd2),
        lambda b, h, i, qi, ki, g, fs, ls: (b, h, qi[i], 0),
        memory_space=pltpu.VMEM,
    )
    kvblk = pl.BlockSpec(
        (1, 1, block_k, hd2),
        lambda b, h, i, qi, ki, g, fs, ls: (b, h // group, ki[i], 0),
        memory_space=pltpu.VMEM,
    )
    dq_args = [q, k, v, do]
    dq_specs = [qblk, kvblk, kvblk, qblk]
    if not aug:
        dq_args += [lse, delta]
        dq_specs += [
            row_spec(lambda b, h, i, qi, ki, g, fs, ls: (b, h, qi[i], 0)),
            row_spec(lambda b, h, i, qi, ki, g, fs, ls: (b, h, qi[i], 0)),
        ]
    if qseg is not None:
        dq_args += [qseg, kseg]
        dq_specs += [
            pl.BlockSpec(
                (1, block_q, 1),
                lambda b, h, i, qi, ki, g, fs, ls: (b, qi[i], 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, block_k),
                lambda b, h, i, qi, ki, g, fs, ls: (b, 0, ki[i]),
                memory_space=pltpu.VMEM,
            ),
        ]

    def dq_kernel(*refs):
        tabs, rest = refs[:5], list(refs[5:])
        q_r, k_r, v_r, do_r = rest[:4]
        rest = rest[4:]
        lse_r = delta_r = qs_r = ks_r = None
        if not aug:
            lse_r, delta_r = rest[:2]
            rest = rest[2:]
        if qseg is not None:
            qs_r, ks_r = rest[:2]
            rest = rest[2:]
        dq_r, scr = rest
        _dq_kernel(
            *tabs, q_r, k_r, v_r, do_r, lse_r, delta_r, qs_r, ks_r,
            dq_r, scr, **common,
        )

    dq = pl.pallas_call(
        dq_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=5,
            grid=(B, Hq, dq_tabs[0].shape[0]),
            in_specs=dq_specs,
            out_specs=pl.BlockSpec(
                (1, 1, block_q, hd),
                lambda b, h, i, qi, ki, g, fs, ls: (b, h, qi[i], 0),
                memory_space=pltpu.VMEM,
            ),
            scratch_shapes=[pltpu.VMEM((block_q, hd2), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((B, Hq, Sq, hd), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*dq_tabs, *dq_args)

    # --- dK/dV: grid (B, Hkv, live (ki, g, qj) triples). The GQA
    # group is folded into the pair walk, so dK/dV accumulate per KV
    # head in VMEM scratch and hit HBM exactly once, in k.dtype — no
    # per-Q-head f32 transients.
    dkv_tabs = _pair_tables(
        num_q=num_q, num_k=num_k, causal=causal, q_offset=q_offset,
        sk=sk, block_q=block_q, block_k=block_k, order="col",
        group=group,
    )
    qhblk = pl.BlockSpec(
        (1, 1, block_q, hd2),
        lambda b, h, i, qi, ki, g, fs, ls: (
            b, h * group + g[i], qi[i], 0
        ),
        memory_space=pltpu.VMEM,
    )
    kvhblk = pl.BlockSpec(
        (1, 1, block_k, hd2),
        lambda b, h, i, qi, ki, g, fs, ls: (b, h, ki[i], 0),
        memory_space=pltpu.VMEM,
    )
    dkv_args = [q, k, v, do]
    dkv_specs = [qhblk, kvhblk, kvhblk, qhblk]
    if not aug:
        dkv_args += [lse, delta]
        dkv_specs += [
            row_spec(lambda b, h, i, qi, ki, g, fs, ls: (
                b, h * group + g[i], qi[i], 0
            )),
            row_spec(lambda b, h, i, qi, ki, g, fs, ls: (
                b, h * group + g[i], qi[i], 0
            )),
        ]
    if qseg is not None:
        dkv_args += [qseg, kseg]
        dkv_specs += [
            pl.BlockSpec(
                (1, block_q, 1),
                lambda b, h, i, qi, ki, g, fs, ls: (b, qi[i], 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, block_k),
                lambda b, h, i, qi, ki, g, fs, ls: (b, 0, ki[i]),
                memory_space=pltpu.VMEM,
            ),
        ]

    def dkv_kernel(*refs):
        tabs, rest = refs[:5], list(refs[5:])
        q_r, k_r, v_r, do_r = rest[:4]
        rest = rest[4:]
        lse_r = delta_r = qs_r = ks_r = None
        if not aug:
            lse_r, delta_r = rest[:2]
            rest = rest[2:]
        if qseg is not None:
            qs_r, ks_r = rest[:2]
            rest = rest[2:]
        dk_r, dv_r, kscr, vscr = rest
        _dkv_kernel(
            *tabs, q_r, k_r, v_r, do_r, lse_r, delta_r, qs_r, ks_r,
            dk_r, dv_r, kscr, vscr, **common,
        )

    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=5,
            grid=(B, Hkv, dkv_tabs[0].shape[0]),
            in_specs=dkv_specs,
            out_specs=(
                pl.BlockSpec(
                    (1, 1, block_k, hd),
                    lambda b, h, i, qi, ki, g, fs, ls: (b, h, ki[i], 0),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    (1, 1, block_k, hd),
                    lambda b, h, i, qi, ki, g, fs, ls: (b, h, ki[i], 0),
                    memory_space=pltpu.VMEM,
                ),
            ),
            scratch_shapes=[
                pltpu.VMEM((block_k, hd2), jnp.float32),
                pltpu.VMEM((block_k, hd2), jnp.float32),
            ],
        ),
        out_shape=(
            jax.ShapeDtypeStruct((B, Hkv, Sk, hd), k.dtype),
            jax.ShapeDtypeStruct((B, Hkv, Sk, hd), v.dtype),
        ),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*dkv_tabs, *dkv_args)

    return dq, dk, dv


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9, 10, 11, 12)
)
def _flash(q, k, v, segment_ids, causal, q_offset, sq, sk,
           block_q, block_k, bwd_block_q, bwd_block_k, interpret):
    out, _ = _flash_fwd(
        q, k, v, segment_ids, causal, q_offset, sq, sk,
        block_q, block_k, bwd_block_q, bwd_block_k, interpret,
    )
    return out


def _prep(q, k, v, segment_ids, sq, sk, block_q, block_k):
    """[B,S,H,d] → padded head-major [B,H,S,d] plus padded segment ids."""
    B = q.shape[0]
    sq_p, sk_p = _ceil_to(sq, block_q), _ceil_to(sk, block_k)
    qt = jnp.moveaxis(q, 1, 2)
    kt = jnp.moveaxis(k, 1, 2)
    vt = jnp.moveaxis(v, 1, 2)
    if sq_p != sq:
        qt = jnp.pad(qt, ((0, 0), (0, 0), (0, sq_p - sq), (0, 0)))
    if sk_p != sk:
        kt = jnp.pad(kt, ((0, 0), (0, 0), (0, sk_p - sk), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, 0), (0, sk_p - sk), (0, 0)))
    qseg = kseg = None
    if segment_ids is not None:
        seg = segment_ids.astype(jnp.int32)
        # Padded rows/cols get sentinel ids that never match real ones.
        # Shapes: qseg [B, Sq, 1] (column), kseg [B, 1, Sk] (row) — see
        # the spec comment in _fwd.
        qseg = jnp.pad(seg, ((0, 0), (0, sq_p - sq)),
                       constant_values=-1)[:, :, None]
        kseg = jnp.pad(seg[:, :sk], ((0, 0), (0, sk_p - sk)),
                       constant_values=-2)[:, None, :]
    return qt, kt, vt, qseg, kseg


def _flash_fwd(q, k, v, segment_ids, causal, q_offset, sq, sk,
               block_q, block_k, bwd_block_q, bwd_block_k, interpret):
    hd = q.shape[-1]
    scale = hd**-0.5
    qt, kt, vt, qseg, kseg = _prep(
        q, k, v, segment_ids, sq, sk, block_q, block_k
    )
    out_p, lse = _fwd(
        qt, kt, vt, qseg, kseg,
        scale=scale, causal=causal, q_offset=q_offset, sk=sk,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    # Named residuals: under ``jax.checkpoint`` a policy that saves
    # "flash_out"/"flash_lse" (models/llama.py remat_policy="attn")
    # keeps exactly these two tensors, so the backward pass never
    # re-executes the forward flash kernel — the recompute is reduced
    # to the (cheap) projections while attention runs fwd-once +
    # bwd-once. O(S·Hq·hd) extra residency per layer, vs the O(S²)
    # score matrix flash exists to avoid.
    out_p = _checkpoint_name(out_p, "flash_out")
    lse = _checkpoint_name(lse, "flash_lse")
    out = jnp.moveaxis(out_p[:, :, :sq], 2, 1)
    return out, (q, k, v, segment_ids, out_p, lse)


def _flash_bwd(causal, q_offset, sq, sk, block_q, block_k,
               bwd_block_q, bwd_block_k, interpret, res, g):
    q, k, v, segment_ids, out_p, lse = res
    hd = q.shape[-1]
    scale = hd**-0.5
    # The dq/dkv kernels have different arithmetic (3 dots each, larger
    # VMEM working set) than the forward, so their optimal tiling
    # differs — they get their own block sizes. Residuals out_p/lse are
    # padded to the FORWARD block multiple; re-pad to the backward one
    # when they disagree (padded q rows are zero ⇒ s = 0 and do = 0
    # there, so any finite lse fill keeps the padded contributions 0).
    bq, bk = bwd_block_q or block_q, bwd_block_k or block_k
    qt, kt, vt, qseg, kseg = _prep(
        q, k, v, segment_ids, sq, sk, bq, bk
    )
    sq_p = qt.shape[2]
    if out_p.shape[2] != sq_p:
        out_p = out_p[:, :, :sq]
        lse = lse[:, :, :sq]
        if sq_p != sq:
            pad = ((0, 0), (0, 0), (0, sq_p - sq), (0, 0))
            out_p = jnp.pad(out_p, pad)
            lse = jnp.pad(lse, pad)
    do = jnp.moveaxis(g, 1, 2)
    if sq_p != sq:
        do = jnp.pad(do, ((0, 0), (0, 0), (0, sq_p - sq), (0, 0)))
    dq, dk, dv = _bwd(
        qt, kt, vt, qseg, kseg, out_p, lse, do,
        scale=scale, causal=causal, q_offset=q_offset, sk=sk,
        block_q=bq, block_k=bk, interpret=interpret,
    )
    dq = jnp.moveaxis(dq[:, :, :sq], 2, 1)
    dk = jnp.moveaxis(dk[:, :, :sk], 2, 1)
    dv = jnp.moveaxis(dv[:, :, :sk], 2, 1)
    return dq, dk, dv, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jnp.ndarray,  # [B, Sq, Hq, hd]
    k: jnp.ndarray,  # [B, Sk, Hkv, hd]
    v: jnp.ndarray,  # [B, Sk, Hkv, hd]
    *,
    causal: bool = True,
    q_offset: int = 0,
    segment_ids: Optional[jnp.ndarray] = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    bwd_block_q: Optional[int] = None,
    bwd_block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Flash attention; same contract as ``dense_attention``.

    ``q_offset`` must be a static python int on this path (the pallas
    grid's causal-skip predicate is specialised on it); the decode path
    with a traced offset should use ``dense_attention``.

    ``bwd_block_q``/``bwd_block_k`` tile the dq/dkv kernels
    independently of the forward (their 3-dot bodies have a different
    VMEM/VPU balance); None inherits the forward blocks.
    """
    if not isinstance(q_offset, int):
        raise TypeError(
            "flash_attention requires a static int q_offset; use "
            "dense_attention for traced offsets (KV-cache decode)."
        )
    B, sq, Hq, hd = q.shape
    _, sk, Hkv, _ = k.shape
    assert Hq % Hkv == 0, (Hq, Hkv)
    if interpret is None:
        interpret = _interpret_default()
    block_q = min(block_q, _ceil_to(sq, 128))
    block_k = min(block_k, _ceil_to(sk, 128))
    if bwd_block_q is not None:
        bwd_block_q = min(bwd_block_q, _ceil_to(sq, 128))
    if bwd_block_k is not None:
        bwd_block_k = min(bwd_block_k, _ceil_to(sk, 128))
    return _flash(
        q, k, v, segment_ids, causal, q_offset, sq, sk,
        block_q, block_k, bwd_block_q, bwd_block_k, interpret,
    )
