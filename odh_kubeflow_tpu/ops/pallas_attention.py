"""Pallas TPU flash attention (forward + backward).

Tiled attention that never materialises the [Sq, Sk] score matrix:
the kernel streams K/V blocks through VMEM and keeps an online-softmax
accumulator (running max ``m``, denominator ``l``, weighted sum ``acc``)
per Q block, so HBM traffic is O(S·d) instead of O(S²). The backward
pass recomputes scores from the saved logsumexp (flash-v2 style) in two
kernels: one accumulating dQ over K blocks, one accumulating dK/dV over
Q blocks.

Drop-in for ``ops.attention.dense_attention`` (same signature; the
reference platform has no attention code at all — SURVEY.md §2.4 — this
is a new TPU-native component). GQA is handled by mapping each Q head's
grid cell onto its KV head (``h // group``) in the K/V index maps, so
KV blocks are fetched once per group from HBM's point of view (Mosaic
caches the revisited block).

Causal masking skips fully-masked K blocks via predication
(``pl.when``), and the MXU sees [block_q, block_k] @ [block_k, hd]
tiles — 128-aligned by construction (inputs are padded).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name as _checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# swept on v5e (fwd, S∈{1k,4k}): 1024×1024 beats 512×1024 by ~10%;
# both clamp to the sequence length for shorter inputs
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024
_NEG_INF = -1e30
# The softmax runs in base-2 end to end: log2(e) folds into the q
# prescale (one [bq, hd] multiply), so the VPU evaluates raw exp2 on
# the [bq, bk] score blocks instead of exp = exp2(x·log2e) — one fewer
# full-block multiply per exponential, and the kernel is VPU-bound at
# small head_dim. The saved logsumexp residual is likewise base-2
# (lse2 = log2e·lse); it never leaves this file.
_LOG2E = 1.4426950408889634


def _ceil_to(x: int, m: int) -> int:
    return -(-x // m) * m


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _ki_live_fn(causal: bool, q_offset: int, block_q: int, block_k: int):
    """Remap causally-dead K-block indices onto the live boundary block.

    The kernel predicates dead blocks out of *compute*; this keeps them
    out of *memory traffic* too — consecutive grid steps that map to the
    same block index skip the re-fetch, so the dead upper-triangle
    blocks cost neither MXU nor HBM bandwidth.
    """
    if not causal:
        return lambda qi, ki: ki

    def live(qi, ki):
        boundary = (qi * block_q + block_q - 1 + q_offset) // block_k
        return jnp.maximum(0, jnp.minimum(ki, boundary))

    return live


def _qj_live_fn(causal: bool, q_offset: int, block_q: int, block_k: int,
                num_q: int):
    """Mirror of _ki_live_fn for the dK/dV kernel's Q-block axis."""
    if not causal:
        return lambda ki, qj: qj

    def live(ki, qj):
        boundary = (ki * block_k - q_offset) // block_q
        return jnp.minimum(num_q - 1, jnp.maximum(qj, boundary))

    return live


def _block_predicates(qb, ki, *, causal, q_offset, sk, block_q, block_k):
    """(run, full) for the block at Q-block index ``qb`` / K-block
    index ``ki``. ``run``: any (row, col) pair is live under the causal
    skip. ``full``: EVERY pair is live — interior causal blocks with no
    padded K columns, the hot case at long context (S=16k, block 1024:
    120 of 136 live blocks are full). Full blocks skip the iota/
    compare/select mask arithmetic, which is what the VPU otherwise
    burns time on between the MXU dots."""
    run = True
    full = (ki + 1) * block_k <= sk
    if causal:
        run = ki * block_k <= qb * block_q + (block_q - 1) + q_offset
        full = jnp.logical_and(
            full, qb * block_q + q_offset >= ki * block_k + (block_k - 1)
        )
    return run, full


def _block_mask(qb, ki, qseg_ref, kseg_ref, *, causal, q_offset, sk,
                block_q, block_k):
    """[block_q, block_k] live-pair mask for an edge block."""
    q_pos = qb * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    mask = k_pos < sk  # padded K columns never contribute
    if causal:
        mask = jnp.logical_and(mask, q_pos + q_offset >= k_pos)
    if qseg_ref is not None:
        mask = jnp.logical_and(mask, qseg_ref[0] == kseg_ref[0])
    return mask


def _when_blocks(run, full, has_segments, body):
    """Dispatch a kernel body over the full/edge split. Segmented
    kernels always take the masked path (segment walls can cut any
    block); otherwise interior blocks run the mask-free fast path."""
    if has_segments:

        @pl.when(run)
        def _masked():
            body(masked=True)

    else:

        @pl.when(jnp.logical_and(run, full))
        def _full():
            body(masked=False)

        @pl.when(jnp.logical_and(run, jnp.logical_not(full)))
        def _edge():
            body(masked=True)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(
    q_ref,  # [1, 1, block_q, hd]
    k_ref,  # [1, 1, block_k, hd]
    v_ref,  # [1, 1, block_k, hd]
    qseg_ref,  # [1, block_q] or None
    kseg_ref,  # [1, block_k] or None
    o_ref,  # [1, 1, block_q, hd]
    lse_ref,  # [1, 1, block_q, 1]
    acc_scr,  # [block_q, hd] f32
    m_scr,  # [block_q, 1] f32
    l_scr,  # [block_q, 1] f32
    *,
    scale: float,
    causal: bool,
    q_offset: int,
    sk: int,
    block_q: int,
    block_k: int,
    num_k: int,
):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    geom = dict(
        causal=causal, q_offset=q_offset, sk=sk,
        block_q=block_q, block_k=block_k,
    )
    run, full = _block_predicates(qi, ki, **geom)

    def body(masked: bool):
        # Dots take the native (bf16) operands — the MXU runs bf16
        # inputs at full rate — and accumulate in f32 via
        # preferred_element_type. Softmax statistics stay f32.
        # Scaling (incl. the base-2 fold) rides on the [bq, hd] q block
        # (block_k/hd ≈ 16× cheaper than scaling the [bq, bk] scores).
        q = q_ref[0, 0] * (scale * _LOG2E)
        k = k_ref[0, 0]
        v = v_ref[0, 0]

        s = jax.lax.dot_general(
            q,
            k,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

        mask = None
        if masked:
            mask = _block_mask(qi, ki, qseg_ref, kseg_ref, **geom)
            s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_scr[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp2(m_prev - m_new)
        p = jnp.exp2(s - m_new)
        if masked:
            # Re-mask after the exp: on a row with no live column yet,
            # m_new == _NEG_INF and exp(s - m_new) == 1 for masked
            # entries, which would poison l/acc with phantom mass.
            p = jnp.where(mask, p, 0.0)
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype),
            v,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[...] = m_new

    _when_blocks(run, full, qseg_ref is not None, body)

    @pl.when(ki == num_k - 1)
    def _finalize():
        l = l_scr[...]
        l_safe = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows
        o_ref[0, 0] = (acc_scr[...] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = m_scr[...] + jnp.log2(l_safe)


def _fwd(
    q,  # [B, Hq, Sq, hd]  (padded, head-major)
    k,  # [B, Hkv, Sk, hd]
    v,
    qseg,  # [B, Sq] int32 or None
    kseg,  # [B, Sk] int32 or None
    *,
    scale: float,
    causal: bool,
    q_offset: int,
    sk: int,
    block_q: int,
    block_k: int,
    interpret: bool,
):
    B, Hq, Sq, hd = q.shape
    _, Hkv, Sk, _ = k.shape
    group = Hq // Hkv
    num_q, num_k = Sq // block_q, Sk // block_k

    ki_live = _ki_live_fn(causal, q_offset, block_q, block_k)
    qspec = pl.BlockSpec(
        (1, 1, block_q, hd),
        lambda b, h, qi, ki: (b, h, qi, 0),
        memory_space=pltpu.VMEM,
    )
    kvspec = pl.BlockSpec(
        (1, 1, block_k, hd),
        lambda b, h, qi, ki: (b, h // group, ki_live(qi, ki), 0),
        memory_space=pltpu.VMEM,
    )
    in_specs = [qspec, kvspec, kvspec]
    args = [q, k, v]
    if qseg is not None:
        # qseg rides as a [B, Sq, 1] column, kseg as a [B, 1, Sk] row:
        # both shapes satisfy Mosaic's (8, 128)-or-full tiling rule and
        # broadcast against each other inside the kernel.
        in_specs.append(
            pl.BlockSpec(
                (1, block_q, 1),
                lambda b, h, qi, ki: (b, qi, 0),
                memory_space=pltpu.VMEM,
            )
        )
        in_specs.append(
            pl.BlockSpec(
                (1, 1, block_k),
                lambda b, h, qi, ki: (b, 0, ki),
                memory_space=pltpu.VMEM,
            )
        )
        args += [qseg, kseg]

    kernel = functools.partial(
        _fwd_kernel,
        scale=scale,
        causal=causal,
        q_offset=q_offset,
        sk=sk,
        block_q=block_q,
        block_k=block_k,
        num_k=num_k,
    )
    if qseg is None:
        base = kernel

        def kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m, l):
            return base(q_ref, k_ref, v_ref, None, None,
                        o_ref, lse_ref, acc, m, l)

    out, lse = pl.pallas_call(
        kernel,
        grid=(B, Hq, num_q, num_k),
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec(
                (1, 1, block_q, hd),
                lambda b, h, qi, ki: (b, h, qi, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, block_q, 1),
                lambda b, h, qi, ki: (b, h, qi, 0),
                memory_space=pltpu.VMEM,
            ),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((B, Hq, Sq, hd), q.dtype),
            jax.ShapeDtypeStruct((B, Hq, Sq, 1), jnp.float32),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, hd), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*args)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _dq_kernel(
    q_ref,
    k_ref,
    v_ref,
    do_ref,
    lse_ref,  # [1, 1, block_q, 1]
    delta_ref,  # [1, 1, block_q]
    qseg_ref,
    kseg_ref,
    dq_ref,  # [1, 1, block_q, hd]
    dq_scr,  # [block_q, hd] f32
    *,
    scale: float,
    causal: bool,
    q_offset: int,
    sk: int,
    block_q: int,
    block_k: int,
    num_k: int,
):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    geom = dict(
        causal=causal, q_offset=q_offset, sk=sk,
        block_q=block_q, block_k=block_k,
    )
    run, full = _block_predicates(qi, ki, **geom)

    def body(masked: bool):
        # s comes from the pre-scaled q (base-2 fold included); the
        # outer `* scale` on ds is linear, so it moves to the finalize
        # (one [bq, hd] multiply instead of a [bq, bk] one per block).
        q = q_ref[0, 0] * (scale * _LOG2E)
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]  # base-2 (see _LOG2E)
        delta = delta_ref[0, 0]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        p = jnp.exp2(s - lse)
        if masked:
            p = jnp.where(
                _block_mask(qi, ki, qseg_ref, kseg_ref, **geom), p, 0.0
            )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta)
        dq_scr[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    _when_blocks(run, full, qseg_ref is not None, body)

    @pl.when(ki == num_k - 1)
    def _finalize():
        dq_ref[0, 0] = (dq_scr[...] * scale).astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref,
    k_ref,
    v_ref,
    do_ref,
    lse_ref,
    delta_ref,
    qseg_ref,
    kseg_ref,
    dk_ref,  # [1, 1, block_k, hd]  per-KV-head
    dv_ref,
    dk_scr,
    dv_scr,
    *,
    scale: float,
    causal: bool,
    q_offset: int,
    sk: int,
    block_q: int,
    block_k: int,
    num_q: int,
    total_q: int,
):
    ki = pl.program_id(2)
    t = pl.program_id(3)  # t = group_member * num_q + q_block
    qj = t % num_q

    @pl.when(t == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    geom = dict(
        causal=causal, q_offset=q_offset, sk=sk,
        block_q=block_q, block_k=block_k,
    )
    # run/full are symmetric in (Q block, K block): same predicates as
    # the forward, evaluated at this program's qj.
    run, full = _block_predicates(qj, ki, **geom)

    def body(masked: bool):
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]

        # s from pre-scaled q (base-2 fold included); dK's `* scale` is
        # linear and moves to the finalize. The dk dot below contracts
        # against the ORIGINAL q — its scale factor is exactly the
        # deferred one.
        s = jax.lax.dot_general(
            q * (scale * _LOG2E), k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        p = jnp.exp2(s - lse)  # [bq, bk]; lse is base-2
        if masked:
            p = jnp.where(
                _block_mask(qj, ki, qseg_ref, kseg_ref, **geom), p, 0.0
            )
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta)
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    _when_blocks(run, full, qseg_ref is not None, body)

    @pl.when(t == total_q - 1)
    def _finalize():
        dk_ref[0, 0] = (dk_scr[...] * scale).astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd(
    q,
    k,
    v,
    qseg,
    kseg,
    out,
    lse,
    do,
    *,
    scale: float,
    causal: bool,
    q_offset: int,
    sk: int,
    block_q: int,
    block_k: int,
    interpret: bool,
):
    B, Hq, Sq, hd = q.shape
    _, Hkv, Sk, _ = k.shape
    group = Hq // Hkv
    num_q, num_k = Sq // block_q, Sk // block_k

    # delta_i = rowsum(dO_i * O_i): cheap elementwise+reduce, XLA fuses it.
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1, keepdims=True
    )

    ki_live = _ki_live_fn(causal, q_offset, block_q, block_k)
    qj_live = _qj_live_fn(causal, q_offset, block_q, block_k, num_q)

    # --- dQ: grid (B, Hq, num_q, num_k), accumulate over k blocks ---
    specs = dict(
        q=pl.BlockSpec(
            (1, 1, block_q, hd),
            lambda b, h, qi, ki: (b, h, qi, 0),
            memory_space=pltpu.VMEM,
        ),
        kv=pl.BlockSpec(
            (1, 1, block_k, hd),
            lambda b, h, qi, ki: (b, h // group, ki_live(qi, ki), 0),
            memory_space=pltpu.VMEM,
        ),
        row=pl.BlockSpec(
            (1, 1, block_q, 1),
            lambda b, h, qi, ki: (b, h, qi, 0),
            memory_space=pltpu.VMEM,
        ),
        qseg=pl.BlockSpec(
            (1, block_q, 1),
            lambda b, h, qi, ki: (b, qi, 0),
            memory_space=pltpu.VMEM,
        ),
        kseg=pl.BlockSpec(
            (1, 1, block_k),
            lambda b, h, qi, ki: (b, 0, ki),
            memory_space=pltpu.VMEM,
        ),
    )

    dq_args = [q, k, v, do, lse, delta]
    dq_specs = [
        specs["q"], specs["kv"], specs["kv"], specs["q"],
        specs["row"], specs["row"],
    ]
    if qseg is not None:
        dq_args += [qseg, kseg]
        dq_specs += [specs["qseg"], specs["kseg"]]

    common = dict(
        scale=scale, causal=causal, q_offset=q_offset, sk=sk,
        block_q=block_q, block_k=block_k,
    )

    def dq_kernel(*refs):
        if qseg is not None:
            (q_r, k_r, v_r, do_r, lse_r, dl_r, qs_r, ks_r, dq_r, scr) = refs
        else:
            (q_r, k_r, v_r, do_r, lse_r, dl_r, dq_r, scr) = refs
            qs_r = ks_r = None
        _dq_kernel(
            q_r, k_r, v_r, do_r, lse_r, dl_r, qs_r, ks_r, dq_r, scr,
            num_k=num_k, **common,
        )

    dq = pl.pallas_call(
        dq_kernel,
        grid=(B, Hq, num_q, num_k),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec(
            (1, 1, block_q, hd),
            lambda b, h, qi, ki: (b, h, qi, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((B, Hq, Sq, hd), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, hd), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*dq_args)

    # --- dK/dV: grid (B, Hkv, num_k, group*num_q). The GQA group is
    # folded into the accumulation axis (t = g*num_q + qj), so dK/dV
    # accumulate per KV head in VMEM scratch and hit HBM exactly once,
    # in k.dtype — no per-Q-head f32 transients.
    total_q = group * num_q

    dkv_args = [q, k, v, do, lse, delta]
    dkv_specs = [
        pl.BlockSpec(
            (1, 1, block_q, hd),
            lambda b, h, ki, t: (
                b, h * group + t // num_q, qj_live(ki, t % num_q), 0
            ),
            memory_space=pltpu.VMEM,
        ),
        pl.BlockSpec(
            (1, 1, block_k, hd),
            lambda b, h, ki, t: (b, h, ki, 0),
            memory_space=pltpu.VMEM,
        ),
        pl.BlockSpec(
            (1, 1, block_k, hd),
            lambda b, h, ki, t: (b, h, ki, 0),
            memory_space=pltpu.VMEM,
        ),
        pl.BlockSpec(
            (1, 1, block_q, hd),
            lambda b, h, ki, t: (
                b, h * group + t // num_q, qj_live(ki, t % num_q), 0
            ),
            memory_space=pltpu.VMEM,
        ),
        pl.BlockSpec(
            (1, 1, block_q, 1),
            lambda b, h, ki, t: (
                b, h * group + t // num_q, qj_live(ki, t % num_q), 0
            ),
            memory_space=pltpu.VMEM,
        ),
        pl.BlockSpec(
            (1, 1, block_q, 1),
            lambda b, h, ki, t: (
                b, h * group + t // num_q, qj_live(ki, t % num_q), 0
            ),
            memory_space=pltpu.VMEM,
        ),
    ]
    if qseg is not None:
        dkv_args += [qseg, kseg]
        dkv_specs += [
            pl.BlockSpec(
                (1, block_q, 1),
                lambda b, h, ki, t: (b, qj_live(ki, t % num_q), 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, block_k),
                lambda b, h, ki, t: (b, 0, ki),
                memory_space=pltpu.VMEM,
            ),
        ]

    def dkv_kernel(*refs):
        if qseg is not None:
            (q_r, k_r, v_r, do_r, lse_r, dl_r, qs_r, ks_r,
             dk_r, dv_r, kscr, vscr) = refs
        else:
            (q_r, k_r, v_r, do_r, lse_r, dl_r, dk_r, dv_r, kscr, vscr) = refs
            qs_r = ks_r = None
        _dkv_kernel(
            q_r, k_r, v_r, do_r, lse_r, dl_r, qs_r, ks_r,
            dk_r, dv_r, kscr, vscr, num_q=num_q, total_q=total_q, **common,
        )

    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(B, Hkv, num_k, total_q),
        in_specs=dkv_specs,
        out_specs=(
            pl.BlockSpec(
                (1, 1, block_k, hd),
                lambda b, h, ki, t: (b, h, ki, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, block_k, hd),
                lambda b, h, ki, t: (b, h, ki, 0),
                memory_space=pltpu.VMEM,
            ),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((B, Hkv, Sk, hd), k.dtype),
            jax.ShapeDtypeStruct((B, Hkv, Sk, hd), v.dtype),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_k, hd), jnp.float32),
            pltpu.VMEM((block_k, hd), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*dkv_args)

    return dq, dk, dv


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9, 10, 11, 12)
)
def _flash(q, k, v, segment_ids, causal, q_offset, sq, sk,
           block_q, block_k, bwd_block_q, bwd_block_k, interpret):
    out, _ = _flash_fwd(
        q, k, v, segment_ids, causal, q_offset, sq, sk,
        block_q, block_k, bwd_block_q, bwd_block_k, interpret,
    )
    return out


def _prep(q, k, v, segment_ids, sq, sk, block_q, block_k):
    """[B,S,H,d] → padded head-major [B,H,S,d] plus padded segment ids."""
    B = q.shape[0]
    sq_p, sk_p = _ceil_to(sq, block_q), _ceil_to(sk, block_k)
    qt = jnp.moveaxis(q, 1, 2)
    kt = jnp.moveaxis(k, 1, 2)
    vt = jnp.moveaxis(v, 1, 2)
    if sq_p != sq:
        qt = jnp.pad(qt, ((0, 0), (0, 0), (0, sq_p - sq), (0, 0)))
    if sk_p != sk:
        kt = jnp.pad(kt, ((0, 0), (0, 0), (0, sk_p - sk), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, 0), (0, sk_p - sk), (0, 0)))
    qseg = kseg = None
    if segment_ids is not None:
        seg = segment_ids.astype(jnp.int32)
        # Padded rows/cols get sentinel ids that never match real ones.
        # Shapes: qseg [B, Sq, 1] (column), kseg [B, 1, Sk] (row) — see
        # the spec comment in _fwd.
        qseg = jnp.pad(seg, ((0, 0), (0, sq_p - sq)),
                       constant_values=-1)[:, :, None]
        kseg = jnp.pad(seg[:, :sk], ((0, 0), (0, sk_p - sk)),
                       constant_values=-2)[:, None, :]
    return qt, kt, vt, qseg, kseg


def _flash_fwd(q, k, v, segment_ids, causal, q_offset, sq, sk,
               block_q, block_k, bwd_block_q, bwd_block_k, interpret):
    hd = q.shape[-1]
    scale = hd**-0.5
    qt, kt, vt, qseg, kseg = _prep(
        q, k, v, segment_ids, sq, sk, block_q, block_k
    )
    out_p, lse = _fwd(
        qt, kt, vt, qseg, kseg,
        scale=scale, causal=causal, q_offset=q_offset, sk=sk,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    # Named residuals: under ``jax.checkpoint`` a policy that saves
    # "flash_out"/"flash_lse" (models/llama.py remat_policy="attn")
    # keeps exactly these two tensors, so the backward pass never
    # re-executes the forward flash kernel — the recompute is reduced
    # to the (cheap) projections while attention runs fwd-once +
    # bwd-once. O(S·Hq·hd) extra residency per layer, vs the O(S²)
    # score matrix flash exists to avoid.
    out_p = _checkpoint_name(out_p, "flash_out")
    lse = _checkpoint_name(lse, "flash_lse")
    out = jnp.moveaxis(out_p[:, :, :sq], 2, 1)
    return out, (q, k, v, segment_ids, out_p, lse)


def _flash_bwd(causal, q_offset, sq, sk, block_q, block_k,
               bwd_block_q, bwd_block_k, interpret, res, g):
    q, k, v, segment_ids, out_p, lse = res
    hd = q.shape[-1]
    scale = hd**-0.5
    # The dq/dkv kernels have different arithmetic (3 dots each, larger
    # VMEM working set) than the forward, so their optimal tiling
    # differs — they get their own block sizes. Residuals out_p/lse are
    # padded to the FORWARD block multiple; re-pad to the backward one
    # when they disagree (padded q rows are zero ⇒ s = 0 and do = 0
    # there, so any finite lse fill keeps the padded contributions 0).
    bq, bk = bwd_block_q or block_q, bwd_block_k or block_k
    qt, kt, vt, qseg, kseg = _prep(
        q, k, v, segment_ids, sq, sk, bq, bk
    )
    sq_p = qt.shape[2]
    if out_p.shape[2] != sq_p:
        out_p = out_p[:, :, :sq]
        lse = lse[:, :, :sq]
        if sq_p != sq:
            pad = ((0, 0), (0, 0), (0, sq_p - sq), (0, 0))
            out_p = jnp.pad(out_p, pad)
            lse = jnp.pad(lse, pad)
    do = jnp.moveaxis(g, 1, 2)
    if sq_p != sq:
        do = jnp.pad(do, ((0, 0), (0, 0), (0, sq_p - sq), (0, 0)))
    dq, dk, dv = _bwd(
        qt, kt, vt, qseg, kseg, out_p, lse, do,
        scale=scale, causal=causal, q_offset=q_offset, sk=sk,
        block_q=bq, block_k=bk, interpret=interpret,
    )
    dq = jnp.moveaxis(dq[:, :, :sq], 2, 1)
    dk = jnp.moveaxis(dk[:, :, :sk], 2, 1)
    dv = jnp.moveaxis(dv[:, :, :sk], 2, 1)
    return dq, dk, dv, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jnp.ndarray,  # [B, Sq, Hq, hd]
    k: jnp.ndarray,  # [B, Sk, Hkv, hd]
    v: jnp.ndarray,  # [B, Sk, Hkv, hd]
    *,
    causal: bool = True,
    q_offset: int = 0,
    segment_ids: Optional[jnp.ndarray] = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    bwd_block_q: Optional[int] = None,
    bwd_block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Flash attention; same contract as ``dense_attention``.

    ``q_offset`` must be a static python int on this path (the pallas
    grid's causal-skip predicate is specialised on it); the decode path
    with a traced offset should use ``dense_attention``.

    ``bwd_block_q``/``bwd_block_k`` tile the dq/dkv kernels
    independently of the forward (their 3-dot bodies have a different
    VMEM/VPU balance); None inherits the forward blocks.
    """
    if not isinstance(q_offset, int):
        raise TypeError(
            "flash_attention requires a static int q_offset; use "
            "dense_attention for traced offsets (KV-cache decode)."
        )
    B, sq, Hq, hd = q.shape
    _, sk, Hkv, _ = k.shape
    assert Hq % Hkv == 0, (Hq, Hkv)
    if interpret is None:
        interpret = _interpret_default()
    block_q = min(block_q, _ceil_to(sq, 128))
    block_k = min(block_k, _ceil_to(sk, 128))
    if bwd_block_q is not None:
        bwd_block_q = min(bwd_block_q, _ceil_to(sq, 128))
    if bwd_block_k is not None:
        bwd_block_k = min(bwd_block_k, _ceil_to(sk, 128))
    return _flash(
        q, k, v, segment_ids, causal, q_offset, sq, sk,
        block_q, block_k, bwd_block_q, bwd_block_k, interpret,
    )
