"""Rotary position embeddings (plain RoPE with configurable theta).

Angles are precomputed once per forward *outside* the layer scan so the
sin/cos tables are computed a single time and live in registers/VMEM
across all layers instead of being re-derived per layer.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_angles(
    positions: jnp.ndarray,  # [B, S] int32 absolute positions
    head_dim: int,
    theta: float = 500_000.0,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (sin, cos), each [B, S, head_dim//2], float32."""
    freq_exponents = jnp.arange(0, head_dim // 2, dtype=jnp.float32) / (head_dim // 2)
    inv_freq = theta**-freq_exponents  # [hd/2]
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # [B, S, hd/2]
    return jnp.sin(angles), jnp.cos(angles)


def apply_rope(
    x: jnp.ndarray,  # [B, S, H, hd]
    sin: jnp.ndarray,  # [B, S, hd/2]
    cos: jnp.ndarray,  # [B, S, hd/2]
) -> jnp.ndarray:
    dtype = x.dtype
    x = x.astype(jnp.float32)
    x1, x2 = jnp.split(x, 2, axis=-1)
    sin = sin[:, :, None, :]
    cos = cos[:, :, None, :]
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return out.astype(dtype)
