"""Pallas TPU grouped matmul (megablocks-style) for dropless MoE.

``gmm(lhs, rhs, group_offsets)`` multiplies row-groups of ``lhs [M, K]``
against per-group weight matrices ``rhs [E, K, N]``: rows in
``[group_offsets[e], group_offsets[e+1])`` use expert ``e``. Unlike the
one-hot (GShard) or capacity-table dispatch in ``models/moe.py``, there
is **no per-expert capacity**: the caller sorts token assignments by
expert (padding each group to a 128 multiple) and every assignment is
computed exactly once — zero drops, zero capacity over-compute. That
padding discipline is what lets the hot kernel skip all boundary
masking (see kernel A below).

The reference platform carries no kernels at all (SURVEY.md §2.4); this
is TPU-native capability on top of it, built for the v5e memory system:

- **Kernel A** (contraction dim K ≤ ~2048, e.g. the gate/up projection
  D→F): grid ``(n_tiles, m_tiles)`` with the *expert weight block
  resident in VMEM* across each group's row tiles (consecutive m tiles
  share a group, so Mosaic re-uses the fetched block) while 128-row lhs
  tiles stream through. K is not split, so there is no accumulator
  scratch. Requires every group boundary 128-aligned — then every lhs
  tile belongs to exactly one group and the kernel has no masks at all.
- **Kernel B** (K large, output dim N ≤ 4096, e.g. the down projection
  F→D and the backward dlhs of gate/up): K is split into ``bk`` blocks
  accumulated in a full-width ``(bm, N)`` f32 scratch. Row tiles are
  512 wide, so a tile may span several groups; the grid runs over
  (tile × group) *span pairs* with scalar-prefetched metadata, masking
  lhs rows outside the pair's group and writing the tile out once, on
  its last pair. Unwritten grid visits flush whatever the rotating
  VMEM buffer holds, so pad pairs target a dedicated dummy tile row
  (the output carries one extra ``bm`` row block the caller slices off).
- **tgmm** computes the weight gradient ``drhs[e] = lhsᵀ · doutᵀ`` per
  group with the same span-pair walk (k, n outer; pairs inner) and a
  per-group f32 accumulator; empty groups get a singleton pair that
  writes zeros (their block would otherwise be uninitialised HBM). With
  frozen expert banks (the QLoRA recipe) the whole tgmm is dead code —
  XLA removes it because ``grad`` never requests those cotangents.

``trans_rhs`` reads ``rhs`` stored as ``[E, N, K]`` (an expert weight
bank used "backwards", as in dlhs = dout · Wᵀ) without materialising a
256 MB transposed copy in HBM — the dot contracts the trailing axis of
both operands and Mosaic handles the in-VMEM layout.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name as _checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# group boundaries are padded to this (kernel A's row tile); kernel B's
# row tile must be a multiple of it
ALIGN = 128

DEFAULT_BM_B = 512
DEFAULT_BK_B = 1024
DEFAULT_BN_B = 1024
DEFAULT_BK_T = 512
DEFAULT_BN_T = 512
# kernel A's contraction limit: (128, K) lhs + (K, bn) rhs blocks must
# double-buffer in ~16MB VMEM
MAX_K_A = 4096
# kernel B's scratch is (bm, N) f32
MAX_N_B = 4096


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _ceil_to(x: int, m: int) -> int:
    return -(-x // m) * m


def _pick_bn(n: int, budget: int) -> int:
    """Largest lane-aligned divisor of ``n`` within the VMEM column
    budget (trace-time loop, ≤ n/128 iterations); sub-ALIGN n (tiny
    test shapes) runs as one block."""
    bn = n
    for cand in range(ALIGN, min(n, budget) + 1, ALIGN):
        if n % cand == 0:
            bn = cand
    return bn


def _group_of_tile(m: int, group_offsets) -> jnp.ndarray:
    """Expert id of each ALIGN-row tile — ALIGN-aligned group
    boundaries guarantee each tile has exactly one."""
    tiles = jnp.arange(m // ALIGN, dtype=jnp.int32) * ALIGN
    return (
        jnp.searchsorted(group_offsets[1:-1], tiles, side="right")
        .astype(jnp.int32)
    )


# ---------------------------------------------------------------------------
# span-pair metadata (traced; E- and tile-count-sized arrays only)


def span_pairs(group_offsets: jnp.ndarray, m: int, bm: int,
               include_empty: bool) -> dict[str, jnp.ndarray]:
    """Tile×group span pairs for kernels that walk ``bm``-row tiles.

    ``group_offsets`` is [E+1] int32 with ``offsets[0]=0``,
    ``offsets[E]=m``, every entry ALIGN-aligned. A *pair* is a (row
    tile, group) intersection; listing pairs in offset order makes
    consecutive pairs of one tile adjacent (so output-buffer revisits
    are consecutive — a Mosaic requirement) and consecutive pairs of
    one group adjacent (so weight blocks stay resident).

    Static length: T + E pairs (T = m // bm), padded with inert pairs.
    Inert pads REUSE the last real pair's block indices and carry
    ``live = 0``: identical consecutive indices mean Mosaic's pipeliner
    issues no DMA for them, and the kernels' ``pl.when(live)`` guard
    skips their dots — before this, every pad burned a full fetch plus
    a masked dot, E/(T+E) ≈ 19% of the grid at the 8×1B kernel-B shape
    (measured: the bulk of kernel B's gap to the dense padded-dot
    bound, ``loadtest/gmm_microbench.py``). With ``include_empty``,
    zero-size groups still get a live pair (tgmm must write zeros to
    their gradient block); without it they are skipped (kernel B
    writes rows, and empty groups own none).

    Returns int32 arrays of length L = T + E:
      ``tile``   lhs/out row-tile index (pads: the last real pair's)
      ``otile``  kernel B's out row tile (pads: the last real pair's —
                 revisits without a write are free; the dummy row T is
                 used only when there are no real pairs at all)
      ``group``  expert id (pads: the last real pair's, for the fetch)
      ``live``   1 on real pairs — the kernels' compute guard
      ``write``  1 on the last pair of each real tile (kernel B writes)
      ``gfirst``/``glast`` group-accumulation boundaries (tgmm; 0 on
                 pads so a pad can never re-write a real block)
    """
    E = group_offsets.shape[0] - 1
    T = m // bm
    L = T + E
    starts = group_offsets[:-1]
    ends = group_offsets[1:]
    sizes = ends - starts
    nonempty = sizes > 0
    # tiles spanned by each group (0 for empty groups unless included)
    first_tile = starts // bm
    last_tile = jnp.where(nonempty, (ends - 1) // bm, first_tile)
    ntiles = jnp.where(nonempty, last_tile - first_tile + 1, 0)
    if include_empty:
        ntiles = jnp.maximum(ntiles, 1)
    cum = jnp.cumsum(ntiles)  # pairs before group e+1
    total = cum[-1]
    i = jnp.arange(L, dtype=jnp.int32)
    # group of pair i: first g with cum[g] > i; pads get E
    group = jnp.searchsorted(cum, i, side="right").astype(jnp.int32)
    pad = i >= total
    group_c = jnp.minimum(group, E - 1)
    within = i - jnp.where(group_c > 0, cum[group_c - 1], 0)
    tile = jnp.clip(first_tile[group_c] + within, 0, T - 1)
    group = jnp.where(pad, E, group_c)
    # write: last pair of its tile — next pair has a different tile (or
    # is a pad). Pads never write. Empty-group pairs sit at their
    # offset's tile but their mask is empty; they must not steal the
    # write flag, so exclude them from tile ownership.
    owns = ~pad & (sizes[group_c] > 0)
    nxt_tile = jnp.concatenate([tile[1:], jnp.full((1,), -1, jnp.int32)])
    nxt_owns = jnp.concatenate([owns[1:], jnp.zeros((1,), bool)])
    write = (owns & ((nxt_tile != tile) | ~nxt_owns)).astype(jnp.int32)
    otile = jnp.where(owns, tile, T).astype(jnp.int32)
    # group accumulation boundaries (tgmm): compare neighbour groups
    # BEFORE the pad remap below (a pad's fetch-group aliases the last
    # real pair's, which must not clear that pair's glast)
    prv_group = jnp.concatenate([jnp.full((1,), -1, jnp.int32), group[:-1]])
    nxt_group = jnp.concatenate([group[1:], jnp.full((1,), -2, jnp.int32)])
    live = (~pad).astype(jnp.int32)
    gfirst = (group != prv_group).astype(jnp.int32) * live
    glast = (group != nxt_group).astype(jnp.int32) * live
    # pads alias the last real pair's indices: unchanged consecutive
    # block indices cost no DMA, and live=0 skips their compute
    last = jnp.maximum(total - 1, 0)

    def pad_alias(arr):
        return jnp.where(pad, arr[last], arr)

    return {
        "tile": pad_alias(tile).astype(jnp.int32),
        "otile": pad_alias(otile).astype(jnp.int32),
        "group": pad_alias(group).astype(jnp.int32),
        "live": live,
        "write": write,
        "gfirst": gfirst,
        "glast": glast,
    }


# ---------------------------------------------------------------------------
# kernel A: small K, rhs-resident, maskless


def _gmm_a_kernel(gid_ref, lhs_ref, rhs_ref, out_ref, *, trans_rhs):
    rhs = rhs_ref[0].astype(lhs_ref.dtype)
    dn = (((1,), (1,)), ((), ())) if trans_rhs else (((1,), (0,)), ((), ()))
    acc = jax.lax.dot_general(
        lhs_ref[...], rhs, dn, preferred_element_type=jnp.float32
    )
    out_ref[...] = acc.astype(out_ref.dtype)


def _gmm_a_kernel_q(gid_ref, lhs_ref, rhs_ref, scale_ref, out_ref, *,
                    trans_rhs):
    """int8 bank variant: the per-output-channel scale (bank's last
    axis — ``models/quant.py``) factors out of the contraction, so the
    weight block is convert-only and one cheap vector multiply lands
    on the f32 accumulator (non-trans) or the streamed lhs tile
    (trans, where the scaled axis is the contraction)."""
    rhs = rhs_ref[0].astype(lhs_ref.dtype)
    dn = (((1,), (1,)), ((), ())) if trans_rhs else (((1,), (0,)), ((), ()))
    if trans_rhs:
        lhs = lhs_ref[...] * scale_ref[0, 0][None, :].astype(lhs_ref.dtype)
        acc = jax.lax.dot_general(
            lhs, rhs, dn, preferred_element_type=jnp.float32
        )
    else:
        acc = jax.lax.dot_general(
            lhs_ref[...], rhs, dn, preferred_element_type=jnp.float32
        )
        acc = acc * scale_ref[0, 0][None, :]
    out_ref[...] = acc.astype(out_ref.dtype)


def _gmm_a(lhs, rhs, group_of_tile, *, trans_rhs, interpret,
           scale=None, base=None):
    m, k = lhs.shape
    n = rhs.shape[1] if trans_rhs else rhs.shape[2]
    # resident weight block ≤4MB so it double-buffers beside the
    # streaming lhs tiles in ~16MB VMEM — int8 banks fit 2× the
    # columns. Largest lane-aligned divisor of n that fits the budget
    # (trace-time loop, ≤ n/128 iterations).
    budget = 4 * 1024 * 1024 // (k * rhs.dtype.itemsize)
    bn = _pick_bn(n, budget)
    assert n % bn == 0, f"N={n} has no legal block under K={k}"
    T = m // ALIGN
    rhs_block = (1, bn, k) if trans_rhs else (1, k, bn)
    # stacked-bank mode (``base``): rhs holds every layer's expert
    # banks [L·E, ...] and the fetch index offsets by the layer's
    # group base — the scan never dynamic-slices a 100+MB bank copy
    # per layer just to feed the custom call (see models/moe.py)
    pref = [group_of_tile] if base is None else [group_of_tile, base]

    def _g(p, t):
        g = p[0][t]
        return g if base is None else p[1][0] + g

    rhs_idx = (
        (lambda ni, t, *p: (_g(p, t), ni, 0))
        if trans_rhs
        else (lambda ni, t, *p: (_g(p, t), 0, ni))
    )
    grid = (n // bn, T)
    in_specs = [
        pl.BlockSpec((ALIGN, k), lambda ni, t, *p: (t, 0)),
        pl.BlockSpec(rhs_block, rhs_idx),
    ]
    operands = pref + [lhs, rhs]
    nker = len(pref)

    def strip(fn):
        # kernel positional args: prefetch refs first — drop them all
        # (bodies never read the ids; index maps consume them)
        def wrapped(*refs):
            return fn(refs[0], *refs[nker:])
        return wrapped

    if scale is None:
        kernel = strip(functools.partial(_gmm_a_kernel, trans_rhs=trans_rhs))
    else:
        kernel = strip(functools.partial(_gmm_a_kernel_q, trans_rhs=trans_rhs))
        scale_block = (1, 1, k) if trans_rhs else (1, 1, bn)
        scale_idx = (
            (lambda ni, t, *p: (_g(p, t), 0, 0))
            if trans_rhs
            else (lambda ni, t, *p: (_g(p, t), 0, ni))
        )
        in_specs.append(pl.BlockSpec(scale_block, scale_idx))
        operands.append(scale)
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=len(pref),
            grid=grid,
            in_specs=in_specs,
            out_specs=pl.BlockSpec((ALIGN, bn), lambda ni, t, *p: (t, ni)),
        ),
        out_shape=jax.ShapeDtypeStruct((m, n), lhs.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(*operands)


# ---------------------------------------------------------------------------
# kernel B: split K, span pairs, full-width scratch


def _gmm_b_kernel(
    tile_ref, otile_ref, group_ref, write_ref, live_ref, offs_ref,
    lhs_ref, rhs_ref, *rest, bm, bn, nk, trans_rhs,
):
    if len(rest) == 3:
        scale_ref, out_ref, acc_ref = rest
    else:
        (out_ref, acc_ref), scale_ref = rest, None
    """Grid is (pairs, n, k) with k innermost: for one (pair, n-tile)
    the k loop accumulates into scratch slice ``acc_ref[ni]`` and the
    out block index stays constant, so every output block's visits are
    consecutive and it is written exactly once (on its tile's last
    pair, final k step). The scratch's leading axis is the n-tile —
    indexing it is a major-dim dynamic slice (lane-dim dynamic slices
    are not a Mosaic-friendly pattern); all n slices persist across
    pairs so a boundary tile's earlier pairs survive until the
    tile-closing pair merges and writes. Pad pairs (live = 0) alias
    the last real pair's block indices, so they cost neither a DMA
    nor (guarded below) a dot."""
    i = pl.program_id(0)
    ni = pl.program_id(1)
    ki = pl.program_id(2)
    g = group_ref[i]
    t = tile_ref[i]
    live = live_ref[i] == 1
    start = offs_ref[g]
    end = offs_ref[g + 1]
    # most pairs cover their whole tile (boundary pairs are ≤E of
    # T+E); the full case skips the row mask select and the masked
    # accumulator merge — VPU work between the MXU dots
    full = jnp.logical_and(start <= t * bm, end >= (t + 1) * bm)

    def _dot(lhs):
        if scale_ref is not None and trans_rhs:
            # int8 bank used backwards: scaled axis is the contraction
            lhs = lhs * scale_ref[0, 0][None, :].astype(lhs.dtype)
        rhs = rhs_ref[0].astype(lhs_ref.dtype)
        dn = (
            (((1,), (1,)), ((), ()))
            if trans_rhs
            else (((1,), (0,)), ((), ()))
        )
        return jax.lax.dot_general(
            lhs, rhs, dn, preferred_element_type=jnp.float32
        )

    @pl.when(jnp.logical_and(live, full))
    def _full():
        d = _dot(lhs_ref[...])

        @pl.when(ki == 0)
        def _init():
            acc_ref[ni] = d

        @pl.when(ki > 0)
        def _accum():
            acc_ref[ni] = acc_ref[ni] + d

    @pl.when(jnp.logical_and(live, jnp.logical_not(full)))
    def _partial():
        rows = t * bm + jax.lax.broadcasted_iota(jnp.int32, (bm, 1), 0)
        mask = jnp.logical_and(rows >= start, rows < end)
        d = _dot(jnp.where(mask, lhs_ref[...], 0).astype(lhs_ref.dtype))

        @pl.when(ki == 0)
        def _init():
            # keep earlier pairs' rows of this tile; lhs is already
            # zeroed outside the mask so d carries no stale part
            acc_ref[ni] = jnp.where(mask, d, acc_ref[ni])

        @pl.when(ki > 0)
        def _accum():
            acc_ref[ni] = acc_ref[ni] + d

    @pl.when(jnp.logical_and(ki == nk - 1, write_ref[i] == 1))
    def _write():
        acc = acc_ref[ni]
        if scale_ref is not None and not trans_rhs:
            # int8 bank forwards: per-output-column scale on the f32
            # accumulator, once per written block
            acc = acc * scale_ref[0, 0][None, :]
        out_ref[...] = acc.astype(out_ref.dtype)


def _gmm_b(lhs, rhs, pairs, group_offsets, *, trans_rhs, bm, bk, bn,
           interpret, scale=None, base=None):
    m, k = lhs.shape
    E = group_offsets.shape[0] - 1  # layer-LOCAL group count
    n = rhs.shape[1] if trans_rhs else rhs.shape[2]
    bk = min(bk, k)
    bn = min(bn, n)
    assert k % bk == 0 and n % bn == 0, (k, bk, n, bn)
    nk = k // bk
    L = pairs["tile"].shape[0]
    rhs_block = (1, bn, bk) if trans_rhs else (1, bk, bn)

    # pad pairs alias a real pair's group (span_pairs) — the clamp
    # stays as belt-and-braces against an out-of-bounds fetch (a hard
    # TPU fault). Stacked-bank mode (``base``, models/moe.py): rhs is
    # [L·E, ...] and the fetch offsets into this layer's bank span.
    def _g(p, i):
        g = jnp.minimum(p[2][i], E - 1)
        return g if base is None else p[6][0] + g

    rhs_idx = (
        (lambda i, ni, ki, *p: (_g(p, i), ni, ki))
        if trans_rhs
        else (lambda i, ni, ki, *p: (_g(p, i), ki, ni))
    )
    # offsets extended so the dummy group E is empty: offs[E+1] = offs[E]
    offs = jnp.concatenate([group_offsets, group_offsets[-1:]])
    in_specs = [
        pl.BlockSpec(
            (bm, bk), lambda i, ni, ki, *p: (p[0][i], ki)
        ),
        pl.BlockSpec(rhs_block, rhs_idx),
    ]
    operands = [
        pairs["tile"], pairs["otile"], pairs["group"], pairs["write"],
        pairs["live"], offs,
    ] + ([] if base is None else [base]) + [lhs, rhs]
    npref = 6 if base is None else 7

    def strip(fn):
        # bodies read the first six prefetch refs; drop the base ref
        def wrapped(*refs):
            return fn(*refs[:6], *refs[npref:])
        return wrapped

    if scale is not None:
        # scaled axis is the bank's last: output columns (non-trans,
        # applied at write) or the contraction (trans, prescaled)
        scale_block = (1, 1, bk) if trans_rhs else (1, 1, bn)
        scale_idx = (
            (lambda i, ni, ki, *p: (_g(p, i), 0, ki))
            if trans_rhs
            else (lambda i, ni, ki, *p: (_g(p, i), 0, ni))
        )
        in_specs.append(pl.BlockSpec(scale_block, scale_idx))
        operands.append(scale)
    out = pl.pallas_call(
        strip(functools.partial(
            _gmm_b_kernel, bm=bm, bn=bn, nk=nk, trans_rhs=trans_rhs
        )),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=npref,
            grid=(L, n // bn, nk),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (bm, bn), lambda i, ni, ki, *p: (p[1][i], ni)
            ),
            scratch_shapes=[pltpu.VMEM((n // bn, bm, bn), jnp.float32)],
        ),
        # one extra bm-row dummy block absorbs inert pairs' buffer flushes
        out_shape=jax.ShapeDtypeStruct((m + bm, n), lhs.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(*operands)
    return out[:m]


# ---------------------------------------------------------------------------
# tgmm: per-group weight gradient


def _tgmm_kernel(
    tile_ref, group_ref, gfirst_ref, glast_ref, live_ref, offs_ref,
    lhs_ref, dout_ref, out_ref, acc_ref, *, bm,
):
    i = pl.program_id(2)
    g = group_ref[i]
    t = tile_ref[i]
    start = offs_ref[g]
    end = offs_ref[g + 1]

    # pad pairs (live = 0, aliased indices — no DMA) must not touch
    # the accumulator: their gfirst/glast are 0, so an unguarded body
    # would ACCUMULATE a stale dot into the last real group
    @pl.when(live_ref[i] == 1)
    def _compute():
        rows = t * bm + jax.lax.broadcasted_iota(jnp.int32, (bm, 1), 0)
        mask = jnp.logical_and(rows >= start, rows < end)
        lhs = jnp.where(mask, lhs_ref[...], 0).astype(lhs_ref.dtype)
        # (bk, bn) = lhsᵀ · dout, contracting the bm rows
        d = jax.lax.dot_general(
            lhs, dout_ref[...], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

        @pl.when(gfirst_ref[i] == 1)
        def _init():
            acc_ref[...] = d

        @pl.when(gfirst_ref[i] == 0)
        def _accum():
            acc_ref[...] = acc_ref[...] + d

        @pl.when(glast_ref[i] == 1)
        def _write():
            out_ref[0] = acc_ref[...].astype(out_ref.dtype)


def _tgmm(lhs, dout, pairs, group_offsets, *, bm, bk, bn, interpret):
    m, k = lhs.shape
    n = dout.shape[1]
    bk = min(bk, k)
    bn = min(bn, n)
    assert k % bk == 0 and n % bn == 0, (k, bk, n, bn)
    E = group_offsets.shape[0] - 1
    L = pairs["tile"].shape[0]
    offs = jnp.concatenate([group_offsets, group_offsets[-1:]])
    out = pl.pallas_call(
        functools.partial(_tgmm_kernel, bm=bm),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=6,
            grid=(k // bk, n // bn, L),
            in_specs=[
                pl.BlockSpec(
                    (bm, bk),
                    lambda ki, ni, i, t, g, gf, gl, lv, o: (t[i], ki),
                ),
                pl.BlockSpec(
                    (bm, bn),
                    lambda ki, ni, i, t, g, gf, gl, lv, o: (t[i], ni),
                ),
            ],
            out_specs=pl.BlockSpec(
                (1, bk, bn),
                lambda ki, ni, i, t, g, gf, gl, lv, o: (g[i], ki, ni),
            ),
            scratch_shapes=[pltpu.VMEM((bk, bn), jnp.float32)],
        ),
        # dummy group E absorbs the no-real-pairs degenerate flush
        out_shape=jax.ShapeDtypeStruct((E + 1, k, n), dout.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(
        pairs["tile"], pairs["group"], pairs["gfirst"], pairs["glast"],
        pairs["live"], offs, lhs, dout,
    )
    return out[:E]


# ---------------------------------------------------------------------------
# public op


def _gmm_fwd_impl(lhs, rhs, group_offsets, *, trans_rhs, interpret,
                  scale=None, base=None):
    m, k = lhs.shape
    n = rhs.shape[1] if trans_rhs else rhs.shape[2]
    assert m % DEFAULT_BM_B == 0, f"M={m} must be a {DEFAULT_BM_B} multiple"
    # kernel A holds a (K, bn) weight block double-buffered in ~16MB
    # VMEM; scale the K limit down for wider dtypes (f32 tests) so a
    # legal-on-CPU shape can't oversubscribe VMEM on hardware
    max_k_a = MAX_K_A * 2 // max(lhs.dtype.itemsize, rhs.dtype.itemsize)
    if k <= max_k_a:
        return _gmm_a(
            lhs, rhs, _group_of_tile(m, group_offsets),
            trans_rhs=trans_rhs, interpret=interpret, scale=scale,
            base=base,
        )
    if n > MAX_N_B:
        raise NotImplementedError(
            f"gmm: K={k} > {MAX_K_A} and N={n} > {MAX_N_B} — no kernel "
            "shape fits VMEM; reshape the problem"
        )
    pairs = span_pairs(group_offsets, m, DEFAULT_BM_B, include_empty=False)
    return _gmm_b(
        lhs, rhs, pairs, group_offsets, trans_rhs=trans_rhs,
        bm=DEFAULT_BM_B, bk=DEFAULT_BK_B, bn=DEFAULT_BN_B,
        interpret=interpret, scale=scale, base=base,
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def gmm(lhs, rhs, group_offsets, trans_rhs=False,
        interpret: Optional[bool] = None, scale=None, group_base=None):
    """Grouped matmul: rows ``[offsets[e], offsets[e+1])`` of ``lhs``
    through ``rhs[e]``. Offsets must be ALIGN-aligned with
    ``offsets[0] = 0`` and ``offsets[E] = M`` (the caller's sort pads
    groups — ``models/moe.py`` ``route_sorted``). Returns [M, N] in
    ``lhs.dtype``; differentiable in ``lhs`` and ``rhs``.

    ``scale`` enables int8-native banks: ``rhs`` int8 with the
    per-output-channel scale [E, 1, bank-last-axis] from
    ``models/quant.py`` — the kernel reads half the weight bytes and
    never materialises a dequantized bank in HBM. Weight gradients are
    not defined through the quantized path (frozen banks — QLoRA).

    ``group_base`` (stacked-bank mode, int32 [1]): ``rhs``/``scale``
    hold EVERY layer's banks ([L·E, ...]) and fetch indices offset by
    this layer's first group — so a per-layer scan never materialises
    a bank copy just to feed the kernel. Frozen (``scale``) banks only:
    the weight-gradient tgmm has no stacked form."""
    if interpret is None:
        interpret = _interpret_default()
    if group_base is not None and scale is None:
        raise NotImplementedError(
            "gmm: group_base (stacked banks) requires int8 frozen "
            "banks (scale) — no stacked weight-gradient path"
        )
    return _gmm_fwd_impl(
        lhs, rhs, group_offsets, trans_rhs=trans_rhs, interpret=interpret,
        scale=scale, base=group_base,
    )


def _gmm_fwd(lhs, rhs, group_offsets, trans_rhs, interpret, scale,
             group_base):
    if interpret is None:
        interpret = _interpret_default()
    out = _gmm_fwd_impl(
        lhs, rhs, group_offsets, trans_rhs=trans_rhs, interpret=interpret,
        scale=scale, base=group_base,
    )
    return out, (lhs, rhs, group_offsets, scale, group_base)


def _gmm_bwd(trans_rhs, interpret, res, dout):
    lhs, rhs, group_offsets, scale, group_base = res
    if interpret is None:
        interpret = _interpret_default()
    # dlhs = dout · rhsᵀ — the same grouped matmul with rhs read
    # "the other way", so the two trans_rhs variants are each other's
    # backward and no transposed weight copy ever hits HBM
    dlhs = _gmm_fwd_impl(
        dout.astype(lhs.dtype), rhs, group_offsets,
        trans_rhs=not trans_rhs, interpret=interpret, scale=scale,
        base=group_base,
    )
    if scale is not None:
        # int8 banks are frozen (QLoRA): no weight cotangents
        return (dlhs, None, None, jnp.zeros_like(scale), None)
    E = rhs.shape[0]
    m = lhs.shape[0]
    pairs = span_pairs(group_offsets, m, DEFAULT_BM_B, include_empty=True)
    if trans_rhs:
        # rhs layout [E, N, K]: drhs[e] = doutᵀ · lhs
        drhs = _tgmm(
            dout.astype(lhs.dtype), lhs, pairs, group_offsets,
            bm=DEFAULT_BM_B, bk=DEFAULT_BK_T, bn=DEFAULT_BN_T,
            interpret=interpret,
        ).astype(rhs.dtype)
    else:
        drhs = _tgmm(
            lhs, dout.astype(lhs.dtype), pairs, group_offsets,
            bm=DEFAULT_BM_B, bk=DEFAULT_BK_T, bn=DEFAULT_BN_T,
            interpret=interpret,
        ).astype(rhs.dtype)
    return dlhs, drhs, None, None, None


gmm.defvjp(_gmm_fwd, _gmm_bwd)


# ---------------------------------------------------------------------------
# fused SwiGLU grouped matmul: h = silu(x·Wg) ⊙ (x·Wu) in one kernel
# ---------------------------------------------------------------------------


def _swiglu_fwd_kernel(gid_ref, *rest, has_base):
    """Kernel-A-shaped fused gate+up: both expert weight blocks stay
    resident across the group's 128-row lhs tiles, the silu·mul
    epilogue runs on the f32 accumulators in VMEM, and only h (plus g,
    which the QLoRA remat policy pins as "moe_g" — measured CHEAPER
    than recomputing g with an extra backward dot, despite the scan
    residual's stacking DUS) ever reach HBM; the separate u tensor and
    the standalone silu fusion's passes disappear."""
    if has_base:
        _base, lhs_ref, wg_ref, wu_ref, sg_ref, su_ref, h_ref, g_ref = rest
    else:
        lhs_ref, wg_ref, wu_ref, sg_ref, su_ref, h_ref, g_ref = rest
    lhs = lhs_ref[...]
    g = jax.lax.dot_general(
        lhs, wg_ref[0].astype(lhs.dtype), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * sg_ref[0, 0][None, :]
    u = jax.lax.dot_general(
        lhs, wu_ref[0].astype(lhs.dtype), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * su_ref[0, 0][None, :]
    h = jax.nn.silu(g) * u
    h_ref[...] = h.astype(h_ref.dtype)
    g_ref[...] = g.astype(g_ref.dtype)


def _swiglu_bwd_kernel(gid_ref, *rest, has_base):
    """Backward fusion: recompute u (the one matmul the moe_g pin
    leaves — recomputing g too was measured slower than reading the
    pin), then the dsilu epilogue — dg = dh·u·silu'(g),
    du = dh·silu(g) — on the in-VMEM tiles. Replaces a standalone
    u-recompute kernel plus two [M, F] dsilu fusions."""
    if has_base:
        _base, lhs_ref, wu_ref, su_ref, g_ref, dh_ref, dg_ref, du_ref = rest
    else:
        lhs_ref, wu_ref, su_ref, g_ref, dh_ref, dg_ref, du_ref = rest
    lhs = lhs_ref[...]
    u = jax.lax.dot_general(
        lhs, wu_ref[0].astype(lhs.dtype), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * su_ref[0, 0][None, :]
    g = g_ref[...].astype(jnp.float32)
    dh = dh_ref[...].astype(jnp.float32)
    sig = jax.nn.sigmoid(g)
    dg_ref[...] = (dh * u * (sig * (1.0 + g * (1.0 - sig)))).astype(
        dg_ref.dtype
    )
    du_ref[...] = (dh * (g * sig)).astype(du_ref.dtype)


def _swiglu_specs(m, k, n, group_of_tile, base):
    """Shared grid/spec plumbing for the two fused kernels: kernel-A
    walk (n-tiles outer, 128-row lhs tiles inner) with the column
    budget halved so the forward's TWO weight blocks double-buffer.
    int8 banks only (itemsize 1 in the budget — enforced by
    swiglu_gmm's signature taking q/scale pairs)."""
    budget = 4 * 1024 * 1024 // (k * 1) // 2  # two resident int8 blocks
    if k > MAX_K_A * 2 or budget < ALIGN:
        # mirror gmm's explicit failure instead of silently resident-
        # loading an oversized [K, N] bank (a Mosaic VMEM fault)
        raise NotImplementedError(
            f"swiglu_gmm: K={k} exceeds the fused kernel-A VMEM "
            "budget; use separate gmm calls (kernel B) for this shape"
        )
    bn = _pick_bn(n, budget)
    T = m // ALIGN
    pref = [group_of_tile] if base is None else [group_of_tile, base]

    def _g(p, t):
        g = p[0][t]
        return g if base is None else p[1][0] + g

    lhs_spec = pl.BlockSpec((ALIGN, k), lambda ni, t, *p: (t, 0))
    w_spec = pl.BlockSpec((1, k, bn), lambda ni, t, *p: (_g(p, t), 0, ni))
    s_spec = pl.BlockSpec((1, 1, bn), lambda ni, t, *p: (_g(p, t), 0, ni))
    row_spec = pl.BlockSpec((ALIGN, bn), lambda ni, t, *p: (t, ni))
    return pref, bn, T, lhs_spec, w_spec, s_spec, row_spec


def _swiglu_fwd_impl(lhs, wg, wu, sg, su, group_of_tile, base, interpret):
    m, k = lhs.shape
    n = wg.shape[2]
    pref, bn, T, lhs_spec, w_spec, s_spec, row_spec = _swiglu_specs(
        m, k, n, group_of_tile, base
    )
    return pl.pallas_call(
        functools.partial(_swiglu_fwd_kernel, has_base=base is not None),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=len(pref),
            grid=(n // bn, T),
            in_specs=[lhs_spec, w_spec, w_spec, s_spec, s_spec],
            out_specs=(row_spec, row_spec),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((m, n), lhs.dtype),
            jax.ShapeDtypeStruct((m, n), lhs.dtype),
        ),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(*pref, lhs, wg, wu, sg, su)


def _swiglu_bwd_impl(lhs, wu, su, g, dh, group_of_tile, base, interpret):
    m, k = lhs.shape
    n = wu.shape[2]
    pref, bn, T, lhs_spec, w_spec, s_spec, row_spec = _swiglu_specs(
        m, k, n, group_of_tile, base
    )
    return pl.pallas_call(
        functools.partial(_swiglu_bwd_kernel, has_base=base is not None),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=len(pref),
            grid=(n // bn, T),
            in_specs=[lhs_spec, w_spec, s_spec, row_spec, row_spec],
            out_specs=(row_spec, row_spec),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((m, n), lhs.dtype),
            jax.ShapeDtypeStruct((m, n), lhs.dtype),
        ),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(*pref, lhs, wu, su, g, dh)


@functools.partial(jax.custom_vjp, nondiff_argnums=(7,))
def swiglu_gmm(lhs, wg_q, wu_q, sg, su, group_offsets, group_base,
               interpret=None):
    """Fused grouped SwiGLU for int8 expert banks:
    ``h[r] = silu(x[r]·Wg[e]) ⊙ (x[r]·Wu[e])`` for rows in expert e's
    group, plus the gate pre-activation ``g`` as a second output. The
    vjp names its g residual "moe_g", so the QLoRA remat policy pins
    it and the backward recomputes ONLY u, fused with the dsilu
    epilogue (both measured: pinning g beats recomputing it, and the
    fused epilogue beats standalone [M, F] dsilu fusions). K ≤ the
    kernel-A budget only (the MoE D→F shape); frozen banks (no weight
    grads). Returns ``(h, g)``.
    """
    if interpret is None:
        interpret = _interpret_default()
    return _swiglu_fwd_fn(
        lhs, wg_q, wu_q, sg, su, group_offsets, group_base, interpret
    )


def _swiglu_fwd_fn(lhs, wg_q, wu_q, sg, su, group_offsets, group_base,
                   interpret):
    assert lhs.shape[0] % ALIGN == 0
    return _swiglu_fwd_impl(
        lhs, wg_q, wu_q, sg, su,
        _group_of_tile(lhs.shape[0], group_offsets), group_base,
        interpret,
    )


def _swiglu_vjp_fwd(lhs, wg_q, wu_q, sg, su, group_offsets, group_base,
                    interpret):
    if interpret is None:
        interpret = _interpret_default()
    h, g = _swiglu_fwd_fn(
        lhs, wg_q, wu_q, sg, su, group_offsets, group_base, interpret
    )
    # name the RESIDUAL itself: under save_only_these_names("moe_g")
    # the backward then reads the pinned value instead of re-running
    # the forward kernel (naming only the returned g would pin a value
    # the backward never consumes)
    g_saved = _checkpoint_name(g, "moe_g")
    return (h, g), (
        lhs, wg_q, wu_q, sg, su, group_offsets, group_base, g_saved
    )


def _swiglu_vjp_bwd(interpret, res, cts):
    lhs, wg_q, wu_q, sg, su, group_offsets, group_base, g = res
    dh, dg_out = cts
    if interpret is None:
        interpret = _interpret_default()
    dg, du = _swiglu_bwd_impl(
        lhs, wu_q, su, g, dh.astype(lhs.dtype),
        _group_of_tile(lhs.shape[0], group_offsets), group_base,
        interpret,
    )
    # g is also an OUTPUT (for the remat pin); fold any cotangent that
    # arrives on it into the pre-activation gradient (normally zero —
    # nothing consumes g downstream — and XLA DCEs the add)
    dg = dg + dg_out.astype(dg.dtype)
    # dlhs through both frozen banks, read "backwards" (trans) — the
    # same kernel-B/A machinery every gmm backward uses
    dlhs = _gmm_fwd_impl(
        dg, wg_q, group_offsets, trans_rhs=True, interpret=interpret,
        scale=sg, base=group_base,
    ) + _gmm_fwd_impl(
        du, wu_q, group_offsets, trans_rhs=True, interpret=interpret,
        scale=su, base=group_base,
    )
    return (dlhs, None, None, jnp.zeros_like(sg), jnp.zeros_like(su),
            None, None)


swiglu_gmm.defvjp(_swiglu_vjp_fwd, _swiglu_vjp_bwd)
