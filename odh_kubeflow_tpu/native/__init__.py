"""Native (C++) runtime components, loaded via ctypes.

The TPU compute path is JAX/XLA; these are the host-side hot loops
around it. Each component ships as a single .cpp with a plain C ABI
(this image has no pybind11) plus a ctypes wrapper here. The shared
object is built on first use with the system g++ and cached next to the
source; everything degrades gracefully to the pure-Python
implementation when no compiler is available (``available()`` →
False), so the package has no hard native dependency.

Build explicitly with ``make native`` (top-level Makefile) or let the
first import compile lazily.
"""

from __future__ import annotations

import ctypes
import os
import shutil
import subprocess
import tempfile
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(__file__)
_SRC = os.path.join(_DIR, "packer.cpp")
_SO = os.path.join(_DIR, "libodhkf_native.so")
_JT_SRC = os.path.join(_DIR, "jsontree.cpp")
_JT_SO = os.path.join(_DIR, "_odhkf_jsontree.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False
_jt_mod = None
_jt_tried = False


def _compile(src: str, out: str, extra: list[str], force: bool) -> Optional[str]:
    if not force and os.path.exists(out):
        if os.path.getmtime(out) >= os.path.getmtime(src):
            return out
    cxx = shutil.which("g++") or shutil.which("c++") or shutil.which("clang++")
    if cxx is None:
        return None
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_DIR)
    os.close(fd)
    try:
        subprocess.run(
            [cxx, "-O3", "-shared", "-fPIC", "-std=c++17", *extra, src, "-o", tmp],
            check=True,
            capture_output=True,
        )
        os.replace(tmp, out)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return out


def build(force: bool = False) -> Optional[str]:
    """Compile the native components; returns the packer .so path (or
    None when no compiler exists). Compiles into temp files then
    atomically renames, so concurrent builders race benignly. Also
    builds the jsontree CPython extension (machinery's hot deepcopy);
    its failure is non-fatal — everything degrades to Python."""
    import sysconfig

    try:
        _compile(
            _JT_SRC,
            _JT_SO,
            ["-I" + sysconfig.get_paths()["include"]],
            force,
        )
    except (OSError, subprocess.CalledProcessError):
        pass
    return _compile(_SRC, _SO, [], force)


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        try:
            so = build()
            if so is None:
                _load_failed = True
                return None
            lib = ctypes.CDLL(so)
            lib.pack_documents_c.restype = ctypes.c_long
            lib.pack_documents_c.argtypes = [
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.c_long,
                ctypes.c_long,
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_float),
                ctypes.c_long,
            ]
            _lib = lib
        except (OSError, subprocess.CalledProcessError):
            _load_failed = True
    return _lib


def available() -> bool:
    return _load() is not None


def _jsontree_module():
    """The lazily built+loaded jsontree extension module, or None.
    One compile+load serves both entry points (deepcopy and dumps)."""
    global _jt_mod, _jt_tried
    if _jt_tried:
        return _jt_mod
    with _lock:
        if _jt_tried:
            return _jt_mod
        try:
            import sysconfig

            so = _compile(
                _JT_SRC,
                _JT_SO,
                ["-I" + sysconfig.get_paths()["include"]],
                False,
            )
            if so is not None:
                from importlib.machinery import ExtensionFileLoader
                from importlib.util import module_from_spec, spec_from_loader

                loader = ExtensionFileLoader("_odhkf_jsontree", so)
                spec = spec_from_loader("_odhkf_jsontree", loader)
                mod = module_from_spec(spec)
                loader.exec_module(mod)
                _jt_mod = mod
        except (OSError, subprocess.CalledProcessError, ImportError):
            _jt_mod = None
        _jt_tried = True
    return _jt_mod


def jsontree_deepcopy():
    """The C deepcopy for JSON-shaped trees (machinery/objects.py's
    hot path), or None when it can't build/load. Lazy-built and cached
    like the packer; parity with the Python fallback is contract-tested
    in tests/test_native.py."""
    mod = _jsontree_module()
    return None if mod is None else mod.deepcopy


def jsontree_dumps():
    """The C serializer for JSON-shaped trees (the web/API tier's hot
    response path; machinery/serialize.py fronts it), or None when it
    can't build/load. The returned callable has EXACT ``json.dumps(obj)
    .encode()`` parity: the extension raises its ``Fallback`` exception
    for any input it cannot prove it serializes identically (non-str
    dict keys, exotic leaves) and this wrapper re-serializes with the
    stdlib — so behaviour, output bytes, and error messages all match.
    Capability-probed: a stale prebuilt .so without the ``dumps`` entry
    point degrades to None (callers use the pure-Python path)."""
    mod = _jsontree_module()
    if mod is None or not hasattr(mod, "dumps") or not hasattr(mod, "Fallback"):
        return None  # stale .so from before the dumps entry point
    import json as _json

    c_dumps = mod.dumps
    fallback = mod.Fallback

    def dumps(obj):
        try:
            return c_dumps(obj)
        except fallback:
            return _json.dumps(obj).encode()

    return dumps


def _i32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def pack_rows(
    flat: np.ndarray,  # int32 [total] concatenated tokens
    doc_lens: np.ndarray,  # int64 [n_docs]
    seq_len: int,
    pad_id: int = 0,
) -> dict:
    """Pack the whole document stream into [n_rows, seq_len] arrays in
    one native pass. Raises RuntimeError when the native library is
    unavailable — callers (train/data.py) decide the fallback."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native packer unavailable (no C++ compiler)")
    flat = np.ascontiguousarray(flat, np.int32)
    doc_lens = np.ascontiguousarray(doc_lens, np.int64)
    total = int(doc_lens.sum())
    if total != flat.size:
        raise ValueError(f"doc_lens sum {total} != flat size {flat.size}")
    max_rows = max((total + seq_len - 1) // seq_len, 1)
    tokens = np.full((max_rows, seq_len), pad_id, np.int32)
    targets = np.full((max_rows, seq_len), pad_id, np.int32)
    seg_ids = np.zeros((max_rows, seq_len), np.int32)
    loss_mask = np.zeros((max_rows, seq_len), np.float32)
    n = lib.pack_documents_c(
        _i32p(flat),
        doc_lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(doc_lens),
        seq_len,
        _i32p(tokens),
        _i32p(targets),
        _i32p(seg_ids),
        loss_mask.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        max_rows,
    )
    if n < 0:
        raise RuntimeError("native packer overflowed its row bound (bug)")
    return {
        "tokens": tokens[:n],
        "targets": targets[:n],
        "segment_ids": seg_ids[:n],
        "loss_mask": loss_mask[:n],
    }
