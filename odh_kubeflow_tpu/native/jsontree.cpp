// JSON-tree deepcopy + dumps — the control plane's hottest functions,
// in C.
//
// deepcopy: the embedded apiserver (machinery/store.py) copies every
// object on get/list to give callers apiserver-like isolation;
// profiling the 100/300-notebook loadtests put the (already
// tree-specialised) Python copy at the top of the profile. API objects
// are JSON-shaped trees — dict/list/str/int/float/bool/None — so this
// extension walks them with direct C-API calls and no memo/
// bookkeeping. Exotic leaves (never produced by the store, but callers
// may stash them) fall back to copy.deepcopy for exact parity with the
// Python implementation in machinery/objects.py.
//
// dumps: the web/API tier serialized every response through Python's
// json.dumps, which walks the whole (frozen, zero-copy) tree in the
// interpreter — the last Python-speed hop on an otherwise C-speed read
// path. This entry point serializes a JSON-shaped tree (including the
// FrozenDict/FrozenList dict/list subclasses the informer cache hands
// out) straight to a bytes object with EXACT json.dumps parity: same
// default separators (", " / ": "), same ensure_ascii escapes
// (surrogate pairs for non-BMP), same float repr (float.__repr__,
// Infinity/-Infinity/NaN), same int repr (int.__repr__, so IntEnum-ish
// subclasses encode as numbers). Anything it can't prove it serializes
// identically (non-str dict keys, unknown leaf types) raises the
// module's ``Fallback`` exception and the Python wrapper re-serializes
// with json.dumps — parity by construction, including error messages.
//
// Built lazily by odh_kubeflow_tpu.native.build() as a real extension
// module (CPython C API; this image has no pybind11).

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cmath>
#include <cstdio>
#include <new>
#include <string>

static PyObject* g_copy_deepcopy = NULL;

static PyObject* tree_copy(PyObject* obj) {
  if (PyDict_CheckExact(obj)) {
    PyObject* out = PyDict_New();
    if (!out) return NULL;
    PyObject *key, *value;
    Py_ssize_t pos = 0;
    while (PyDict_Next(obj, &pos, &key, &value)) {
      PyObject* cv = tree_copy(value);
      if (!cv) {
        Py_DECREF(out);
        return NULL;
      }
      if (PyDict_SetItem(out, key, cv) < 0) {
        Py_DECREF(cv);
        Py_DECREF(out);
        return NULL;
      }
      Py_DECREF(cv);
    }
    return out;
  }
  if (PyList_CheckExact(obj)) {
    Py_ssize_t n = PyList_GET_SIZE(obj);
    PyObject* out = PyList_New(n);
    if (!out) return NULL;
    for (Py_ssize_t i = 0; i < n; ++i) {
      PyObject* cv = tree_copy(PyList_GET_ITEM(obj, i));
      if (!cv) {
        Py_DECREF(out);
        return NULL;
      }
      PyList_SET_ITEM(out, i, cv);  // steals cv
    }
    return out;
  }
  if (PyUnicode_CheckExact(obj) || PyLong_CheckExact(obj) ||
      PyFloat_CheckExact(obj) || PyBool_Check(obj) || obj == Py_None) {
    Py_INCREF(obj);
    return obj;
  }
  // dict/list SUBCLASSES (the frozen read-only wrappers the informer
  // cache hands out, machinery/objects.py FrozenDict/FrozenList) copy
  // into PLAIN dicts/lists: this is the fast path behind mutable(),
  // the cache's copy-on-write escape hatch. PyDict_Next / the list
  // item API read the concrete storage directly, so no (blocked)
  // subclass method is ever invoked.
  if (PyDict_Check(obj)) {
    PyObject* out = PyDict_New();
    if (!out) return NULL;
    PyObject *key, *value;
    Py_ssize_t pos = 0;
    while (PyDict_Next(obj, &pos, &key, &value)) {
      PyObject* cv = tree_copy(value);
      if (!cv) {
        Py_DECREF(out);
        return NULL;
      }
      if (PyDict_SetItem(out, key, cv) < 0) {
        Py_DECREF(cv);
        Py_DECREF(out);
        return NULL;
      }
      Py_DECREF(cv);
    }
    return out;
  }
  if (PyList_Check(obj)) {
    Py_ssize_t n = PyList_GET_SIZE(obj);
    PyObject* out = PyList_New(n);
    if (!out) return NULL;
    for (Py_ssize_t i = 0; i < n; ++i) {
      PyObject* cv = tree_copy(PyList_GET_ITEM(obj, i));
      if (!cv) {
        Py_DECREF(out);
        return NULL;
      }
      PyList_SET_ITEM(out, i, cv);  // steals cv
    }
    return out;
  }
  return PyObject_CallFunctionObjArgs(g_copy_deepcopy, obj, NULL);
}

static PyObject* jsontree_deepcopy(PyObject* Py_UNUSED(self), PyObject* obj) {
  return tree_copy(obj);
}

// ---------------------------------------------------------------------------
// dumps — serialize a JSON-shaped tree to bytes, byte-identical to
// json.dumps(obj).encode() with default arguments.

static PyObject* g_fallback_exc = NULL;

static void append_escaped_string(std::string& out, PyObject* s) {
  // py_encode_basestring_ascii parity: printable ASCII minus '"'/'\\'
  // passes through; the short escapes for \b \t \n \f \r; everything
  // else (controls, DEL, non-ASCII) as lowercase \uXXXX, with
  // surrogate pairs above the BMP. Lone surrogates emit as-is, same as
  // the stdlib encoder.
  const int kind = PyUnicode_KIND(s);
  const void* data = PyUnicode_DATA(s);
  const Py_ssize_t n = PyUnicode_GET_LENGTH(s);
  char buf[16];
  out += '"';
  if (kind == PyUnicode_1BYTE_KIND) {
    // the overwhelmingly common case (ASCII names/labels): bulk-copy
    // maximal clean runs instead of appending char-by-char
    const unsigned char* p = (const unsigned char*)data;
    Py_ssize_t i = 0;
    while (i < n) {
      Py_ssize_t j = i;
      while (j < n && p[j] >= 0x20 && p[j] < 0x7f && p[j] != '"' &&
             p[j] != '\\')
        ++j;
      if (j > i) out.append((const char*)p + i, (size_t)(j - i));
      if (j >= n) break;
      unsigned char c = p[j];
      switch (c) {
        case '"': out += "\\\""; break;
        case '\\': out += "\\\\"; break;
        case '\b': out += "\\b"; break;
        case '\f': out += "\\f"; break;
        case '\n': out += "\\n"; break;
        case '\r': out += "\\r"; break;
        case '\t': out += "\\t"; break;
        default:
          std::snprintf(buf, sizeof(buf), "\\u%04x", (unsigned)c);
          out += buf;
      }
      i = j + 1;
    }
    out += '"';
    return;
  }
  for (Py_ssize_t i = 0; i < n; ++i) {
    Py_UCS4 c = PyUnicode_READ(kind, data, i);
    if (c >= 0x20 && c < 0x7f && c != '"' && c != '\\') {
      out += static_cast<char>(c);
      continue;
    }
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\b': out += "\\b"; break;
      case '\f': out += "\\f"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (c > 0xffff) {
          c -= 0x10000;
          std::snprintf(buf, sizeof(buf), "\\u%04x\\u%04x",
                        0xd800 + (unsigned)(c >> 10),
                        0xdc00 + (unsigned)(c & 0x3ff));
        } else {
          std::snprintf(buf, sizeof(buf), "\\u%04x", (unsigned)c);
        }
        out += buf;
    }
  }
  out += '"';
}

static int append_repr_of(std::string& out, PyObject* num, reprfunc repr) {
  // int/float repr through the BASE type's tp_repr, exactly what the
  // stdlib C encoder does — a subclass overriding __repr__ still
  // encodes as a plain number
  PyObject* r = repr(num);
  if (r == NULL) return -1;
  Py_ssize_t len = 0;
  const char* utf8 = PyUnicode_AsUTF8AndSize(r, &len);
  if (utf8 == NULL) {
    Py_DECREF(r);
    return -1;
  }
  out.append(utf8, (size_t)len);
  Py_DECREF(r);
  return 0;
}

static int tree_dump(PyObject* obj, std::string& out) {
  // bool before int (bool subclasses int), exact checks before the
  // subclass checks so plain API objects never branch-miss
  if (obj == Py_True) {
    out += "true";
    return 0;
  }
  if (obj == Py_False) {
    out += "false";
    return 0;
  }
  if (obj == Py_None) {
    out += "null";
    return 0;
  }
  if (PyUnicode_Check(obj)) {
    append_escaped_string(out, obj);
    return 0;
  }
  if (PyLong_Check(obj)) {
    return append_repr_of(out, obj, PyLong_Type.tp_repr);
  }
  if (PyFloat_Check(obj)) {
    double v = PyFloat_AS_DOUBLE(obj);
    if (std::isnan(v)) {
      out += "NaN";
    } else if (std::isinf(v)) {
      out += (v > 0) ? "Infinity" : "-Infinity";
    } else {
      return append_repr_of(out, obj, PyFloat_Type.tp_repr);
    }
    return 0;
  }
  if (PyDict_Check(obj)) {  // FrozenDict included: PyDict_Next reads
    if (Py_EnterRecursiveCall(" while serializing JSON tree")) return -1;
    out += '{';            // the concrete storage, no methods invoked
    PyObject *key, *value;
    Py_ssize_t pos = 0;
    bool first = true;
    while (PyDict_Next(obj, &pos, &key, &value)) {
      if (!PyUnicode_Check(key)) {
        // json.dumps coerces int/float/bool/None keys (and raises on
        // the rest); both are rare enough to hand the WHOLE call back
        Py_LeaveRecursiveCall();
        PyErr_SetString(g_fallback_exc, "non-str dict key");
        return -1;
      }
      if (!first) out += ", ";
      first = false;
      append_escaped_string(out, key);
      out += ": ";
      if (tree_dump(value, out) < 0) {
        Py_LeaveRecursiveCall();
        return -1;
      }
    }
    out += '}';
    Py_LeaveRecursiveCall();
    return 0;
  }
  if (PyList_Check(obj) || PyTuple_Check(obj)) {
    if (Py_EnterRecursiveCall(" while serializing JSON tree")) return -1;
    const bool is_list = PyList_Check(obj);
    const Py_ssize_t n =
        is_list ? PyList_GET_SIZE(obj) : PyTuple_GET_SIZE(obj);
    out += '[';
    for (Py_ssize_t i = 0; i < n; ++i) {
      if (i) out += ", ";
      PyObject* item =
          is_list ? PyList_GET_ITEM(obj, i) : PyTuple_GET_ITEM(obj, i);
      if (tree_dump(item, out) < 0) {
        Py_LeaveRecursiveCall();
        return -1;
      }
    }
    out += ']';
    Py_LeaveRecursiveCall();
    return 0;
  }
  PyErr_SetString(g_fallback_exc, "leaf type the C serializer cannot prove");
  return -1;
}

static PyObject* jsontree_dumps(PyObject* Py_UNUSED(self), PyObject* obj) {
  try {
    std::string out;
    out.reserve(512);
    if (tree_dump(obj, out) < 0) return NULL;
    return PyBytes_FromStringAndSize(out.data(), (Py_ssize_t)out.size());
  } catch (const std::bad_alloc&) {
    PyErr_NoMemory();
    return NULL;
  }
}

static PyMethodDef Methods[] = {
    {"deepcopy", (PyCFunction)jsontree_deepcopy, METH_O,
     "Deep copy a JSON-shaped tree (dict/list/scalars); exotic leaves "
     "fall back to copy.deepcopy."},
    {"dumps", (PyCFunction)jsontree_dumps, METH_O,
     "Serialize a JSON-shaped tree to bytes, byte-identical to "
     "json.dumps(obj).encode(); raises Fallback for input it cannot "
     "prove identical (the wrapper re-serializes via json.dumps)."},
    {NULL, NULL, 0, NULL}};

static struct PyModuleDef moduledef = {PyModuleDef_HEAD_INIT,
                                       "_odhkf_jsontree",
                                       NULL,
                                       -1,
                                       Methods,
                                       NULL,
                                       NULL,
                                       NULL,
                                       NULL};

PyMODINIT_FUNC PyInit__odhkf_jsontree(void) {
  PyObject* copy_mod = PyImport_ImportModule("copy");
  if (!copy_mod) return NULL;
  g_copy_deepcopy = PyObject_GetAttrString(copy_mod, "deepcopy");
  Py_DECREF(copy_mod);
  if (!g_copy_deepcopy) return NULL;
  PyObject* mod = PyModule_Create(&moduledef);
  if (!mod) return NULL;
  g_fallback_exc =
      PyErr_NewException("_odhkf_jsontree.Fallback", NULL, NULL);
  if (!g_fallback_exc || PyModule_AddObject(mod, "Fallback", g_fallback_exc) < 0) {
    Py_XDECREF(g_fallback_exc);
    Py_DECREF(mod);
    return NULL;
  }
  Py_INCREF(g_fallback_exc);  // module owns one ref; keep ours for C use
  return mod;
}
