// JSON-tree deepcopy — the control plane's hottest function, in C.
//
// The embedded apiserver (machinery/store.py) copies every object on
// get/list to give callers apiserver-like isolation; profiling the
// 100/300-notebook loadtests put the (already tree-specialised) Python
// copy at the top of the profile. API objects are JSON-shaped trees —
// dict/list/str/int/float/bool/None — so this extension walks them
// with direct C-API calls and no memo/bookkeeping. Exotic leaves
// (never produced by the store, but callers may stash them) fall back
// to copy.deepcopy for exact parity with the Python implementation in
// machinery/objects.py.
//
// Built lazily by odh_kubeflow_tpu.native.build() as a real extension
// module (CPython C API; this image has no pybind11).

#define PY_SSIZE_T_CLEAN
#include <Python.h>

static PyObject* g_copy_deepcopy = NULL;

static PyObject* tree_copy(PyObject* obj) {
  if (PyDict_CheckExact(obj)) {
    PyObject* out = PyDict_New();
    if (!out) return NULL;
    PyObject *key, *value;
    Py_ssize_t pos = 0;
    while (PyDict_Next(obj, &pos, &key, &value)) {
      PyObject* cv = tree_copy(value);
      if (!cv) {
        Py_DECREF(out);
        return NULL;
      }
      if (PyDict_SetItem(out, key, cv) < 0) {
        Py_DECREF(cv);
        Py_DECREF(out);
        return NULL;
      }
      Py_DECREF(cv);
    }
    return out;
  }
  if (PyList_CheckExact(obj)) {
    Py_ssize_t n = PyList_GET_SIZE(obj);
    PyObject* out = PyList_New(n);
    if (!out) return NULL;
    for (Py_ssize_t i = 0; i < n; ++i) {
      PyObject* cv = tree_copy(PyList_GET_ITEM(obj, i));
      if (!cv) {
        Py_DECREF(out);
        return NULL;
      }
      PyList_SET_ITEM(out, i, cv);  // steals cv
    }
    return out;
  }
  if (PyUnicode_CheckExact(obj) || PyLong_CheckExact(obj) ||
      PyFloat_CheckExact(obj) || PyBool_Check(obj) || obj == Py_None) {
    Py_INCREF(obj);
    return obj;
  }
  // dict/list SUBCLASSES (the frozen read-only wrappers the informer
  // cache hands out, machinery/objects.py FrozenDict/FrozenList) copy
  // into PLAIN dicts/lists: this is the fast path behind mutable(),
  // the cache's copy-on-write escape hatch. PyDict_Next / the list
  // item API read the concrete storage directly, so no (blocked)
  // subclass method is ever invoked.
  if (PyDict_Check(obj)) {
    PyObject* out = PyDict_New();
    if (!out) return NULL;
    PyObject *key, *value;
    Py_ssize_t pos = 0;
    while (PyDict_Next(obj, &pos, &key, &value)) {
      PyObject* cv = tree_copy(value);
      if (!cv) {
        Py_DECREF(out);
        return NULL;
      }
      if (PyDict_SetItem(out, key, cv) < 0) {
        Py_DECREF(cv);
        Py_DECREF(out);
        return NULL;
      }
      Py_DECREF(cv);
    }
    return out;
  }
  if (PyList_Check(obj)) {
    Py_ssize_t n = PyList_GET_SIZE(obj);
    PyObject* out = PyList_New(n);
    if (!out) return NULL;
    for (Py_ssize_t i = 0; i < n; ++i) {
      PyObject* cv = tree_copy(PyList_GET_ITEM(obj, i));
      if (!cv) {
        Py_DECREF(out);
        return NULL;
      }
      PyList_SET_ITEM(out, i, cv);  // steals cv
    }
    return out;
  }
  return PyObject_CallFunctionObjArgs(g_copy_deepcopy, obj, NULL);
}

static PyObject* jsontree_deepcopy(PyObject* Py_UNUSED(self), PyObject* obj) {
  return tree_copy(obj);
}

static PyMethodDef Methods[] = {
    {"deepcopy", (PyCFunction)jsontree_deepcopy, METH_O,
     "Deep copy a JSON-shaped tree (dict/list/scalars); exotic leaves "
     "fall back to copy.deepcopy."},
    {NULL, NULL, 0, NULL}};

static struct PyModuleDef moduledef = {PyModuleDef_HEAD_INIT,
                                       "_odhkf_jsontree",
                                       NULL,
                                       -1,
                                       Methods,
                                       NULL,
                                       NULL,
                                       NULL,
                                       NULL};

PyMODINIT_FUNC PyInit__odhkf_jsontree(void) {
  PyObject* copy_mod = PyImport_ImportModule("copy");
  if (!copy_mod) return NULL;
  g_copy_deepcopy = PyObject_GetAttrString(copy_mod, "deepcopy");
  Py_DECREF(copy_mod);
  if (!g_copy_deepcopy) return NULL;
  return PyModule_Create(&moduledef);
}
