// Native document packer — the host-side hot loop of the input
// pipeline (train/data.py).
//
// The TPU compute path is JAX/XLA; this is the runtime *around* it:
// packing variable-length token documents into fixed-shape [rows, S]
// training windows is pure CPU byte-shuffling that sits on the critical
// path of every training step's host feed. The Python/numpy
// implementation walks documents piece-by-piece with per-piece fancy
// indexing; this C++ pass writes each output element exactly once and
// is memory-bandwidth-bound.
//
// Semantics are IDENTICAL to train/data.pack_documents (the contract
// test asserts bit-equality): documents are concatenated greedily into
// rows of seq_len, each document piece gets a 1-based segment id that
// resets per row, targets are next-token *within a piece*, and the last
// token of each piece plus all padding carry loss_mask 0.
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in this
// image); arrays are caller-allocated numpy buffers.

#include <cstdint>

extern "C" {

// Returns the number of rows written (<= max_rows), or -1 if the packed
// stream would overflow max_rows. Outputs must hold max_rows*seq_len
// elements each; callers pre-fill tokens/targets with pad_id and
// seg/mask with zero (matching numpy allocation in the wrapper).
long pack_documents_c(const int32_t* flat,      // concatenated tokens
                      const int64_t* doc_lens,  // [n_docs]
                      long n_docs,
                      long seq_len,
                      int32_t* tokens,   // [max_rows, seq_len]
                      int32_t* targets,  // [max_rows, seq_len]
                      int32_t* seg_ids,  // [max_rows, seq_len]
                      float* loss_mask,  // [max_rows, seq_len]
                      long max_rows) {
  long row = 0;       // current row
  long used = 0;      // tokens used in current row
  int32_t seg = 0;    // segment counter within current row
  bool row_open = false;
  const int32_t* cursor = flat;

  for (long d = 0; d < n_docs; ++d) {
    int64_t remaining = doc_lens[d];
    while (remaining > 0) {
      if (used == seq_len) {  // row full: advance
        ++row;
        used = 0;
        seg = 0;
      }
      if (row >= max_rows) return -1;
      row_open = true;
      long space = seq_len - used;
      long n = remaining < space ? static_cast<long>(remaining) : space;
      ++seg;
      int32_t* t = tokens + row * seq_len + used;
      int32_t* tg = targets + row * seq_len + used;
      int32_t* sg = seg_ids + row * seq_len + used;
      float* m = loss_mask + row * seq_len + used;
      for (long i = 0; i < n; ++i) {
        t[i] = cursor[i];
        sg[i] = seg;
      }
      // next-token targets within the piece; last token masked out
      for (long i = 0; i + 1 < n; ++i) {
        tg[i] = cursor[i + 1];
        m[i] = 1.0f;
      }
      cursor += n;
      used += n;
      remaining -= n;
    }
  }
  return row_open ? row + 1 : row;
}

}  // extern "C"
