"""Idleness culling, TPU-duty-cycle aware.

Reference parity (notebook-controller/pkg/culler/culler.go): probes the
running server's Jupyter REST API (``/api/kernels``, ``/api/terminals``,
:155-221), maintains the ``notebooks.kubeflow.org/last-activity``
annotation with a monotonic guard (:266-355), and sets
``kubeflow-resource-stopped`` once idle beyond the threshold (:405-420).
Design doc: components/proposals/20220121-jupyter-notebook-idleness.md.

TPU-first change (SURVEY.md §7 hard part (b)): kernel-state probing
alone would cull a notebook mid-fine-tune — a long training step looks
"busy-but-quiet" (no new kernel activity, websocket silent). The culler
therefore also probes ``/api/tpu/activity`` (served by the in-image
runtime agent, images/: jupyter-jax-tpu) and treats recent TPU duty
cycle above a threshold as activity. A multi-host slice is culled
atomically — the stop annotation acts on the Notebook, never a subset
of hosts.
"""

from __future__ import annotations

import calendar
import dataclasses
import json
import time
import urllib.error
import urllib.request
from typing import Any, Callable, Optional

from odh_kubeflow_tpu.apis import (
    LAST_ACTIVITY_ANNOTATION,
    LAST_ACTIVITY_CHECK_ANNOTATION,
    STOP_ANNOTATION,
    SUSPEND_REASON_ANNOTATION,
    SUSPENDED_AT_ANNOTATION,
    TPU_ACCELERATOR_ANNOTATION,
    TPU_DUTY_CYCLE_ANNOTATION,
)
from odh_kubeflow_tpu.controllers.runtime import Result
from odh_kubeflow_tpu.machinery import objects as obj_util
from odh_kubeflow_tpu.machinery.events import EventRecorder
from odh_kubeflow_tpu.machinery.store import APIServer, Conflict, NotFound

Obj = dict[str, Any]

TIME_FORMAT = "%Y-%m-%dT%H:%M:%SZ"


def _parse_time(s: str) -> float:
    s = s.split(".")[0].rstrip("Z") + "Z"
    return calendar.timegm(time.strptime(s, TIME_FORMAT))


def _fmt_time(t: float) -> str:
    return time.strftime(TIME_FORMAT, time.gmtime(t))


@dataclasses.dataclass
class CullerConfig:
    cull_idle_seconds: float = 1440 * 60.0
    idleness_check_seconds: float = 60.0
    cluster_domain: str = "cluster.local"
    probe_timeout: float = 5.0
    # TPU activity: duty cycle above this percentage counts as active.
    # check_tpu_duty_cycle=False skips the agent probe entirely
    # (CULL_CHECK_TPU_DUTY_CYCLE env — clusters without the in-image
    # tpu-activity-agent fall back to Jupyter-kernel idleness only)
    check_tpu_duty_cycle: bool = True
    tpu_duty_cycle_threshold: float = 5.0
    # port the in-image tpu-activity-agent listens on (exposed by the
    # notebook Service for TPU notebooks; images/*/tpu-activity-agent)
    tpu_agent_port: int = 8890
    # suspend-to-checkpoint instead of a plain stop: the cull stamps
    # ``suspended-at`` alongside ``kubeflow-resource-stopped`` so the
    # session manager snapshots kernel state before the slice is
    # released, and JWA shows "suspended, resumable" — not "stopped"
    suspend_on_cull: bool = False


class Culler:
    def __init__(
        self,
        api: APIServer,
        config: Optional[CullerConfig] = None,
        base_url_fn: Optional[Callable[[Obj], str]] = None,
        now_fn: Callable[[], float] = time.time,
        cull_counter=None,
        tpu_url_fn: Optional[Callable[[Obj], str]] = None,
        meter: Optional[Any] = None,
    ):
        self.api = api
        self.config = config or CullerConfig()
        # shared chip-hour ledger (machinery.usage.UsageMeter duck):
        # the probed duty sample feeds the meter instead of being
        # discarded after the threshold comparison
        self.meter = meter
        self._base_url_fn = base_url_fn or self._default_base_url
        # TPU probe URL: the agent serves on its own port (the Jupyter
        # port can't proxy it). When a test injects base_url_fn only,
        # the TPU probe rides the same fake base.
        if tpu_url_fn is None:
            if base_url_fn is None:
                tpu_url_fn = self._default_tpu_url
            else:
                tpu_url_fn = lambda nb: base_url_fn(nb) + "/api/tpu/activity"  # noqa: E731
        self._tpu_url_fn = tpu_url_fn
        self.now = now_fn
        self.m_cull = cull_counter
        self.m_last_cull = None  # gauge, wired by the notebook controller
        self.recorder = EventRecorder(api, "notebook-controller")

    def _default_base_url(self, notebook: Obj) -> str:
        name = obj_util.name_of(notebook)
        ns = obj_util.namespace_of(notebook)
        # service port 80 → jupyter 8888 (culler.go:155-180 URL shape)
        return (
            f"http://{name}.{ns}.svc.{self.config.cluster_domain}"
            f"/notebook/{ns}/{name}"
        )

    def _default_tpu_url(self, notebook: Obj) -> str:
        from odh_kubeflow_tpu.apis import notebook_agent_url

        return (
            notebook_agent_url(
                notebook,
                self.config.cluster_domain,
                self.config.tpu_agent_port,
            )
            + "/api/tpu/activity"
        )

    # -- probes -------------------------------------------------------------

    def _get_json(self, url: str):
        try:
            with urllib.request.urlopen(url, timeout=self.config.probe_timeout) as r:
                return json.loads(r.read().decode())
        except (urllib.error.URLError, OSError, ValueError):
            return None

    def probe_activity(self, notebook: Obj) -> Optional[float]:
        """Returns the server's latest activity timestamp (epoch), or
        None when the server is unreachable (treated as no-information:
        the annotation is left alone, matching the reference's behavior
        of skipping updates when probing fails)."""
        base = self._base_url_fn(notebook)
        latest: Optional[float] = None

        kernels = self._get_json(f"{base}/api/kernels")
        if isinstance(kernels, list):
            for k in kernels:
                if not isinstance(k, dict):
                    continue
                if k.get("execution_state") == "busy":
                    return self.now()
                la = k.get("last_activity")
                if la:
                    t = _parse_time(la)
                    latest = t if latest is None else max(latest, t)

        terminals = self._get_json(f"{base}/api/terminals")
        if isinstance(terminals, list):
            for term in terminals:
                if not isinstance(term, dict):
                    continue
                la = term.get("last_activity")
                if la:
                    t = _parse_time(la)
                    latest = t if latest is None else max(latest, t)

        # TPU probe only for TPU notebooks — non-TPU Services don't
        # expose the agent port, and an undeclared ClusterIP port can
        # stall the probe for its full timeout
        tpu = (
            self._get_json(self._tpu_url_fn(notebook))
            if self.config.check_tpu_duty_cycle
            and TPU_ACCELERATOR_ANNOTATION in obj_util.annotations_of(notebook)
            else None
        )
        if isinstance(tpu, dict):
            # a valid-JSON-but-wrong-shape payload (or a non-numeric
            # duty field) is no-information — a gap, exactly like an
            # unreachable agent; it must neither crash the loop nor
            # read as duty 0
            try:
                duty = float(tpu.get("duty_cycle_pct"))
            except (TypeError, ValueError):
                duty = None
            if duty is not None:
                self._observe_duty(notebook, duty)
                if duty >= self.config.tpu_duty_cycle_threshold:
                    return self.now()
            la = tpu.get("last_active")
            if la:
                try:
                    t = _parse_time(la)
                except (TypeError, ValueError):
                    t = None
                if t is not None:
                    latest = t if latest is None else max(latest, t)

        return latest

    def _observe_duty(self, notebook: Obj, duty: float) -> None:
        """A probed duty sample is evidence, not just a threshold
        input: feed it to the shared usage meter and stamp the
        last-observed annotation (rides the reconcile's annotation
        patch) so the cull decision is auditable."""
        now = self.now()
        if self.meter is not None:
            self.meter.observe_sample(
                obj_util.namespace_of(notebook),
                obj_util.name_of(notebook),
                duty,
                t=now,
                source="culler",
            )
        obj_util.set_annotation(
            notebook,
            # protocol-ok: audit trail for operators (kubectl describe)
            TPU_DUTY_CYCLE_ANNOTATION,
            f"{duty:g}@{_fmt_time(now)}",
        )

    # -- annotation state machine -------------------------------------------

    def reconcile_notebook(self, notebook: Obj) -> Result:
        """Called from the notebook controller's reconcile tail
        (reference :252-281). Returns the requeue period."""
        ann = obj_util.annotations_of(notebook)
        if STOP_ANNOTATION in ann:
            return Result()  # already stopped; nothing to track

        now = self.now()
        period = self.config.idleness_check_seconds

        last_check = ann.get(LAST_ACTIVITY_CHECK_ANNOTATION)
        if last_check is not None and now - _parse_time(last_check) < period:
            remaining = period - (now - _parse_time(last_check))
            return Result(requeue_after=max(remaining, 1.0))

        running = self._notebook_running(notebook)
        if not running and self._notebook_queued(notebook):
            # queue wait is not idleness: a gang waiting for admission
            # (or for slice capacity) has no server to be idle. Pin
            # last-activity to now so a long queue wait can never tip
            # the notebook over the cull threshold the moment it
            # finally starts.
            obj_util.set_annotation(
                notebook, LAST_ACTIVITY_ANNOTATION, _fmt_time(now)
            )
        if running:
            # initialize on first sight (culler.go:118-141): without
            # this, a server that never reports activity (no kernels,
            # probe unreachable) would hold its TPU slice forever.
            if LAST_ACTIVITY_ANNOTATION not in ann:
                obj_util.set_annotation(
                    notebook, LAST_ACTIVITY_ANNOTATION, _fmt_time(now)
                )
                ann = obj_util.annotations_of(notebook)
            activity = self.probe_activity(notebook)
            if activity is not None:
                prev = ann.get(LAST_ACTIVITY_ANNOTATION)
                # monotonic guard (culler.go:302-355)
                if prev is None or activity > _parse_time(prev):
                    obj_util.set_annotation(
                        notebook, LAST_ACTIVITY_ANNOTATION, _fmt_time(activity)
                    )
        obj_util.set_annotation(
            notebook, LAST_ACTIVITY_CHECK_ANNOTATION, _fmt_time(now)
        )

        if running and self.needs_culling(notebook):
            obj_util.set_annotation(notebook, STOP_ANNOTATION, _fmt_time(now))
            if self.m_cull is not None:
                self.m_cull.inc()
            if self.m_last_cull is not None:
                self.m_last_cull.set(now)
            # a re-cull of the same notebook (restarted, idled again)
            # bumps the Event count instead of stacking duplicates
            if self.config.suspend_on_cull:
                # suspended, not stopped: a DISTINCT event + the
                # suspended-at stamp let JWA (and users) tell
                # "resumable with warm state" apart from a plain stop
                obj_util.set_annotation(
                    notebook, SUSPENDED_AT_ANNOTATION, _fmt_time(now)
                )
                obj_util.set_annotation(
                    notebook, SUSPEND_REASON_ANNOTATION, "cull"
                )
                self.recorder.normal(
                    notebook,
                    "Suspended",
                    "Notebook idle beyond threshold; suspending session "
                    "to checkpoint and releasing the slice",
                )
            else:
                self.recorder.normal(
                    notebook,
                    "Culled",
                    "Notebook idle beyond threshold; scaling to zero",
                )
        self._patch_annotations(notebook)
        return Result(requeue_after=period)

    def needs_culling(self, notebook: Obj) -> bool:
        ann = obj_util.annotations_of(notebook)
        last = ann.get(LAST_ACTIVITY_ANNOTATION)
        if last is None:
            return False
        return self.now() - _parse_time(last) > self.config.cull_idle_seconds

    def _notebook_queued(self, notebook: Obj) -> bool:
        """Whether the notebook is waiting on admission/scheduling
        rather than running: its Workload is not admitted, or its pods
        exist but sit Pending (gated or unschedulable)."""
        name = obj_util.name_of(notebook)
        ns = obj_util.namespace_of(notebook)
        try:
            wl = self.api.get("Workload", name, ns)
            if obj_util.get_path(wl, "status", "state") != "Admitted":
                return True
        except NotFound:
            pass  # no workload (queueing off) or kind not registered
        return any(
            obj_util.get_path(p, "status", "phase") == "Pending"
            for p in self.api.list(
                "Pod",
                namespace=ns,
                label_selector={"matchLabels": {"statefulset": name}},
            )
        )

    def _notebook_running(self, notebook: Obj) -> bool:
        try:
            pod = self.api.get(
                "Pod",
                f"{obj_util.name_of(notebook)}-0",
                obj_util.namespace_of(notebook),
            )
        except NotFound:
            return False
        return obj_util.get_path(pod, "status", "phase") == "Running"

    def _patch_annotations(self, notebook: Obj) -> None:
        patch = {
            "metadata": {"annotations": dict(obj_util.annotations_of(notebook))}
        }
        try:
            self.api.patch(
                "Notebook",
                obj_util.name_of(notebook),
                patch,
                obj_util.namespace_of(notebook),
            )
        except (Conflict, NotFound):
            pass  # next requeue retries
