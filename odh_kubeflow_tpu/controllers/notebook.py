"""notebook-controller: Notebook CR → StatefulSet + Service(+ routes),
with native TPU pod-slice scheduling.

Reference parity (components/notebook-controller/controllers/
notebook_controller.go): Reconcile :90-282, generateStatefulSet
:418-481, generateService :483-510, generateVirtualService :516-610,
event re-emission :94-118 + nbNameFromInvolvedObject :653-677, status
mirroring :300-359, culling branch :252-281.

TPU-first redesign (the single biggest semantic change, SURVEY.md §5
"distributed communication backend"):
- The accelerator request is (accelerator_type, topology) annotations +
  a ``google.com/tpu`` chip limit, not a GPU vendor limit.
- Multi-host slices: StatefulSet replicas == hosts-in-slice (the
  reference hard-codes 0/1), a headless service gives stable per-host
  DNS, and every pod gets the libtpu/JAX multi-host contract injected:
  TPU_WORKER_ID (pod ordinal), TPU_WORKER_HOSTNAMES (all hosts),
  JAX coordinator address on host 0. ICI inside a slice needs no
  platform wiring (libtpu discovers it); this env is the DCN story.
- Culling treats the host group atomically: replicas go hosts→0, never
  partial.
"""

from __future__ import annotations

import dataclasses
import os
import time as _time
from typing import Any, Optional

from odh_kubeflow_tpu.apis import (
    STOP_ANNOTATION,
    TPU_ACCEL_NODE_LABEL,
    TPU_ACCELERATOR_ANNOTATION,
    TPU_RESOURCE,
    TPU_TOPO_NODE_LABEL,
    TPU_TOPOLOGY_ANNOTATION,
)
from odh_kubeflow_tpu.controllers import reconcilehelper
from odh_kubeflow_tpu.controllers.runtime import Manager, Request, Result
from odh_kubeflow_tpu.machinery import objects as obj_util
from odh_kubeflow_tpu.machinery.cache import list_by_index
from odh_kubeflow_tpu.machinery.events import EventRecorder
from odh_kubeflow_tpu.machinery.objects import mutable
from odh_kubeflow_tpu.machinery.store import APIServer, Conflict, NotFound
from odh_kubeflow_tpu.scheduling import (
    ADMISSION_GATE_ANNOTATION,
    WORKLOAD_LABEL,
)
from odh_kubeflow_tpu.scheduling.workload import (
    resolve_priority,
    workload_from_statefulset,
)
from odh_kubeflow_tpu.utils import prometheus, tracing
from odh_kubeflow_tpu.utils.tpu import TPU_TOPOLOGIES, chips_in_topology, hosts_in_slice
from odh_kubeflow_tpu.warmup import PREFERRED_POOL_ANNOTATION

Obj = dict[str, Any]

DEFAULT_CONTAINER_PORT = 8888
DEFAULT_SERVICE_PORT = 80
DEFAULT_FSGROUP = 100
PREFIX_ENV = "NB_PREFIX"
TPU_AGENT_PORT = 8890


@dataclasses.dataclass
class NotebookControllerConfig:
    """Env-driven toggles, names matching the reference
    (notebook_controller.go:204,472,534,548; culler.go:26-30)."""

    use_istio: bool = False
    istio_gateway: str = "kubeflow/kubeflow-gateway"
    istio_host: str = "*"
    cluster_domain: str = "cluster.local"
    add_fsgroup: bool = True
    enable_culling: bool = False
    cull_idle_seconds: float = 1440 * 60.0
    idleness_check_seconds: float = 60.0
    # gang admission through the TPU slice scheduler: TPU notebooks get
    # a Workload + admission-gated pods instead of racing the quota
    enable_queueing: bool = False
    # suspend-to-checkpoint sessions (sessions/ subsystem): culls become
    # suspends, the scale-down waits for the kernel snapshot, and
    # suspended notebooks resume warm instead of starting cold
    enable_sessions: bool = False
    # wedge-breaker: a suspend whose snapshot never lands within this
    # window degrades to a plain stop (losing state beats leaking chips)
    suspend_grace_seconds: float = 600.0
    # whether the culler probes the in-image tpu-activity-agent for
    # duty cycle before declaring a TPU notebook idle
    cull_check_tpu_duty_cycle: bool = True
    # compilation-cache service mount (warmup/ subsystem): when set,
    # TPU notebook kernels get JAX_COMPILATION_CACHE_DIR pointed at
    # this cache-service-backed path, so their first jit loads the
    # fleet's shared artifacts instead of compiling
    compile_cache_mount: str = ""

    @staticmethod
    def from_env() -> "NotebookControllerConfig":
        env = os.environ

        def flag(name: str, default: str = "false") -> bool:
            return env.get(name, default).lower() == "true"

        return NotebookControllerConfig(
            use_istio=flag("USE_ISTIO"),
            istio_gateway=env.get("ISTIO_GATEWAY", "kubeflow/kubeflow-gateway"),
            istio_host=env.get("ISTIO_HOST", "*"),
            cluster_domain=env.get("CLUSTER_DOMAIN", "cluster.local"),
            add_fsgroup=flag("ADD_FSGROUP", "true"),
            enable_culling=flag("ENABLE_CULLING"),
            cull_idle_seconds=float(env.get("CULL_IDLE_TIME", "1440")) * 60.0,
            idleness_check_seconds=float(env.get("IDLENESS_CHECK_PERIOD", "1"))
            * 60.0,
            enable_queueing=flag("ENABLE_TPU_QUEUEING", "true"),
            enable_sessions=flag("ENABLE_SESSION_SUSPEND", "true"),
            cull_check_tpu_duty_cycle=flag("CULL_CHECK_TPU_DUTY_CYCLE", "true"),
            suspend_grace_seconds=float(
                env.get("SESSION_SUSPEND_GRACE_SECONDS", "600")
            ),
            compile_cache_mount=env.get("COMPILE_CACHE_MOUNT", ""),
        )


# ---------------------------------------------------------------------------
# TPU request derivation


@dataclasses.dataclass(frozen=True)
class TpuRequest:
    accelerator_type: str
    topology: str

    @property
    def chips(self) -> int:
        return chips_in_topology(self.topology)

    @property
    def hosts(self) -> int:
        return hosts_in_slice(self.accelerator_type, self.topology)

    @property
    def chips_per_host(self) -> int:
        return self.chips // self.hosts


def tpu_request_of(notebook: Obj) -> Optional[TpuRequest]:
    ann = obj_util.annotations_of(notebook)
    accel = ann.get(TPU_ACCELERATOR_ANNOTATION, "")
    topo = ann.get(TPU_TOPOLOGY_ANNOTATION, "")
    if not accel:
        return None
    if accel not in TPU_TOPOLOGIES:
        raise ValueError(f"unknown TPU accelerator type {accel!r}")
    if not topo:
        topo = TPU_TOPOLOGIES[accel]["topologies"][0]
    if topo not in TPU_TOPOLOGIES[accel]["topologies"]:
        raise ValueError(f"unknown topology {topo!r} for {accel}")
    return TpuRequest(accel, topo)


# ---------------------------------------------------------------------------
# controller


class NotebookController:
    def __init__(
        self,
        api: APIServer,
        config: Optional[NotebookControllerConfig] = None,
        registry: Optional[prometheus.Registry] = None,
        culler: Optional[Any] = None,
        meter: Optional[Any] = None,
    ):
        self.api = api
        self.config = config or NotebookControllerConfig()
        self.culler = culler
        # chip-hour ledger tap: a scale-down/suspend deletes the
        # Workload here, which is an allocation release the scheduler
        # never sees (machinery.usage.UsageMeter duck)
        self.meter = meter
        self.recorder = EventRecorder(api, "notebook-controller")
        reg = registry or prometheus.default_registry
        self.m_create = reg.counter(
            "notebook_create_total", "Total times of creating notebooks"
        )
        self.m_create_failed = reg.counter(
            "notebook_create_failed_total", "Failed creations"
        )
        self.m_cull = reg.counter("notebook_culling_total", "Culled notebooks")
        self.m_last_cull = reg.gauge(
            "last_notebook_culling_timestamp_seconds",
            "Timestamp of the last notebook culling in seconds",
        )
        # spawn→ready, observed once per notebook at its FIRST ready
        # transition (creation → readyReplicas>0; restarts/resumes are
        # excluded via the Started event's dedupe count). Feeds the
        # spawn-ready-p99 SLO (utils/slo.py default_slos).
        self.m_spawn_ready = reg.histogram(
            "notebook_spawn_ready_seconds",
            "Notebook creation to first Ready (platform spawn path)",
            buckets=(0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0),
        )
        reg.register_collector(self._collect_running)
        # wire the metrics into the culler (reference metrics.go:13-20:
        # the culling counter/timestamp are the controller's metrics,
        # incremented when the cull decision fires)
        if culler is not None and getattr(culler, "m_cull", None) is None:
            culler.m_cull = self.m_cull
            culler.m_last_cull = self.m_last_cull

    def _collect_running(self):
        # notebook StatefulSets only — the old cluster-wide list copied
        # every StatefulSet per scrape; the Exists selector filters in
        # the store before any copy, and through the CachedClient it is
        # a zero-copy label-index union
        n = 0
        selector = {
            "matchExpressions": [
                {"key": "notebook-name", "operator": "Exists"}
            ]
        }
        for sts in self.api.list("StatefulSet", label_selector=selector):
            if obj_util.get_path(sts, "status", "readyReplicas", default=0):
                n += 1
        yield "# HELP notebook_running Number of currently running notebooks"
        yield "# TYPE notebook_running gauge"
        yield f"notebook_running {n}"

    # -- wiring -------------------------------------------------------------

    def register(self, mgr: Manager) -> None:
        ctrl = mgr.new_controller("notebook-controller", "Notebook", self.reconcile)
        ctrl.owns("StatefulSet").owns("Service")
        ctrl.watches("Pod", self._map_pod, predicate=self._pod_predicate)
        ctrl.watches("Event", self._map_event)
        if self.config.enable_sessions:
            # the checkpoint turning durable is what releases the held
            # scale-down — the suspend completes on this watch
            ctrl.watches("SessionCheckpoint", self._map_checkpoint)
        if self.config.use_istio:
            ctrl.owns("VirtualService")

    @staticmethod
    def _map_checkpoint(_etype: str, ckpt: Obj) -> list[Request]:
        name = obj_util.get_path(
            ckpt, "spec", "notebook", default=obj_util.name_of(ckpt)
        )
        return [Request(obj_util.namespace_of(ckpt), name)] if name else []

    def _pod_predicate(self, _etype: str, pod: Obj) -> bool:
        return "notebook-name" in obj_util.labels_of(pod)

    def _map_pod(self, _etype: str, pod: Obj) -> list[Request]:
        name = obj_util.labels_of(pod).get("notebook-name", "")
        return [Request(obj_util.namespace_of(pod), name)] if name else []

    def _map_event(self, etype: str, event: Obj) -> list[Request]:
        """Re-queue the Notebook named by an Event on its StatefulSet or
        Pods (reference nbNameFromInvolvedObject :653-677: strip the
        ordinal suffix and verify a Notebook of that name exists), and
        re-emit the event onto the Notebook CR itself so
        ``kubectl describe notebook`` tells the whole story (reference
        notebook_controller.go:94-118,649-723)."""
        if etype == "DELETED":
            # an event EXPIRING (store retention prune now notifies
            # DELETED) is not a fresh observation — re-mirroring it
            # would resurrect long-resolved failures with current
            # timestamps, and at the retention limit each re-emission
            # triggers another prune (a cascade)
            return []
        involved = event.get("involvedObject") or {}
        ns = involved.get("namespace", "")
        name = involved.get("name", "")
        kind = involved.get("kind", "")
        if kind == "Pod" and "-" in name:
            name = name.rsplit("-", 1)[0]
        if not ns or not name:
            return []
        try:
            notebook = self.api.get("Notebook", name, ns)
        except NotFound:
            return []
        if kind in ("StatefulSet", "Pod"):
            self._mirror_event(notebook, event)
        return [Request(ns, name)]

    def _mirror_event(self, notebook: Obj, event: Obj) -> None:
        """Copy an owned-object Warning event onto the Notebook (Normal
        events are noise at the CR level — the reference's useful signal
        is failures). Dedupe is server-side with real kube count
        semantics: an identical (reason, message, type) event already on
        the CR absorbs the re-observation as a count bump + lastTimestamp
        advance instead of a duplicate object, so a restarted controller
        replaying the Event watch cannot flood the CR, while a recurring
        failure stays visibly fresh. Events older than the CR (a
        recreated notebook inheriting stale pod events, reference
        :700-712) are skipped."""
        if event.get("type") != "Warning":
            return
        created = obj_util.meta(notebook).get("creationTimestamp", "")
        stamp = event.get("lastTimestamp") or event.get("firstTimestamp") or ""
        if created and stamp and stamp < created:
            return
        reason = event.get("reason", "")
        message = event.get("message", "")
        if not reason and not message:
            return
        name = obj_util.name_of(notebook)
        for existing in list_by_index(
            self.api,
            "Event",
            "involved",
            f"Notebook/{name}",
            namespace=obj_util.namespace_of(notebook),
        ):
            involved = existing.get("involvedObject", {})
            if (
                involved.get("kind") == "Notebook"
                and involved.get("name") == name
                and existing.get("reason") == reason
                and existing.get("message") == message
                and existing.get("type") == "Warning"
            ):
                if stamp and stamp > existing.get("lastTimestamp", ""):
                    existing = mutable(existing)
                    existing["count"] = int(existing.get("count", 1)) + 1
                    existing["lastTimestamp"] = stamp
                    try:
                        self.api.update(existing)
                    except Conflict:
                        pass  # another worker bumped it; same truth
                return
        self.api.emit_event(
            notebook,
            reason,
            message,
            event_type="Warning",
            component="notebook-controller",
        )

    # -- reconcile ----------------------------------------------------------

    def reconcile(self, req: Request) -> Result:
        try:
            # mutable(): this reconcile writes status/conditions onto
            # the in-hand object, so a cache hit takes one private copy
            # here (instead of the store's mandatory copy per get)
            notebook = mutable(self.api.get("Notebook", req.name, req.namespace))
        except NotFound:
            return Result()

        try:
            tpu = tpu_request_of(notebook)
        except ValueError as e:
            self.api.emit_event(
                notebook,
                "InvalidTPURequest",
                str(e),
                event_type="Warning",
                component="notebook-controller",
            )
            self._set_condition(notebook, "TPURequestInvalid", str(e))
            return Result()

        # suspend hold: a requested suspend keeps the pods (and the
        # Workload reservation) alive until the kernel snapshot is
        # durable — only then does the scale-down release the slice
        suspend_hold = False
        if self.config.enable_sessions:
            from odh_kubeflow_tpu import sessions

            suspend_hold = sessions.suspend_pending(
                self.api,
                notebook,
                grace_seconds=self.config.suspend_grace_seconds,
            )

        sts = self.generate_statefulset(notebook, tpu, suspend_hold=suspend_hold)
        try:
            _, created = reconcilehelper.reconcile_object(
                self.api, sts, owner=notebook
            )
            if created:
                self.m_create.inc()
                self.recorder.normal(
                    notebook, "Created", f"Created StatefulSet {req.name}"
                )
        except Exception as e:
            # the failure path probes existence (it is rare; the steady
            # state pays no extra GET): only a failed CREATE counts
            try:
                self.api.get("StatefulSet", req.name, req.namespace)
            except NotFound:
                self.m_create_failed.inc()
                self.recorder.warning(
                    notebook,
                    "FailedCreate",
                    f"Failed to create StatefulSet {req.name}: {e}",
                )
            raise

        if self.config.enable_queueing:
            self._reconcile_workload(notebook, sts)

        svc = self.generate_service(notebook, tpu)
        reconcilehelper.reconcile_object(self.api, svc, owner=notebook)
        if tpu is not None and tpu.hosts > 1:
            headless = self.generate_headless_service(notebook)
            reconcilehelper.reconcile_object(self.api, headless, owner=notebook)
        if self.config.use_istio:
            vs = self.generate_virtualservice(notebook)
            reconcilehelper.reconcile_object(self.api, vs, owner=notebook)

        self.mirror_status(notebook)

        if tpu is not None:
            slice_result = self._reconcile_slice_health(notebook, tpu)
            if slice_result is not None:
                return slice_result

        if self.config.enable_culling and self.culler is not None:
            return self.culler.reconcile_notebook(notebook)
        return Result()

    # -- gang admission (scheduling/ subsystem) -----------------------------

    def _reconcile_workload(self, notebook: Obj, sts: Obj) -> None:
        """Keep the Workload in lockstep with the generated StatefulSet
        shape. A stopped/non-TPU notebook has no Workload (deleting it
        releases the admission reservation — culled notebooks free
        their chips for the queue)."""
        name = obj_util.name_of(notebook)
        ns = obj_util.namespace_of(notebook)
        priority, pclass, resolved = resolve_priority(self.api, notebook)
        if not resolved:
            self.recorder.warning(
                notebook,
                "UnknownPriorityClass",
                f"PriorityClass {pclass!r} not found; scheduling at "
                "default priority 0",
            )
        desired = workload_from_statefulset(
            sts,
            priority=priority,
            priority_class=pclass,
            # warm-pool handout: the claimed notebook prefers the slice
            # pool its standby just freed (warmup/ subsystem)
            preferred_pool=obj_util.annotations_of(notebook).get(
                PREFERRED_POOL_ANNOTATION, ""
            ),
        )
        if desired is not None:
            # the Workload carries the notebook's spawn trace so the
            # scheduler's admission span lands in the same tree
            tid = tracing.trace_id_of(notebook)
            if tid:
                desired["metadata"].setdefault("annotations", {})[
                    tracing.TRACE_ANNOTATION
                ] = tid
        try:
            if desired is None:
                try:
                    self.api.delete("Workload", name, ns)
                except NotFound:
                    pass
                else:
                    if self.meter is not None:
                        self.meter.workload_released(
                            ns, name, reason="scale-down"
                        )
                return
            reconcilehelper.reconcile_object(self.api, desired, owner=notebook)
        except NotFound:
            # Workload kind not registered — queueing enabled without
            # the scheduling subsystem installed; degrade to the legacy
            # per-pod path rather than wedging the reconcile
            return

    # -- TPU slice health (SURVEY.md §7 hard part (d)) ----------------------

    def _reconcile_slice_health(
        self, notebook: Obj, tpu: TpuRequest
    ) -> Optional[Result]:
        """Preempted TPU slices surface as CR conditions and restart
        cleanly. A slice is a gang: one preempted host makes the whole
        SPMD group useless (jax.distributed needs every worker present),
        so recovery deletes ALL the group's pods — survivors included —
        and lets the StatefulSet re-materialise them together. The
        reference never needed this (GPUs are per-pod); preemptible
        slices are a TPU-platform fact of life."""
        name = obj_util.name_of(notebook)
        ns = obj_util.namespace_of(notebook)
        # filter in the store (before the per-object copy), not here:
        # at N notebooks this reconcile runs N times per drain, and an
        # unfiltered list would copy all N slices' pods every time
        pods = self.api.list(
            "Pod",
            namespace=ns,
            label_selector={"matchLabels": {"statefulset": name}},
        )
        failed = [
            p
            for p in pods
            if obj_util.get_path(p, "status", "phase") == "Failed"
        ]
        if failed:
            hosts = ", ".join(sorted(obj_util.name_of(p) for p in failed))
            msg = (
                f"TPU slice preempted: host pod(s) {hosts} failed; "
                "restarting the slice group atomically"
            )
            self.api.emit_event(
                notebook,
                "TPUSlicePreempted",
                msg,
                event_type="Warning",
                component="notebook-controller",
            )
            self._upsert_condition(
                notebook, "SlicePreempted", "True", "SlicePreempted", msg
            )
            for p in pods:
                try:
                    self.api.delete("Pod", obj_util.name_of(p), ns)
                except NotFound:
                    pass
            return Result(requeue_after=1.0)

        # recovery: the full gang is ready again → flip the condition
        for cond in obj_util.get_path(
            notebook, "status", "conditions", default=[]
        ) or []:
            if cond.get("type") == "SlicePreempted" and cond.get("status") == "True":
                # count live pods, not the (possibly stale) STS status:
                # right after the gang teardown the STS still reports
                # its pre-preemption readyReplicas
                running = sum(
                    1
                    for p in pods
                    if obj_util.get_path(p, "status", "phase") == "Running"
                )
                if running == tpu.hosts:
                    self._upsert_condition(
                        notebook,
                        "SlicePreempted",
                        "False",
                        "SliceRecovered",
                        f"all {tpu.hosts} slice host(s) ready",
                    )
                break
        return None

    # -- generators ---------------------------------------------------------

    def _notebook_prefix(self, notebook: Obj) -> str:
        return f"/notebook/{obj_util.namespace_of(notebook)}/{obj_util.name_of(notebook)}"

    def generate_statefulset(
        self,
        notebook: Obj,
        tpu: Optional[TpuRequest],
        suspend_hold: bool = False,
    ) -> Obj:
        name = obj_util.name_of(notebook)
        ns = obj_util.namespace_of(notebook)
        template = obj_util.deepcopy(
            obj_util.get_path(notebook, "spec", "template", default={}) or {}
        )
        pod_spec = template.setdefault("spec", {})
        containers = pod_spec.setdefault("containers", [])
        if containers:
            c0 = containers[0]
            c0["name"] = name
            c0.setdefault("workingDir", "/home/jovyan")
            c0.setdefault(
                "ports",
                [
                    {
                        "containerPort": DEFAULT_CONTAINER_PORT,
                        "name": "notebook-port",
                        "protocol": "TCP",
                    }
                ],
            )
            env = c0.setdefault("env", [])
            if not any(e.get("name") == PREFIX_ENV for e in env):
                env.append(
                    {"name": PREFIX_ENV, "value": self._notebook_prefix(notebook)}
                )

        if self.config.add_fsgroup:
            pod_spec.setdefault("securityContext", {}).setdefault(
                "fsGroup", DEFAULT_FSGROUP
            )

        stopped = (
            STOP_ANNOTATION in obj_util.annotations_of(notebook)
            and not suspend_hold
        )
        replicas = 0 if stopped else 1

        if tpu is not None:
            replicas = 0 if stopped else tpu.hosts
            self._apply_tpu_scheduling(notebook, pod_spec, tpu)
            if self.config.enable_queueing:
                # admission gate: the kubelet sim keeps these pods
                # Pending (SchedulingGated) until the slice scheduler
                # admits the gang, then binds all hosts to the recorded
                # assignment atomically
                tmeta = template.setdefault("metadata", {})
                tmeta.setdefault("annotations", {})[
                    ADMISSION_GATE_ANNOTATION
                ] = name
                tmeta.setdefault("labels", {})[WORKLOAD_LABEL] = name

        labels = {"statefulset": name, "notebook-name": name}
        template.setdefault("metadata", {}).setdefault("labels", {}).update(labels)
        # propagate the notebook's spawn trace down to its pods: the
        # kubelet's gang-bind and container-start spans key off the pod
        # annotation, so the whole spawn assembles into ONE trace. Part
        # of the desired template (not a post-hoc stamp), so the
        # reconcilehelper diff never churns on it.
        tid = tracing.trace_id_of(notebook)
        if tid:
            template["metadata"].setdefault("annotations", {}).setdefault(
                tracing.TRACE_ANNOTATION, tid
            )
        return {
            "apiVersion": "apps/v1",
            "kind": "StatefulSet",
            "metadata": {"name": name, "namespace": ns, "labels": dict(labels)},
            "spec": {
                "replicas": replicas,
                "serviceName": f"{name}-hosts" if tpu and tpu.hosts > 1 else name,
                "selector": {"matchLabels": {"statefulset": name}},
                "template": template,
            },
        }

    def _apply_tpu_scheduling(
        self, notebook: Obj, pod_spec: Obj, tpu: TpuRequest
    ) -> None:
        """The TPU replacement for the reference's GPU vendor limits
        (jwa form.py:226-252 writes nvidia.com/gpu; here the controller
        owns the full scheduling contract)."""
        name = obj_util.name_of(notebook)
        selector = pod_spec.setdefault("nodeSelector", {})
        selector[TPU_ACCEL_NODE_LABEL] = tpu.accelerator_type
        selector[TPU_TOPO_NODE_LABEL] = tpu.topology
        containers = pod_spec.get("containers") or []
        if not containers:
            return
        c0 = containers[0]
        resources = c0.setdefault("resources", {})
        limits = resources.setdefault("limits", {})
        requests = resources.setdefault("requests", {})
        limits[TPU_RESOURCE] = str(tpu.chips_per_host)
        requests[TPU_RESOURCE] = str(tpu.chips_per_host)

        env = c0.setdefault("env", [])

        def set_env(entry: Obj) -> None:
            for e in env:
                if e.get("name") == entry["name"]:
                    e.clear()
                    e.update(entry)
                    return
            env.append(entry)

        if tpu.hosts > 1:
            hosts_svc = f"{name}-hosts"
            hostnames = ",".join(
                f"{name}-{i}.{hosts_svc}" for i in range(tpu.hosts)
            )
            set_env({"name": "TPU_WORKER_HOSTNAMES", "value": hostnames})
            set_env(
                {
                    "name": "TPU_WORKER_ID",
                    "valueFrom": {
                        "fieldRef": {
                            "fieldPath": (
                                "metadata.labels['apps.kubernetes.io/pod-index']"
                            )
                        }
                    },
                }
            )
            set_env(
                {
                    "name": "JAX_COORDINATOR_ADDRESS",
                    "value": f"{name}-0.{hosts_svc}:8476",
                }
            )
            set_env({"name": "NUM_TPU_HOSTS", "value": str(tpu.hosts)})
        else:
            set_env({"name": "TPU_WORKER_ID", "value": "0"})
            set_env({"name": "TPU_WORKER_HOSTNAMES", "value": "localhost"})
        if self.config.compile_cache_mount:
            # kernels jit against the cache-service-backed mount: the
            # fleet's shared compile artifacts load instead of
            # recompiling (warmup/compilecache.py stages/ingests the
            # directory; see docs/GUIDE.md "Compilation cache & warm
            # pools")
            set_env(
                {
                    "name": "JAX_COMPILATION_CACHE_DIR",
                    "value": self.config.compile_cache_mount,
                }
            )

    def generate_service(
        self, notebook: Obj, tpu: Optional[TpuRequest] = None
    ) -> Obj:
        name = obj_util.name_of(notebook)
        ns = obj_util.namespace_of(notebook)
        ports = [
            {
                # http- prefix: Istio protocol selection
                # (reference :500-501)
                "name": f"http-{name}",
                "port": DEFAULT_SERVICE_PORT,
                "targetPort": DEFAULT_CONTAINER_PORT,
                "protocol": "TCP",
            }
        ]
        if tpu is not None:
            # the in-image tpu-activity-agent the culler probes
            # (images/jupyter-jax-tpu/tpu-activity-agent)
            ports.append(
                {
                    "name": "http-tpu-activity",
                    "port": TPU_AGENT_PORT,
                    "targetPort": TPU_AGENT_PORT,
                    "protocol": "TCP",
                }
            )
        return {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {"name": name, "namespace": ns},
            "spec": {
                "type": "ClusterIP",
                "selector": {"statefulset": name},
                "ports": ports,
            },
        }

    def generate_headless_service(self, notebook: Obj) -> Obj:
        """Stable per-host DNS for multi-host slices — the names feeding
        TPU_WORKER_HOSTNAMES."""
        name = obj_util.name_of(notebook)
        return {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {
                "name": f"{name}-hosts",
                "namespace": obj_util.namespace_of(notebook),
            },
            "spec": {
                "clusterIP": "None",
                "selector": {"statefulset": name},
                "ports": [
                    {"name": "jax-coordinator", "port": 8476, "protocol": "TCP"}
                ],
            },
        }

    def generate_virtualservice(self, notebook: Obj) -> Obj:
        name = obj_util.name_of(notebook)
        ns = obj_util.namespace_of(notebook)
        prefix = self._notebook_prefix(notebook) + "/"
        service_host = f"{name}.{ns}.svc.{self.config.cluster_domain}"
        return {
            "apiVersion": "networking.istio.io/v1beta1",
            "kind": "VirtualService",
            "metadata": {"name": f"notebook-{ns}-{name}", "namespace": ns},
            "spec": {
                "hosts": [self.config.istio_host],
                "gateways": [self.config.istio_gateway],
                "http": [
                    {
                        "match": [{"uri": {"prefix": prefix}}],
                        "rewrite": {"uri": "/"},
                        "route": [
                            {
                                "destination": {
                                    "host": service_host,
                                    "port": {"number": DEFAULT_SERVICE_PORT},
                                }
                            }
                        ],
                        "timeout": "300s",
                    }
                ],
            },
        }

    # -- status -------------------------------------------------------------

    def mirror_status(self, notebook: Obj) -> None:
        """Status from the StatefulSet + pod (reference :300-359): ready
        replicas, pod conditions, container state of the notebook
        container, error-event surfacing."""
        name = obj_util.name_of(notebook)
        ns = obj_util.namespace_of(notebook)
        prev_ready = obj_util.get_path(
            notebook, "status", "readyReplicas", default=0
        )
        status: Obj = {
            "readyReplicas": 0,
            "conditions": [],
            "containerState": {},
        }
        # the session manager's suspend/resume phase is its field, not
        # this mirror's — preserve it across the rebuild
        phase = obj_util.get_path(notebook, "status", "phase", default="")
        if phase:
            status["phase"] = phase
        # first-ever-ready marker (spawn-SLO dedupe): owned by this
        # mirror, preserved across rebuilds like phase — durable state,
        # unlike the Started event whose dedupe identity embeds the
        # ready-host count and whose retention window prunes
        first_ready = obj_util.get_path(
            notebook, "status", "firstReadyAt", default=""
        )
        if first_ready:
            status["firstReadyAt"] = first_ready
        # controller-owned conditions survive the pod-mirror rebuild
        for cond in (
            obj_util.get_path(notebook, "status", "conditions", default=[]) or []
        ):
            if cond.get("type") == "SlicePreempted":
                status["conditions"].append(cond)
        try:
            sts = self.api.get("StatefulSet", name, ns)
            status["readyReplicas"] = obj_util.get_path(
                sts, "status", "readyReplicas", default=0
            )
        except NotFound:
            pass
        try:
            pod = self.api.get("Pod", f"{name}-0", ns)
            for cond in obj_util.get_path(pod, "status", "conditions", default=[]) or []:
                status["conditions"].append(
                    {"type": cond.get("type"), "status": cond.get("status"),
                     **({"reason": cond["reason"]} if cond.get("reason") else {}),
                     **({"message": cond["message"]} if cond.get("message") else {})}
                )
            for cs in (
                obj_util.get_path(pod, "status", "containerStatuses", default=[])
                or []
            ):
                if cs.get("name") == name:
                    status["containerState"] = cs.get("state") or {}
        except NotFound:
            pass
        # ready-transition Event (0 → ready): level-triggered, so the
        # guard is the stored status — re-reconciles of a ready
        # notebook see prev_ready > 0 and stay quiet
        observe_spawn = False
        if status["readyReplicas"] and not prev_ready:
            self.recorder.normal(
                notebook,
                "Started",
                f"Notebook server started ({status['readyReplicas']} "
                "ready host(s))",
            )
            # first-EVER ready only: a stop/restart or suspend/resume
            # transition would otherwise observe creation→now and
            # poison the spawn SLO. The histogram is observed AFTER
            # the status write lands — a Conflict retry would
            # otherwise re-observe the same spawn (the marker exists
            # exactly so this fires once).
            if not first_ready:
                status["firstReadyAt"] = obj_util.now_rfc3339()
                observe_spawn = True
        if (notebook.get("status") or {}) == status:
            # steady state: the mirrored status is already what's
            # stored — skip the API round-trip entirely (the store
            # would suppress the write anyway, but only after three
            # deepcopies; at N notebooks per drain that tax dominates)
            return
        notebook["status"] = status
        if reconcilehelper.update_status_level_triggered(self.api, notebook) is None:
            return  # Conflict: the conflicting write re-enqueues this key
        if observe_spawn:
            created = obj_util.meta(notebook).get("creationTimestamp", "")
            if created:
                self.m_spawn_ready.observe(
                    max(_time.time() - obj_util.parse_rfc3339(created), 0.0)
                )

    def _set_condition(self, notebook: Obj, reason: str, message: str) -> None:
        self._upsert_condition(notebook, "Degraded", "True", reason, message)

    def _upsert_condition(
        self, notebook: Obj, ctype: str, status: str, reason: str, message: str
    ) -> None:
        conditions = notebook.setdefault("status", {}).setdefault("conditions", [])
        cond = {
            "type": ctype,
            "status": status,
            "reason": reason,
            "message": message,
        }
        for i, existing in enumerate(conditions):
            if existing.get("type") == ctype:
                conditions[i] = cond
                break
        else:
            conditions.append(cond)
        reconcilehelper.update_status_level_triggered(self.api, notebook)


def main() -> None:
    """Split-process entrypoint (manifests/notebook-controller): attach
    to $KUBE_API_URL and run the reconciler + culler forever."""
    from odh_kubeflow_tpu.machinery.runner import run_controller

    def register(api, mgr):
        from odh_kubeflow_tpu.controllers.culler import Culler, CullerConfig
        from odh_kubeflow_tpu.scheduling import register_scheduling
        from odh_kubeflow_tpu.sessions import register_sessions

        cfg = NotebookControllerConfig.from_env()
        if cfg.enable_queueing:
            register_scheduling(api)  # the remote client needs the kind
        if cfg.enable_sessions:
            register_sessions(api)
        culler = None
        if cfg.enable_culling:
            culler = Culler(
                api,
                CullerConfig(
                    cull_idle_seconds=cfg.cull_idle_seconds,
                    idleness_check_seconds=cfg.idleness_check_seconds,
                    cluster_domain=cfg.cluster_domain,
                    suspend_on_cull=cfg.enable_sessions,
                    check_tpu_duty_cycle=cfg.cull_check_tpu_duty_cycle,
                ),
            )
        # the controller's own counters must live on the registry the
        # runner serves at /metrics, not the process default
        NotebookController(
            api, cfg, registry=mgr.metrics_registry, culler=culler
        ).register(mgr)
        if cfg.enable_sessions:
            from odh_kubeflow_tpu.sessions.manager import (
                SessionConfig,
                SessionManager,
            )

            SessionManager(
                api, SessionConfig.from_env(), registry=mgr.metrics_registry
            ).register(mgr)

    run_controller("notebook-controller", register)


if __name__ == "__main__":
    main()
