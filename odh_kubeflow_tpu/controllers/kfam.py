"""kfam: profile + contributor-binding management (access-management).

Reference parity (components/access-management/kfam/): binding name
mangling bindings.go:60-77, role-name map :39-46, Create (RoleBinding +
per-user AuthorizationPolicy) :80-150, owner/admin permission gate
api_default.go:303 + informer-backed RoleBinding lookup :53-91.

This module is the service's logic; ``web/kfam.py`` wraps it with the
HTTP surface (port 8081 in the reference)."""

from __future__ import annotations

from typing import Any, Optional

from odh_kubeflow_tpu.controllers.profile import USER_HEADER
from odh_kubeflow_tpu.machinery import objects as obj_util
from odh_kubeflow_tpu.machinery.store import APIServer, AlreadyExists, Invalid, NotFound

Obj = dict[str, Any]

# kfam role name ↔ ClusterRole (bindings.go:39-46)
ROLE_MAP = {
    "admin": "kubeflow-admin",
    "edit": "kubeflow-edit",
    "view": "kubeflow-view",
}
ROLE_MAP_REVERSE = {v: k for k, v in ROLE_MAP.items()}


def binding_name(user: str, role: str) -> str:
    """user-<mangled-email>-clusterrole-<role> (bindings.go:60-77)."""
    mangled = user.replace("@", "-").replace(".", "-").lower()
    return f"user-{mangled}-clusterrole-kubeflow-{role}"


class KfamService:
    def __init__(self, api: APIServer, cluster_admins: Optional[set[str]] = None):
        self.api = api
        self.cluster_admins = cluster_admins or set()

    # -- permission gate -----------------------------------------------------

    def is_cluster_admin(self, user: str) -> bool:
        return user in self.cluster_admins

    def is_owner_or_admin(self, user: str, namespace: str) -> bool:
        if self.is_cluster_admin(user):
            return True
        try:
            profile = self.api.get("Profile", namespace)
        except NotFound:
            return False
        owner = obj_util.get_path(profile, "spec", "owner", "name", default="")
        if owner == user:
            return True
        for rb in self.api.list("RoleBinding", namespace=namespace):
            if obj_util.get_path(rb, "roleRef", "name") != "kubeflow-admin":
                continue
            for s in rb.get("subjects") or []:
                if s.get("kind") == "User" and s.get("name") == user:
                    return True
        return False

    def has_binding(self, user: str, namespace: str) -> bool:
        """Any kfam-managed binding for ``user`` in ``namespace`` —
        contributors see read-only namespace panels (quota, activities)
        the owner does."""
        return bool(self.list_bindings(namespace=namespace, user=user))

    # -- profiles ------------------------------------------------------------

    def create_profile(self, profile: Obj) -> Obj:
        return self.api.create(profile)

    def delete_profile(self, name: str, requester: str) -> None:
        if not self.is_owner_or_admin(requester, name):
            raise Invalid(f"{requester} may not delete profile {name}")
        self.api.delete("Profile", name)

    def list_profiles(self) -> list[Obj]:
        return self.api.list("Profile")

    # -- bindings ------------------------------------------------------------

    def create_binding(self, binding: Obj, requester: str) -> None:
        """binding = {user: Subject, referredNamespace, RoleRef}."""
        namespace = binding.get("referredNamespace", "")
        if not namespace:
            raise Invalid("referredNamespace required")
        if not self.is_owner_or_admin(requester, namespace):
            raise Invalid(
                f"{requester} is neither owner nor admin of {namespace}"
            )
        user = obj_util.get_path(binding, "user", "name", default="")
        role_ref = binding.get("roleRef") or {}
        role = ROLE_MAP_REVERSE.get(role_ref.get("name", ""), "")
        if not user or not role:
            raise Invalid("binding needs user.name and a known roleRef")

        rb = {
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "RoleBinding",
            "metadata": {
                "name": binding_name(user, role),
                "namespace": namespace,
                "annotations": {"role": role, "user": user},
            },
            "subjects": [
                {
                    "kind": "User",
                    "name": user,
                    "apiGroup": "rbac.authorization.k8s.io",
                }
            ],
            "roleRef": {
                "apiGroup": "rbac.authorization.k8s.io",
                "kind": "ClusterRole",
                "name": ROLE_MAP[role],
            },
        }
        try:
            self.api.create(rb)
        except AlreadyExists:
            pass
        # per-user istio AuthorizationPolicy (bindings.go:80-95)
        policy = {
            "apiVersion": "security.istio.io/v1beta1",
            "kind": "AuthorizationPolicy",
            "metadata": {
                "name": binding_name(user, role),
                "namespace": namespace,
                "annotations": {"role": role, "user": user},
            },
            "spec": {
                "rules": [
                    {
                        "when": [
                            {
                                "key": f"request.headers[{USER_HEADER}]",
                                "values": [user],
                            }
                        ]
                    }
                ]
            },
        }
        try:
            self.api.create(policy)
        except AlreadyExists:
            pass

    def delete_binding(self, binding: Obj, requester: str) -> None:
        namespace = binding.get("referredNamespace", "")
        if not self.is_owner_or_admin(requester, namespace):
            raise Invalid(
                f"{requester} is neither owner nor admin of {namespace}"
            )
        user = obj_util.get_path(binding, "user", "name", default="")
        role = ROLE_MAP_REVERSE.get(
            obj_util.get_path(binding, "roleRef", "name", default=""), ""
        )
        name = binding_name(user, role)
        for kind in ("RoleBinding", "AuthorizationPolicy"):
            try:
                self.api.delete(kind, name, namespace)
            except NotFound:
                pass

    def list_bindings(
        self, namespace: Optional[str] = None, user: Optional[str] = None
    ) -> list[Obj]:
        out = []
        for rb in self.api.list("RoleBinding", namespace=namespace):
            ann = obj_util.annotations_of(rb)
            if "user" not in ann or "role" not in ann:
                continue  # not a kfam-managed binding
            if user and ann["user"] != user:
                continue
            out.append(
                {
                    "user": {"kind": "User", "name": ann["user"]},
                    "referredNamespace": obj_util.namespace_of(rb),
                    "roleRef": {
                        "apiGroup": "rbac.authorization.k8s.io",
                        "kind": "ClusterRole",
                        "name": ROLE_MAP.get(ann["role"], ann["role"]),
                    },
                }
            )
        return out

    def namespaces_for_user(self, user: str) -> list[str]:
        """Namespaces where the user is owner or contributor — what the
        spawner's namespace dropdown shows."""
        namespaces = set()
        for profile in self.api.list("Profile"):
            owner = obj_util.get_path(profile, "spec", "owner", "name", default="")
            if owner == user:
                namespaces.add(obj_util.name_of(profile))
        for b in self.list_bindings(user=user):
            namespaces.add(b["referredNamespace"])
        return sorted(namespaces)
