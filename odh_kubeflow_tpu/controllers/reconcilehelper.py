"""Create-or-update helpers with field-copy semantics.

Parity with the reference's common/reconcilehelper/util.go:18-219:
create if missing; otherwise copy only the fields a controller owns
(labels, annotations, replicas, pod template / spec) so server-managed
fields (clusterIP, status) survive, and only write when something
changed (level-triggered idempotence)."""

from __future__ import annotations

from typing import Any, Callable, Optional

from odh_kubeflow_tpu.machinery import backoff, objects as obj_util
from odh_kubeflow_tpu.machinery.objects import FrozenObjectError, mutable
from odh_kubeflow_tpu.machinery.store import APIServer, Conflict, NotFound

Obj = dict[str, Any]


def _copy_meta(dst: Obj, src: Obj) -> bool:
    changed = False
    for field in ("labels", "annotations"):
        want = obj_util.meta(src).get(field) or {}
        have = obj_util.meta(dst).get(field) or {}
        if want != have:
            obj_util.meta(dst)[field] = dict(want)
            changed = True
    return changed


def copy_statefulset_fields(desired: Obj, current: Obj) -> bool:
    changed = _copy_meta(current, desired)
    for path in (("spec", "replicas"), ("spec", "template"), ("spec", "serviceName")):
        want = obj_util.get_path(desired, *path)
        have = obj_util.get_path(current, *path)
        if want != have:
            cur = current
            for p in path[:-1]:
                cur = cur.setdefault(p, {})
            cur[path[-1]] = want
            changed = True
    return changed


def copy_deployment_fields(desired: Obj, current: Obj) -> bool:
    return copy_statefulset_fields(desired, current)


def copy_service_fields(desired: Obj, current: Obj) -> bool:
    """Service: keep clusterIP (server-assigned), copy ports/selector."""
    changed = _copy_meta(current, desired)
    want_spec = dict(desired.get("spec") or {})
    have_spec = current.setdefault("spec", {})
    if "clusterIP" in have_spec:
        want_spec["clusterIP"] = have_spec["clusterIP"]
    if want_spec != have_spec:
        current["spec"] = want_spec
        changed = True
    return changed


def copy_spec_wholesale(desired: Obj, current: Obj) -> bool:
    changed = _copy_meta(current, desired)
    if desired.get("spec") != current.get("spec"):
        current["spec"] = obj_util.deepcopy(desired.get("spec") or {})
        changed = True
    return changed


_COPIERS: dict[str, Callable[[Obj, Obj], bool]] = {
    "StatefulSet": copy_statefulset_fields,
    "Deployment": copy_deployment_fields,
    "Service": copy_service_fields,
}


def update_status_level_triggered(api: APIServer, obj: Obj) -> Optional[Obj]:
    """Status-mirror write under the PR-5 posture: a Conflict means the
    object moved under us — the conflicting write's own watch event
    re-enqueues the key and the next reconcile mirrors from fresh
    state, so retrying the stale resourceVersion in place cannot land
    and the Conflict is absorbed (``None`` returned) instead of
    surfacing as a reconcile error. On success the in-hand object's
    resourceVersion is refreshed for follow-up status writes in the
    same reconcile, and the updated object is returned."""
    try:
        updated = api.update_status(obj)
    except Conflict:
        return None
    obj["metadata"]["resourceVersion"] = updated["metadata"]["resourceVersion"]
    return updated


def _reconcile_attempt(
    api: APIServer, desired: Obj, copier: Callable[[Obj, Obj], bool]
) -> tuple[Obj, bool]:
    """One full create-or-update pass: read fresh, copy owned fields,
    write. A Conflict re-runs the WHOLE pass (fresh read included) via
    the retry wrapper in :func:`reconcile_object` — retrying just the
    write would re-send the stale resourceVersion forever."""
    kind = desired.get("kind", "")
    meta = desired.get("metadata", {})
    try:
        current = api.get(kind, meta.get("name", ""), meta.get("namespace"))
    except NotFound:
        return api.create(desired), True
    # copy-on-write against the shared cache: run the copier on the
    # (possibly frozen) cached object; the steady state — nothing
    # to change — completes with ZERO copies. Only when the copier
    # actually needs to write does the frozen object raise, and we
    # retry on a private mutable copy.
    try:
        changed = copier(desired, current)
    except FrozenObjectError:
        current = mutable(current)
        changed = copier(desired, current)
    if changed:
        return api.update(current), False
    return current, False


def reconcile_object(
    api: APIServer,
    desired: Obj,
    owner: Optional[Obj] = None,
    copier: Optional[Callable[[Obj, Obj], bool]] = None,
) -> tuple[Obj, bool]:
    """Create ``desired`` (with controller ownerReference) or update the
    existing object using the kind-appropriate field copier. Conflicts
    re-run the read-merge-write through ``machinery.backoff`` (jittered
    exponential delays, capped attempts — the PR-5 retry policy; the
    error-contract lint holds every reconcile path to it). Returns
    ``(object, created)`` — the flag lets callers count/emit on first
    materialisation without a pre-flight existence GET."""
    if owner is not None:
        obj_util.set_controller_reference(desired, owner)
    copier = copier or _COPIERS.get(desired.get("kind", ""), copy_spec_wholesale)
    return backoff.retry(
        lambda: _reconcile_attempt(api, desired, copier),
        retryable=Conflict,
        attempts=4,
        base=0.01,
        cap=0.5,
    )
