"""profile-controller: multi-tenancy — Profile CR → namespace + RBAC +
authz policy + TPU-chip ResourceQuota + cloud-credential plugins.

Reference parity (components/profile-controller/controllers/
profile_controller.go): Reconcile :105-322, namespace create + owner
guard :127-198, AuthorizationPolicy :407-472, SA+rolebinding helpers
:559-639, quota :526-557, plugin dispatch :643-675, finalizer :284-319,
default-labels live reload :356-387 + readDefaultLabelsFromFile
:743-758. Plugins: plugin_iam.go:22-80, plugin_workload_identity.go
:32-52.

TPU-first: ``kf-resource-quota`` speaks ``requests.google.com/tpu`` —
per-namespace TPU chip budgeting is the platform's quota story
(BASELINE config #5)."""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Optional

from odh_kubeflow_tpu.controllers import reconcilehelper
from odh_kubeflow_tpu.controllers.runtime import Manager, Request, Result
from odh_kubeflow_tpu.machinery import objects as obj_util
from odh_kubeflow_tpu.machinery.objects import mutable
from odh_kubeflow_tpu.machinery.store import APIServer, NotFound
from odh_kubeflow_tpu.utils import prometheus

Obj = dict[str, Any]

PROFILE_FINALIZER = "profile-finalizer.kubeflow.org"
OWNER_ANNOTATION = "owner"
QUOTA_NAME = "kf-resource-quota"
# the TPU-chip quota key injected into kf-resource-quota — the
# profile-controller manifest sets QUOTA_TPU_KEY (reference
# profile_controller.go:253-268 generalized)
TPU_QUOTA_KEY = os.environ.get("QUOTA_TPU_KEY", "requests.google.com/tpu")
USER_HEADER = os.environ.get("USERID_HEADER", "kubeflow-userid")
DEFAULT_EDITOR = "default-editor"
DEFAULT_VIEWER = "default-viewer"
ADMIN_ROLE = "kubeflow-admin"
EDIT_ROLE = "kubeflow-edit"
VIEW_ROLE = "kubeflow-view"


def _stamp_editor_sa(api: APIServer, ns: str, key: str, value: str) -> None:
    """Annotate the namespace's default-editor ServiceAccount through
    ``patch`` — the server-side guaranteedUpdate shape (read-merge-write
    with Conflict retries, the error-contract policy anchor), so a race
    with another controller stamping the same SA never surfaces."""
    api.patch(
        "ServiceAccount",
        DEFAULT_EDITOR,
        {"metadata": {"annotations": {key: value}}},
        ns,
    )


class ProfilePlugin:
    """Apply/Revoke contract (profile_controller.go:77-83); revoke must
    be idempotent."""

    kind = ""

    def apply(self, api: APIServer, profile: Obj, spec: Obj) -> None:
        raise NotImplementedError

    def revoke(self, api: APIServer, profile: Obj, spec: Obj) -> None:
        raise NotImplementedError


class GcpWorkloadIdentityPlugin(ProfilePlugin):
    """Binds the namespace's default-editor KSA to a GCP service account
    (plugin_workload_identity.go:32-52). The IAM mutation goes through
    an injectable client so tests (and clusters without egress) stub it."""

    kind = "WorkloadIdentity"

    def __init__(self, iam_client: Optional[Callable[[str, str, str], None]] = None):
        # iam_client(gcp_sa, member, action) — action add|remove
        self.iam_client = iam_client or (lambda *a: None)

    def apply(self, api: APIServer, profile: Obj, spec: Obj) -> None:
        gcp_sa = spec.get("gcpServiceAccount", "")
        ns = obj_util.name_of(profile)
        # protocol-ok: read by GKE workload identity, not package code
        _stamp_editor_sa(api, ns, "iam.gke.io/gcp-service-account", gcp_sa)
        member = f"serviceAccount:{ns}.svc.id.goog[{ns}/{DEFAULT_EDITOR}]"
        self.iam_client(gcp_sa, member, "add")

    def revoke(self, api: APIServer, profile: Obj, spec: Obj) -> None:
        gcp_sa = spec.get("gcpServiceAccount", "")
        ns = obj_util.name_of(profile)
        member = f"serviceAccount:{ns}.svc.id.goog[{ns}/{DEFAULT_EDITOR}]"
        self.iam_client(gcp_sa, member, "remove")


class AwsIamForServiceAccountPlugin(ProfilePlugin):
    kind = "AwsIamForServiceAccount"

    def __init__(self, iam_client: Optional[Callable[[str, str, str], None]] = None):
        self.iam_client = iam_client or (lambda *a: None)

    def apply(self, api: APIServer, profile: Obj, spec: Obj) -> None:
        arn = spec.get("awsIamRole", "")
        ns = obj_util.name_of(profile)
        # protocol-ok: read by the EKS pod-identity webhook
        _stamp_editor_sa(api, ns, "eks.amazonaws.com/role-arn", arn)
        self.iam_client(arn, f"{ns}/{DEFAULT_EDITOR}", "add")

    def revoke(self, api: APIServer, profile: Obj, spec: Obj) -> None:
        arn = spec.get("awsIamRole", "")
        ns = obj_util.name_of(profile)
        self.iam_client(arn, f"{ns}/{DEFAULT_EDITOR}", "remove")


class ProfileController:
    def __init__(
        self,
        api: APIServer,
        default_labels: Optional[dict[str, str]] = None,
        labels_path: Optional[str] = None,
        plugins: Optional[dict[str, ProfilePlugin]] = None,
        registry: Optional[prometheus.Registry] = None,
    ):
        self.api = api
        self.labels_path = labels_path
        self._default_labels = default_labels or {
            "istio-injection": "enabled",
            # protocol-ok: consumed by the external katib webhook
            "katib.kubeflow.org/metrics-collector-injection": "enabled",
        }
        self.plugins = plugins or {
            "WorkloadIdentity": GcpWorkloadIdentityPlugin(),
            "AwsIamForServiceAccount": AwsIamForServiceAccountPlugin(),
        }
        reg = registry or prometheus.default_registry
        self.m_requests = reg.counter(
            "profile_controller_requests_total", "Profile reconcile requests"
        )
        self.m_errors = reg.counter(
            "profile_controller_errors_total", "Profile reconcile errors"
        )
        self._labels_mtime: Optional[float] = None

    def register(self, mgr: Manager) -> None:
        ctrl = mgr.new_controller("profile-controller", "Profile", self.reconcile)
        ctrl.owns("Namespace")
        ctrl.owns("AuthorizationPolicy")
        ctrl.owns("ServiceAccount")
        ctrl.owns("RoleBinding")
        ctrl.owns("ResourceQuota")

    # -- default labels live reload ------------------------------------------

    def default_labels(self) -> dict[str, str]:
        """Re-read the labels file when it changed (the fsnotify watch
        in the reference, :356-387, polled here)."""
        if not self.labels_path:
            return dict(self._default_labels)
        try:
            mtime = os.path.getmtime(self.labels_path)
            if mtime != self._labels_mtime:
                with open(self.labels_path) as f:
                    self._default_labels = json.load(f)
                self._labels_mtime = mtime
        except OSError:
            pass
        return dict(self._default_labels)

    def reconcile_all(self) -> None:
        for profile in self.api.list("Profile"):
            self.reconcile(Request("", obj_util.name_of(profile)))

    # -- reconcile ----------------------------------------------------------

    def reconcile(self, req: Request) -> Result:
        self.m_requests.inc()
        try:
            # mutable(): the finalizer add/remove edits the in-hand object
            profile = mutable(self.api.get("Profile", req.name))
        except NotFound:
            return Result()

        meta = obj_util.meta(profile)
        if meta.get("deletionTimestamp"):
            self._run_plugins(profile, revoke=True)
            if PROFILE_FINALIZER in (meta.get("finalizers") or []):
                meta["finalizers"] = [
                    f for f in meta["finalizers"] if f != PROFILE_FINALIZER
                ]
                # a Conflict re-enqueues this Profile; the strip is
                # idempotent on the next pass
                self.api.update(profile)  # contract-ok: level-triggered
            return Result()

        if PROFILE_FINALIZER not in (meta.get("finalizers") or []):
            meta.setdefault("finalizers", []).append(PROFILE_FINALIZER)
            # a Conflict re-enqueues this Profile; the stamp is
            # idempotent on the next pass
            profile = self.api.update(profile)  # contract-ok: level-triggered

        try:
            self._reconcile_namespace(profile)
            self._reconcile_authorization_policy(profile)
            self._reconcile_service_accounts(profile)
            self._reconcile_owner_rolebinding(profile)
            self._reconcile_quota(profile)
            self._run_plugins(profile, revoke=False)
        except Exception:
            self.m_errors.inc(labels={"severity": "major"})
            raise
        return Result()

    def _owner_email(self, profile: Obj) -> str:
        return obj_util.get_path(profile, "spec", "owner", "name", default="")

    def _reconcile_namespace(self, profile: Obj) -> None:
        name = obj_util.name_of(profile)
        labels = self.default_labels()
        # protocol-ok: standard grouping label read by dashboards/kubectl
        labels["app.kubernetes.io/part-of"] = "kubeflow-profile"
        labels["kubernetes.io/metadata.name"] = name
        ns = {
            "apiVersion": "v1",
            "kind": "Namespace",
            "metadata": {
                "name": name,
                "labels": labels,
                "annotations": {OWNER_ANNOTATION: self._owner_email(profile)},
            },
        }
        try:
            existing = self.api.get("Namespace", name)
            # ownership guard (:169-198): a namespace not created by this
            # profile must not be captured
            owner_ann = obj_util.annotations_of(existing).get(OWNER_ANNOTATION)
            refs = obj_util.meta(existing).get("ownerReferences") or []
            owned = any(
                r.get("uid") == obj_util.meta(profile).get("uid") for r in refs
            )
            if not owned and owner_ann != self._owner_email(profile):
                raise RuntimeError(
                    f"namespace {name} exists and is not owned by profile"
                )
        except NotFound:
            pass
        reconcilehelper.reconcile_object(
            self.api, ns, owner=profile, copier=self._ns_copier
        )

    @staticmethod
    def _ns_copier(desired: Obj, current: Obj) -> bool:
        changed = False
        cur_labels = obj_util.meta(current).setdefault("labels", {})
        for k, v in obj_util.labels_of(desired).items():
            if cur_labels.get(k) != v:
                cur_labels[k] = v
                changed = True
        cur_ann = obj_util.meta(current).setdefault("annotations", {})
        for k, v in obj_util.annotations_of(desired).items():
            if cur_ann.get(k) != v:
                cur_ann[k] = v
                changed = True
        return changed

    def _reconcile_authorization_policy(self, profile: Obj) -> None:
        """User-header match + same-ns + probe paths + the notebook
        controller's kernels GET (:407-472)."""
        name = obj_util.name_of(profile)
        policy = {
            "apiVersion": "security.istio.io/v1beta1",
            "kind": "AuthorizationPolicy",
            "metadata": {"name": f"ns-owner-access-istio", "namespace": name},
            "spec": {
                "rules": [
                    {
                        "when": [
                            {
                                "key": f"request.headers[{USER_HEADER}]",
                                "values": [self._owner_email(profile)],
                            }
                        ]
                    },
                    {
                        "from": [
                            {"source": {"namespaces": [name]}}
                        ]
                    },
                    {
                        "to": [
                            {
                                "operation": {
                                    "paths": [
                                        "/healthz",
                                        "/metrics",
                                        "/wait-for-drain",
                                    ]
                                }
                            }
                        ]
                    },
                    {
                        "to": [
                            {
                                "operation": {
                                    "methods": ["GET"],
                                    "paths": ["*/api/kernels"],
                                }
                            }
                        ]
                    },
                ]
            },
        }
        reconcilehelper.reconcile_object(self.api, policy, owner=profile)

    def _reconcile_service_accounts(self, profile: Obj) -> None:
        ns = obj_util.name_of(profile)
        for sa_name, role in ((DEFAULT_EDITOR, EDIT_ROLE), (DEFAULT_VIEWER, VIEW_ROLE)):
            sa = {
                "apiVersion": "v1",
                "kind": "ServiceAccount",
                "metadata": {"name": sa_name, "namespace": ns},
            }
            reconcilehelper.reconcile_object(
                self.api, sa, owner=profile, copier=lambda d, c: False
            )
            rb = {
                "apiVersion": "rbac.authorization.k8s.io/v1",
                "kind": "RoleBinding",
                "metadata": {"name": sa_name, "namespace": ns},
                "subjects": [
                    {"kind": "ServiceAccount", "name": sa_name, "namespace": ns}
                ],
                "roleRef": {
                    "apiGroup": "rbac.authorization.k8s.io",
                    "kind": "ClusterRole",
                    "name": role,
                },
            }
            reconcilehelper.reconcile_object(self.api, rb, owner=profile)

    def _reconcile_owner_rolebinding(self, profile: Obj) -> None:
        ns = obj_util.name_of(profile)
        rb = {
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "RoleBinding",
            "metadata": {"name": "namespaceAdmin", "namespace": ns},
            "subjects": [
                {
                    "kind": obj_util.get_path(
                        profile, "spec", "owner", "kind", default="User"
                    ),
                    "name": self._owner_email(profile),
                    "apiGroup": "rbac.authorization.k8s.io",
                }
            ],
            "roleRef": {
                "apiGroup": "rbac.authorization.k8s.io",
                "kind": "ClusterRole",
                "name": ADMIN_ROLE,
            },
        }
        reconcilehelper.reconcile_object(self.api, rb, owner=profile)

    def _reconcile_quota(self, profile: Obj) -> None:
        spec = obj_util.get_path(
            profile, "spec", "resourceQuotaSpec", default={}
        ) or {}
        ns = obj_util.name_of(profile)
        if not spec.get("hard"):
            try:
                self.api.delete("ResourceQuota", QUOTA_NAME, ns)
            except NotFound:
                pass
            return
        quota = {
            "apiVersion": "v1",
            "kind": "ResourceQuota",
            "metadata": {"name": QUOTA_NAME, "namespace": ns},
            "spec": obj_util.deepcopy(spec),
        }
        reconcilehelper.reconcile_object(self.api, quota, owner=profile)

    def _run_plugins(self, profile: Obj, revoke: bool) -> None:
        for plugin_spec in (
            obj_util.get_path(profile, "spec", "plugins", default=[]) or []
        ):
            kind = plugin_spec.get("kind", "")
            plugin = self.plugins.get(kind)
            if plugin is None:
                continue
            spec = plugin_spec.get("spec") or {}
            if revoke:
                plugin.revoke(self.api, profile, spec)
            else:
                plugin.apply(self.api, profile, spec)


def plugins_from_env() -> dict[str, ProfilePlugin]:
    """Real cloud-IAM clients when the deployment configures them
    (reference behavior: plugin_workload_identity.go calls the Google
    IAM API, plugin_iam.go edits the AWS trust policy); annotation-only
    no-op clients otherwise (clusters without egress / tests)."""
    import os

    plugins: dict[str, ProfilePlugin] = {}
    if os.environ.get("GCP_IAM_ENABLE", "").lower() == "true":
        from odh_kubeflow_tpu.machinery.cloudiam import GcpIamClient

        token_path = os.environ.get(
            "GCP_TOKEN_PATH",
            "/var/run/secrets/kubernetes.io/serviceaccount/token",
        )

        def token_fn() -> str:
            try:
                with open(token_path) as f:
                    return f.read().strip()
            except OSError:
                return ""

        plugins["WorkloadIdentity"] = GcpWorkloadIdentityPlugin(
            iam_client=GcpIamClient(token_fn=token_fn)
        )
    else:
        plugins["WorkloadIdentity"] = GcpWorkloadIdentityPlugin()

    oidc_arn = os.environ.get("AWS_OIDC_PROVIDER_ARN", "")
    if oidc_arn:
        from odh_kubeflow_tpu.machinery.cloudiam import AwsIamClient

        plugins["AwsIamForServiceAccount"] = AwsIamForServiceAccountPlugin(
            iam_client=AwsIamClient(
                oidc_provider_arn=oidc_arn,
                issuer_host=os.environ.get("AWS_OIDC_ISSUER_HOST", ""),
                access_key=os.environ.get("AWS_ACCESS_KEY_ID", ""),
                secret_key=os.environ.get("AWS_SECRET_ACCESS_KEY", ""),
                session_token=os.environ.get("AWS_SESSION_TOKEN", ""),
                region=os.environ.get("AWS_REGION", "us-east-1"),
            )
        )
    else:
        plugins["AwsIamForServiceAccount"] = AwsIamForServiceAccountPlugin()
    return plugins


def main() -> None:
    """Split-process entrypoint (manifests/profile-controller)."""
    import os

    from odh_kubeflow_tpu.machinery.runner import run_controller

    run_controller(
        "profile-controller",
        lambda api, mgr: ProfileController(
            api,
            labels_path=os.environ.get("NAMESPACE_LABELS_PATH"),
            plugins=plugins_from_env(),
        ).register(mgr),
    )


if __name__ == "__main__":
    main()
