from odh_kubeflow_tpu.controllers.runtime import (  # noqa: F401
    Controller,
    Manager,
    Request,
    Result,
)
