"""tensorboard-controller: Tensorboard CR → Deployment + Service + route.

Reference parity (components/tensorboard-controller/controllers/
tensorboard_controller.go): Reconcile :67-149, generateDeployment
:159-284 (image from TENSORBOARD_IMAGE :164, gs:// secret mount
:224-239, RWO-PVC co-scheduling affinity :199-223 + :408-451 gated by
RWO_PVC_SCHEDULING :456-466), logspath parsing :360-390, VirtualService
with 300s timeout :306-358.

TPU-first: ``gs://`` logdirs are the *primary* path — serving XLA/TPU
profiler traces from GCS is BASELINE config #3. The deployment sets
the profile-plugin flag and uses workload identity (the namespace
``default-editor`` KSA from the profile controller) instead of mounting
a ``user-gcp-sa`` key secret; the reference's secret mount remains as a
fallback when the annotation asks for it."""

from __future__ import annotations

import os
from typing import Any, Optional

from odh_kubeflow_tpu.controllers import reconcilehelper
from odh_kubeflow_tpu.controllers.runtime import Manager, Request, Result
from odh_kubeflow_tpu.machinery import objects as obj_util
from odh_kubeflow_tpu.machinery.cache import list_by_index
from odh_kubeflow_tpu.machinery.events import EventRecorder
from odh_kubeflow_tpu.machinery.objects import mutable
from odh_kubeflow_tpu.machinery.store import APIServer, NotFound

Obj = dict[str, Any]

DEFAULT_IMAGE = "tensorflow/tensorflow:2.15.0"
GCP_SA_SECRET_ANNOTATION = "tensorboards.kubeflow.org/gcp-sa-secret"


class TensorboardController:
    def __init__(self, api: APIServer):
        self.api = api
        self.image = os.environ.get("TENSORBOARD_IMAGE", DEFAULT_IMAGE)
        self.rwo_scheduling = (
            os.environ.get("RWO_PVC_SCHEDULING", "true").lower() == "true"
        )
        self.recorder = EventRecorder(api, "tensorboard-controller")

    def register(self, mgr: Manager) -> None:
        ctrl = mgr.new_controller(
            "tensorboard-controller", "Tensorboard", self.reconcile
        )
        ctrl.owns("Deployment").owns("Service").owns("HTTPRoute")

    def reconcile(self, req: Request) -> Result:
        try:
            # mutable(): _mirror_status writes onto the in-hand object
            tb = mutable(self.api.get("Tensorboard", req.name, req.namespace))
        except NotFound:
            return Result()
        deployment = self.generate_deployment(tb)
        try:
            _, created = reconcilehelper.reconcile_object(
                self.api, deployment, owner=tb
            )
            if created:
                self.recorder.normal(
                    tb, "Created", f"Created Deployment {req.name}"
                )
        except Exception as e:
            try:
                self.api.get("Deployment", req.name, req.namespace)
            except NotFound:
                self.recorder.warning(
                    tb,
                    "FailedCreate",
                    f"Failed to create Deployment {req.name}: {e}",
                )
            raise
        service = self.generate_service(tb)
        reconcilehelper.reconcile_object(self.api, service, owner=tb)
        route = self.generate_route(tb)
        reconcilehelper.reconcile_object(self.api, route, owner=tb)
        self._mirror_status(tb)
        return Result()

    # -- logspath parsing (:360-390) ----------------------------------------

    @staticmethod
    def parse_logspath(path: str) -> dict[str, str]:
        if path.startswith("pvc://"):
            rest = path[len("pvc://") :]
            pvc, _, sub = rest.partition("/")
            return {"kind": "pvc", "pvc": pvc, "subpath": sub}
        if path.startswith("gs://"):
            return {"kind": "gcs", "path": path}
        if path.startswith("s3://"):
            return {"kind": "s3", "path": path}
        return {"kind": "local", "path": path}

    # -- generators ---------------------------------------------------------

    def generate_deployment(self, tb: Obj) -> Obj:
        name = obj_util.name_of(tb)
        ns = obj_util.namespace_of(tb)
        logspath = obj_util.get_path(tb, "spec", "logspath", default="")
        parsed = self.parse_logspath(logspath)

        container: Obj = {
            "name": "tensorboard",
            "image": self.image,
            "command": ["/usr/local/bin/tensorboard"],
            "args": [
                f"--logdir={logspath}",
                "--bind_all",
                "--port=6006",
                # XLA/TPU profiler traces (BASELINE config #3)
                "--load_fast=false",
            ],
            "ports": [{"containerPort": 6006, "name": "http", "protocol": "TCP"}],
            "resources": {
                "requests": {"cpu": "250m", "memory": "1Gi"},
                "limits": {"cpu": "2", "memory": "4Gi"},
            },
        }
        pod_spec: Obj = {"containers": [container]}

        if parsed["kind"] == "pvc":
            container["args"][0] = "--logdir=/logs/" + parsed["subpath"]
            container["volumeMounts"] = [{"name": "logs", "mountPath": "/logs"}]
            pod_spec["volumes"] = [
                {
                    "name": "logs",
                    "persistentVolumeClaim": {"claimName": parsed["pvc"]},
                }
            ]
            if self.rwo_scheduling:
                affinity = self._rwo_affinity(ns, parsed["pvc"])
                if affinity:
                    pod_spec["affinity"] = affinity
        elif parsed["kind"] == "gcs":
            # workload identity first; key-secret fallback by annotation
            pod_spec["serviceAccountName"] = "default-editor"
            # protocol-ok: user-set on the Tensorboard; no package writer
            secret = obj_util.annotations_of(tb).get(GCP_SA_SECRET_ANNOTATION)
            if secret:
                container["volumeMounts"] = [
                    {"name": "gcp-creds", "mountPath": "/secret", "readOnly": True}
                ]
                container.setdefault("env", []).append(
                    {
                        "name": "GOOGLE_APPLICATION_CREDENTIALS",
                        "value": "/secret/key.json",
                    }
                )
                pod_spec["volumes"] = [
                    {"name": "gcp-creds", "secret": {"secretName": secret}}
                ]

        labels = {"app": name, "tensorboard": name}
        return {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {"name": name, "namespace": ns, "labels": dict(labels)},
            "spec": {
                "replicas": 1,
                "selector": {"matchLabels": {"tensorboard": name}},
                "template": {
                    "metadata": {"labels": dict(labels)},
                    "spec": pod_spec,
                },
            },
        }

    def _rwo_affinity(self, ns: str, pvc_name: str) -> Optional[Obj]:
        """Co-schedule with the pod already mounting the RWO PVC
        (:199-223,408-451): node affinity to that pod's node."""
        try:
            pvc = self.api.get("PersistentVolumeClaim", pvc_name, ns)
        except NotFound:
            return None
        modes = obj_util.get_path(pvc, "spec", "accessModes", default=[]) or []
        if "ReadWriteMany" in modes:
            return None
        # pods mounting this claim, via the ``pvc`` field index (the
        # uncached fallback still scans only the namespace)
        for pod in list_by_index(
            self.api, "Pod", "pvc", pvc_name, namespace=ns
        ):
            node = obj_util.get_path(pod, "spec", "nodeName")
            if not node:
                continue
            for vol in obj_util.get_path(pod, "spec", "volumes", default=[]) or []:
                claim = obj_util.get_path(vol, "persistentVolumeClaim", "claimName")
                if claim == pvc_name:
                    return {
                        "nodeAffinity": {
                            "requiredDuringSchedulingIgnoredDuringExecution": {
                                "nodeSelectorTerms": [
                                    {
                                        "matchExpressions": [
                                            {
                                                # protocol-ok: kubelet-owned node identity label
                                                "key": "kubernetes.io/hostname",
                                                "operator": "In",
                                                "values": [node],
                                            }
                                        ]
                                    }
                                ]
                            }
                        }
                    }
        return None

    def generate_service(self, tb: Obj) -> Obj:
        name = obj_util.name_of(tb)
        return {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {
                "name": name,
                "namespace": obj_util.namespace_of(tb),
            },
            "spec": {
                "type": "ClusterIP",
                "selector": {"tensorboard": name},
                "ports": [
                    {
                        "name": "http-tb",
                        "port": 80,
                        "targetPort": 6006,
                        "protocol": "TCP",
                    }
                ],
            },
        }

    def generate_route(self, tb: Obj) -> Obj:
        name = obj_util.name_of(tb)
        ns = obj_util.namespace_of(tb)
        return {
            "apiVersion": "gateway.networking.k8s.io/v1",
            "kind": "HTTPRoute",
            "metadata": {"name": f"tensorboard-{name}", "namespace": ns},
            "spec": {
                "parentRefs": [{"name": "kubeflow-gateway", "namespace": "kubeflow"}],
                "rules": [
                    {
                        "matches": [
                            {
                                "path": {
                                    "type": "PathPrefix",
                                    "value": f"/tensorboard/{ns}/{name}",
                                }
                            }
                        ],
                        "backendRefs": [{"name": name, "port": 80}],
                        # long profile loads (reference VS timeout :306-358)
                        "timeouts": {"request": "300s"},
                    }
                ],
            },
        }

    def _mirror_status(self, tb: Obj) -> None:
        try:
            deploy = self.api.get(
                "Deployment", obj_util.name_of(tb), obj_util.namespace_of(tb)
            )
        except NotFound:
            return
        ready = obj_util.get_path(deploy, "status", "readyReplicas", default=0)
        prev_ready = obj_util.get_path(tb, "status", "readyReplicas", default=0)
        if ready and not prev_ready:
            self.recorder.normal(tb, "Started", "Tensorboard server started")
        status = {
            "readyReplicas": ready,
            "conditions": [
                {
                    "type": "Available" if ready else "Progressing",
                    "status": "True",
                }
            ],
        }
        if (tb.get("status") or {}) == status:
            return  # steady state: skip the no-op status round-trip
        tb["status"] = status
        reconcilehelper.update_status_level_triggered(self.api, tb)


def main() -> None:
    """Split-process entrypoint (manifests/tensorboard-controller)."""
    from odh_kubeflow_tpu.machinery.runner import run_controller

    run_controller(
        "tensorboard-controller",
        lambda api, mgr: TensorboardController(api).register(mgr),
    )


if __name__ == "__main__":
    main()
