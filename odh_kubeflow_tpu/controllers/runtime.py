"""Controller runtime: level-triggered reconciliation over the APIServer.

A from-scratch controller-runtime equivalent (the reference builds every
operator on sigs.k8s.io/controller-runtime; SURVEY.md §1 L2):

- ``Controller``: owns a workqueue of (namespace, name) requests; a
  reconcile function is invoked per key, never concurrently for the
  same key, with rate-limited error backoff and ``Result.requeue_after``.
- ``For/Owns/Watches`` wiring: the primary kind enqueues itself; owned
  kinds map back through the controller ownerReference; arbitrary
  watches use a mapping function (the reference uses this for
  Event→Notebook re-emission and Pod→Notebook by label).
- ``Manager``: starts each controller's watch pumps + worker, exposes
  ``drain()`` for deterministic single-threaded tests (process every
  pending event/request until quiescent — the envtest idiom without
  sleeps).
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from odh_kubeflow_tpu.analysis import sanitizer as _sanitizer
from odh_kubeflow_tpu.machinery import objects as obj_util, overload
from odh_kubeflow_tpu.machinery.store import (
    APIServer,
    FencedOut,
    NotLeader,
    Watch,
)
from odh_kubeflow_tpu.utils import prometheus, tracing

log = logging.getLogger("controller-runtime")

Obj = dict[str, Any]

# workqueue latencies span µs (drain tests) to many seconds (backoff)
_QUEUE_BUCKETS = (
    0.0001, 0.001, 0.01, 0.1, 0.5, 1.0, 5.0, 10.0, 30.0, 60.0,
)
_RECONCILE_BUCKETS = (
    0.0001, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 30.0,
)


class RuntimeMetrics:
    """The controller-runtime metric surface (its exact metric names,
    so reference dashboards/alerts port over), labelled per controller.
    One instance per Manager; registering twice against one shared
    registry converges on the same series (Registry is get-or-create)."""

    def __init__(self, registry: prometheus.Registry):
        self.depth = registry.gauge(
            "workqueue_depth",
            "Current depth of the workqueue",
            labelnames=("name",),
        )
        self.adds = registry.counter(
            "workqueue_adds_total",
            "Total number of adds handled by the workqueue",
            labelnames=("name",),
        )
        self.queue_duration = registry.histogram(
            "workqueue_queue_duration_seconds",
            "How long a request stays in the workqueue before processing",
            buckets=_QUEUE_BUCKETS,
            labelnames=("name",),
        )
        self.reconcile_time = registry.histogram(
            "controller_runtime_reconcile_time_seconds",
            "Length of time per reconciliation",
            buckets=_RECONCILE_BUCKETS,
            labelnames=("controller",),
        )
        self.reconcile_errors = registry.counter(
            "controller_runtime_reconcile_errors_total",
            "Total number of reconciliations that returned an error",
            labelnames=("controller",),
        )
        self.reconcile_total = registry.counter(
            "controller_runtime_reconcile_total",
            "Total number of reconciliations per controller and result",
            labelnames=("controller", "result"),
        )


@dataclass(frozen=True)
class Request:
    namespace: str
    name: str


@dataclass
class Result:
    requeue_after: Optional[float] = None  # seconds


@dataclass
class _WatchSpec:
    kind: str
    map_fn: Callable[[str, Obj], list[Request]]
    predicate: Optional[Callable[[str, Obj], bool]] = None


class _RateLimiter:
    """Per-key exponential backoff: 5ms * 2^failures, capped at 16s.
    ``when``/``forget`` run from every worker thread (``_process``), so
    the failure map is guarded by its own lock."""

    def __init__(self, base: float = 0.005, cap: float = 16.0):
        self.base = base
        self.cap = cap
        self.failures: dict[Request, int] = {}
        # the PR 1 fix moved the backoff sleep OUT of this critical
        # section; the sanitizer's blocking-under-lock probe guards the
        # invariant at runtime (tests/test_analysis.py)
        self._lock = _sanitizer.new_lock("controller.ratelimiter")

    def when(self, req: Request) -> float:
        with self._lock:
            n = self.failures.get(req, 0)
            self.failures[req] = n + 1
        return min(self.base * (2**n), self.cap)

    def forget(self, req: Request) -> None:
        with self._lock:
            self.failures.pop(req, None)


class Controller:
    def __init__(
        self,
        name: str,
        api: APIServer,
        reconcile: Callable[[Request], Optional[Result]],
        for_kind: str,
        time_fn: Callable[[], float] = time.monotonic,
        workers: int = 1,
        metrics: Optional[RuntimeMetrics] = None,
        informer: Optional[Any] = None,
        fence_fn: Optional[Callable[[], Any]] = None,
        shard: Optional[Any] = None,
    ):
        self.name = name
        self.api = api
        self.reconcile = reconcile
        self.for_kind = for_kind
        self.time_fn = time_fn
        # fence_fn() returns a context manager installing the replica's
        # current lease epoch around the reconcile body, so every store
        # write it issues is fencing-token checked (a deposed replica's
        # in-flight writes are rejected, not applied). None = unfenced.
        self.fence_fn = fence_fn
        # shard (machinery.leader.ShardMembership): when set, this
        # replica only reconciles namespaces it owns under the current
        # membership — two replicas never reconcile the same object.
        self.shard = shard
        # shared informer cache (Manager-owned): kinds it serves feed
        # this controller through event handlers — one frozen copy per
        # store event for ALL controllers — instead of a private watch
        self.informer = informer
        # a standalone Controller gets a private sink registry; the
        # Manager path shares its RuntimeMetrics across controllers
        self.metrics = metrics or RuntimeMetrics(prometheus.Registry())
        self._m_depth = self.metrics.depth.labels(name=name)
        self._m_adds = self.metrics.adds.labels(name=name)
        self._m_queue_duration = self.metrics.queue_duration.labels(name=name)
        self._m_reconcile_time = self.metrics.reconcile_time.labels(
            controller=name
        )
        self._m_reconcile_errors = self.metrics.reconcile_errors.labels(
            controller=name
        )
        # MaxConcurrentReconciles: workers share the queue but a key is
        # never reconciled by two workers at once (controller-runtime
        # semantics). >1 keeps one slow reconcile — e.g. a culler probe
        # against a dead notebook burning its 5s timeout — from
        # stalling every other notebook.
        self.workers = max(int(workers), 1)
        self._inflight: set[Request] = set()
        self._watch_specs: list[_WatchSpec] = []
        self._watches: list[Watch] = []
        self._queue: list[Request] = []
        self._queued: set[Request] = set()
        self._delayed: list[tuple[float, Request]] = []
        # per-request enqueue instant (workqueue_queue_duration) and
        # the trace id carried from the triggering watch object; both
        # live under _cv with the queue itself
        self._enqueued_at: dict[Request, float] = {}
        self._req_trace: dict[Request, str] = {}
        self._lock = _sanitizer.new_lock(f"workqueue.{name}")
        self._cv = threading.Condition(self._lock)
        self._limiter = _RateLimiter()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        # latched when a reconcile write is rejected by the fencing
        # check — the observable trace of a deposed (or stalled-past-
        # expiry) epoch between the rejection and the elector's verdict
        self.fenced_out = False

        self.watches(
            for_kind,
            lambda _etype, obj: [
                Request(obj_util.namespace_of(obj), obj_util.name_of(obj))
            ],
        )

    # -- wiring -------------------------------------------------------------

    def owns(self, kind: str) -> "Controller":
        """Enqueue the owner (of ``for_kind``) of changed child objects."""

        def map_owner(_etype: str, obj: Obj) -> list[Request]:
            for ref in obj_util.meta(obj).get("ownerReferences") or []:
                if ref.get("kind") == self.for_kind and ref.get("controller", True):
                    return [Request(obj_util.namespace_of(obj), ref.get("name", ""))]
            return []

        return self.watches(kind, map_owner)

    def watches(
        self,
        kind: str,
        map_fn: Callable[[str, Obj], list[Request]],
        predicate: Optional[Callable[[str, Obj], bool]] = None,
    ) -> "Controller":
        self._watch_specs.append(_WatchSpec(kind, map_fn, predicate))
        return self

    # -- queue --------------------------------------------------------------

    def enqueue(
        self,
        req: Request,
        after: Optional[float] = None,
        trace_id: Optional[str] = None,
    ) -> None:
        with self._cv:
            if trace_id:
                self._req_trace[req] = trace_id
            if after:
                self._delayed.append((self.time_fn() + after, req))
            elif req not in self._queued:
                self._queue.append(req)
                self._queued.add(req)
                self._enqueued_at.setdefault(req, self.time_fn())
                self._m_adds.inc()
                self._m_depth.set(len(self._queue))
            self._cv.notify_all()

    def _pop(self, timeout: Optional[float]) -> Optional[Request]:
        deadline = self.time_fn() + timeout if timeout is not None else None
        with self._cv:
            while True:
                now = self.time_fn()
                ready = [d for d in self._delayed if d[0] <= now]
                for d in ready:
                    self._delayed.remove(d)
                    if d[1] not in self._queued:
                        self._queue.append(d[1])
                        self._queued.add(d[1])
                        # queue duration measures READY time: a delayed
                        # requeue starts its clock when it becomes due
                        self._enqueued_at.setdefault(d[1], now)
                        self._m_adds.inc()
                        self._m_depth.set(len(self._queue))
                # hand out the first key not currently being reconciled
                # by another worker (per-key exclusion)
                for i, req in enumerate(self._queue):
                    if req not in self._inflight:
                        self._queue.pop(i)
                        self._queued.discard(req)
                        self._inflight.add(req)
                        self._m_depth.set(len(self._queue))
                        t0 = self._enqueued_at.pop(req, None)
                        if t0 is not None:
                            self._m_queue_duration.observe(
                                max(self.time_fn() - t0, 0.0)
                            )
                        return req
                if self._stop.is_set():
                    return None
                waits = [0.05]
                if deadline is not None:
                    if now >= deadline:
                        return None
                    waits.append(deadline - now)
                if self._delayed:
                    waits.append(max(min(d[0] for d in self._delayed) - now, 0.001))
                self._cv.wait(timeout=min(waits))

    def _process(self, req: Request) -> None:
        with self._cv:
            trace_id = self._req_trace.pop(req, None)
        if self.shard is not None and not self.shard.owns(req.namespace):
            # not ours under the current membership: the owning replica
            # sees the same watch events and reconciles it. Checked at
            # process time (not enqueue) so a reshard between the two
            # re-routes instead of dropping.
            self.metrics.reconcile_total.inc(
                {"controller": self.name, "result": "sharded_out"}
            )
            self._done(req)
            self._limiter.forget(req)
            return
        key = f"{req.namespace}/{req.name}"
        start = self.time_fn()
        with tracing.span(
            "reconcile",
            trace_id=trace_id,
            controller=self.name,
            reconcile_key=key,
        ):
            try:
                fence = self.fence_fn() if self.fence_fn else contextlib.nullcontext()
                # one reconcile runs under one end-to-end deadline
                # (REQUEST_DEADLINE_DEFAULT): every API call it makes
                # carries the remaining budget, so a wedged apiserver
                # cannot pin a worker forever — the attempt 504s and
                # the error-backoff requeue takes over
                with fence, overload.deadline_scope():
                    result = self.reconcile(req) or Result()
            except (FencedOut, NotLeader) as e:
                # authority failure, not a data race (PR-8 fencing
                # rule): the write carried a stale/absent epoch and was
                # REJECTED — correctness is already protected by the
                # store, and retrying under the same fence cannot land,
                # so the key is dropped (no backoff requeue). Do NOT
                # hard-stop the controller here: a lease that merely
                # EXPIRED during a stall re-acquires with the SAME
                # token on the elector's next renew, and the next watch
                # event picks the key back up under the fresh fence. A
                # genuinely deposed replica keeps landing here (every
                # write rejected, nothing applied) only until its
                # elector observes the takeover and fires on_lost — the
                # process-exit stand-down lives THERE
                # (runner.run_controller wires on_lost → os._exit),
                # where expiry-then-renew and deposition are
                # distinguishable.
                self._m_reconcile_time.observe(self.time_fn() - start)
                self.metrics.reconcile_total.inc(
                    {"controller": self.name, "result": "fenced_out"}
                )
                tracing.set_status("error", f"{type(e).__name__}: {e}")
                self.fenced_out = True  # recorded for operators/drills
                log.error(
                    "%s: reconcile %s rejected by fencing (%s); dropping "
                    "the key without requeue — the elector owns the "
                    "stand-down decision",
                    self.name,
                    req,
                    e,
                )
                self._done(req)
                # same fresh-start posture as the sharded_out drop: the
                # key was not processed here, so stale error-backoff
                # state must not survive into its next incarnation
                self._limiter.forget(req)
                return
            except Exception as e:
                elapsed = self.time_fn() - start
                self._m_reconcile_time.observe(elapsed)
                self._m_reconcile_errors.inc()
                self.metrics.reconcile_total.inc(
                    {"controller": self.name, "result": "error"}
                )
                # the exception is handled HERE (inside the span), so
                # the span wouldn't see it escape — mark it explicitly
                # or the collector's keep-error-traces rule can't fire
                tracing.set_status(
                    "error", f"{type(e).__name__}: {e}"
                )
                log.exception("%s: reconcile %s failed", self.name, req)
                self._done(req)
                # the retry is the same unit of work: it keeps the trace
                self.enqueue(req, after=self._limiter.when(req), trace_id=trace_id)
                return
            elapsed = self.time_fn() - start
            self._m_reconcile_time.observe(elapsed)
            self.metrics.reconcile_total.inc(
                {
                    "controller": self.name,
                    "result": "requeue_after" if result.requeue_after else "success",
                }
            )
            log.debug(
                "%s: reconciled %s in %.6fs%s",
                self.name,
                key,
                elapsed,
                f" (requeue after {result.requeue_after}s)"
                if result.requeue_after
                else "",
            )
        self._done(req)
        self._limiter.forget(req)
        if result.requeue_after:
            self.enqueue(req, after=result.requeue_after)

    def _done(self, req: Request) -> None:
        with self._cv:
            self._inflight.discard(req)
            self._cv.notify_all()

    # -- event pumping ------------------------------------------------------

    def _start_watches(self) -> None:
        for spec in self._watch_specs:
            if self.informer is not None and self.informer.has_kind(spec.kind):
                # informer-fed: the shared cache pushes events (with an
                # ADDED replay of current state) — no private watch, no
                # per-controller event copy
                self.informer.add_handler(
                    spec.kind,
                    lambda etype, obj, _spec=spec: self._handle_event(
                        _spec, etype, obj
                    ),
                )
                self._watches.append(None)
            else:
                self._watches.append(self.api.watch(spec.kind))

    def _handle_event(self, spec: _WatchSpec, etype: str, obj: Obj) -> None:
        if spec.predicate and not spec.predicate(etype, obj):
            return
        # the store stamps the creating request's trace id onto the
        # object; carry it so the reconcile logs in the same trace
        trace_id = tracing.trace_id_of(obj)
        for req in spec.map_fn(etype, obj):
            if req.name:
                self.enqueue(req, trace_id=trace_id)

    def _pump_once(self, spec_idx: int, timeout: float = 0.0) -> bool:
        """Drain one event from watch ``spec_idx``; returns False if none."""
        w = self._watches[spec_idx]
        if w is None:  # informer-fed spec: events arrive via handler
            return False
        spec = self._watch_specs[spec_idx]
        item = w.get(timeout=timeout) if timeout else w.try_get()
        if item is None:
            return False
        etype, obj = item
        self._handle_event(spec, etype, obj)
        return True

    # -- execution ----------------------------------------------------------

    def start(self) -> None:
        self._start_watches()

        def pump(i: int):
            while not self._stop.is_set():
                self._pump_once(i, timeout=0.2)

        for i in range(len(self._watch_specs)):
            if self._watches[i] is None:
                continue  # informer-fed: the cache's pump delivers
            t = threading.Thread(target=pump, args=(i,), daemon=True)
            t.start()
            self._threads.append(t)
        for _ in range(self.workers):
            worker = threading.Thread(target=self._worker, daemon=True)
            worker.start()
            self._threads.append(worker)

    def _worker(self) -> None:
        while not self._stop.is_set():
            req = self._pop(timeout=0.2)
            if req is not None:
                self._process(req)

    def stop(self) -> None:
        self._stop.set()
        for w in self._watches:
            if w is not None:
                w.stop()
        with self._cv:
            self._cv.notify_all()

    # -- deterministic drain (tests) ----------------------------------------

    def drain_once(self) -> bool:
        """Pump all watch events, process all due requests. Returns True
        if anything happened."""
        if not self._watches:
            self._start_watches()
        moved = False
        if self.informer is not None and self.informer.drain_once():
            moved = True
        for i in range(len(self._watch_specs)):
            while self._pump_once(i):
                moved = True
        while True:
            with self._cv:
                has = bool(self._queue) or any(
                    d[0] <= self.time_fn() for d in self._delayed
                )
            if not has:
                break
            req = self._pop(timeout=0)
            if req is None:
                break
            self._process(req)
            moved = True
        return moved


class Manager:
    def __init__(
        self,
        api: APIServer,
        time_fn: Callable[[], float] = time.monotonic,
        registry: Optional[prometheus.Registry] = None,
        cache: Optional[Any] = None,
        elector: Optional[Any] = None,
        shard: Optional[Any] = None,
    ):
        self.api = api
        self.time_fn = time_fn
        self.controllers: list[Controller] = []
        # every controller the manager runs instruments into this one
        # registry (controller-runtime's metrics.Registry equivalent);
        # the platform serves it at /metrics
        self.metrics_registry = registry or prometheus.Registry()
        self._runtime_metrics = RuntimeMetrics(self.metrics_registry)
        # the shared informer cache (machinery.cache.InformerCache):
        # the manager owns its lifecycle — start + sync barrier before
        # any controller runs, pumped first on every drain round
        self.cache = cache
        self._cache_started = False
        # leader elector (machinery.leader.LeaderElector) and/or shard
        # membership (ShardMembership): reconciles run inside the
        # replica's fence so deposed-epoch writes are rejected by the
        # store, and — with a shard — only owned namespaces reconcile
        self.elector = elector
        self.shard = shard
        if shard is not None and hasattr(shard, "add_on_change"):
            shard.add_on_change(self._reshard_resync)

    def _reshard_resync(self, old: list[str], new: list[str]) -> None:
        """Membership changed: re-enqueue every primary object so keys
        in namespaces this replica NEWLY owns get reconciled. A peer
        that expired left no watch event behind; without this resync
        its slice would sit unreconciled until the next organic event.
        Keys still owned elsewhere are filtered at process time."""
        log.info(
            "shard membership changed %s -> %s; resyncing %d controllers",
            old,
            new,
            len(self.controllers),
        )
        for c in self.controllers:
            try:
                objs = self.api.list(c.for_kind)
            except Exception:  # noqa: BLE001 — API blip; next change retries
                log.exception("reshard resync list %s failed", c.for_kind)
                continue
            for obj in objs:
                c.enqueue(
                    Request(
                        obj_util.namespace_of(obj), obj_util.name_of(obj)
                    )
                )

    def _fence_fn(self) -> Optional[Callable[[], Any]]:
        if self.shard is not None:
            return self.shard.fence
        if self.elector is not None:
            return self.elector.fence
        return None

    def new_controller(
        self,
        name: str,
        for_kind: str,
        reconcile: Callable[[Request], Optional[Result]],
        workers: Optional[int] = None,
    ) -> Controller:
        import os

        if workers is None:
            workers = int(os.environ.get("MAX_CONCURRENT_RECONCILES", "1"))
        ctrl = Controller(
            name,
            self.api,
            reconcile,
            for_kind,
            time_fn=self.time_fn,
            workers=workers,
            metrics=self._runtime_metrics,
            informer=self.cache,
            fence_fn=self._fence_fn(),
            shard=self.shard,
        )
        self.controllers.append(ctrl)
        return ctrl

    def _ensure_cache(self, live: bool) -> None:
        if self.cache is None:
            return
        # informer start + sync barrier: controllers must never see a
        # half-primed cache (controller-runtime's WaitForCacheSync
        # contract). start() is idempotent and upgrades a drain-mode
        # cache to live pumps.
        if not self._cache_started or live:
            self.cache.start(live=live)
        if not self._cache_started:
            self.cache.wait_for_sync()
            self._cache_started = True

    def start(self) -> None:
        self._ensure_cache(live=True)
        for c in self.controllers:
            c.start()

    def stop(self) -> None:
        for c in self.controllers:
            c.stop()
        if self.cache is not None and self._cache_started:
            self.cache.stop()

    def drain(self, max_rounds: int = 60) -> None:
        """Run controllers synchronously until no controller has pending
        work (the deterministic test idiom — no sleeps, no races)."""
        self._ensure_cache(live=False)
        for _ in range(max_rounds):
            cache_moved = (
                self.cache.drain_once() if self.cache is not None else False
            )
            if not any(c.drain_once() for c in self.controllers) and not cache_moved:
                return
        raise RuntimeError("manager did not quiesce; reconcile livelock?")
