"""Exposure controller: the odh-notebook-controller role, GKE-native.

Second operator watching the same Notebook CR (reference:
components/odh-notebook-controller/controllers/notebook_controller.go
:126-198): external exposure, auth materials, network policy, and the
create-time reconciliation-lock release.

Redesign:
- OpenShift ``Route`` → Gateway-API ``HTTPRoute`` (TLS terminates at
  the gateway; re-encrypt to the auth sidecar's 8443).
- OAuth SA annotations → plain ServiceAccount + cookie Secret + tls
  Secret; certificates are expected from the platform's cert issuer
  (cert-manager style), named ``<notebook>-tls``.
- NetworkPolicies: notebook port 8888 only from the platform namespace
  (controllers + gateway), auth port 8443 open (notebook_network.go
  :130-209).
- Lock release: once the per-notebook ServiceAccount and secrets exist,
  remove the webhook's lock annotation → the notebook controller's
  StatefulSet finally scales up (notebook_controller.go:94-122).
"""

from __future__ import annotations

import base64
import os
import secrets
from typing import Any, Optional

from odh_kubeflow_tpu.controllers import reconcilehelper
from odh_kubeflow_tpu.controllers.runtime import Manager, Request, Result
from odh_kubeflow_tpu.machinery import objects as obj_util
from odh_kubeflow_tpu.machinery.store import APIServer, NotFound
from odh_kubeflow_tpu.webhooks.notebook import (
    AUTH_PROXY_PORT,
    INJECT_AUTH_ANNOTATION,
    LOCK_ANNOTATION,
    LOCK_VALUE,
)

Obj = dict[str, Any]

GATEWAY_NAME = os.environ.get("GATEWAY_NAME", "kubeflow-gateway")
GATEWAY_NAMESPACE = os.environ.get("GATEWAY_NAMESPACE", "kubeflow")


class ExposureController:
    def __init__(self, api: APIServer, platform_namespace: str = "kubeflow"):
        self.api = api
        self.platform_namespace = platform_namespace

    def register(self, mgr: Manager) -> None:
        ctrl = mgr.new_controller("exposure-controller", "Notebook", self.reconcile)
        ctrl.owns("Service").owns("Secret").owns("ServiceAccount")
        ctrl.owns("HTTPRoute").owns("NetworkPolicy")

    # -- reconcile ----------------------------------------------------------

    def reconcile(self, req: Request) -> Result:
        try:
            notebook = self.api.get("Notebook", req.name, req.namespace)
        except NotFound:
            return Result()
        if obj_util.meta(notebook).get("deletionTimestamp"):
            return Result()

        auth = (
            obj_util.annotations_of(notebook).get(INJECT_AUTH_ANNOTATION) == "true"
        )
        self._reconcile_network_policies(notebook, auth)
        if auth:
            self._reconcile_service_account(notebook)
            self._reconcile_tls_service(notebook)
            self._reconcile_secrets(notebook)
        self._reconcile_route(notebook, auth)
        self._maybe_release_lock(notebook, auth)
        return Result()

    # -- auth materials -----------------------------------------------------

    def _reconcile_service_account(self, notebook: Obj) -> None:
        name = obj_util.name_of(notebook)
        sa = {
            "apiVersion": "v1",
            "kind": "ServiceAccount",
            "metadata": {
                "name": name,
                "namespace": obj_util.namespace_of(notebook),
                "annotations": {
                    # protocol-ok: routed by the external auth proxy layer
                    "auth.kubeflow.org/redirect-path": (
                        f"/notebook/{obj_util.namespace_of(notebook)}/{name}/"
                    )
                },
            },
        }
        reconcilehelper.reconcile_object(self.api, sa, owner=notebook)

    def _reconcile_tls_service(self, notebook: Obj) -> None:
        name = obj_util.name_of(notebook)
        svc = {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {
                "name": f"{name}-tls",
                "namespace": obj_util.namespace_of(notebook),
                "annotations": {
                    # cert issuer contract: materialise <name>-tls secret
                    # protocol-ok: the external cert controller consumes it
                    "cert.kubeflow.org/serving-cert-secret-name": f"{name}-tls"
                },
            },
            "spec": {
                "type": "ClusterIP",
                "selector": {"statefulset": name},
                "ports": [
                    {
                        "name": "https-auth",
                        "port": AUTH_PROXY_PORT,
                        "targetPort": AUTH_PROXY_PORT,
                        "protocol": "TCP",
                    }
                ],
            },
        }
        reconcilehelper.reconcile_object(self.api, svc, owner=notebook)

    def _reconcile_secrets(self, notebook: Obj) -> None:
        name = obj_util.name_of(notebook)
        ns = obj_util.namespace_of(notebook)
        cookie = {
            "apiVersion": "v1",
            "kind": "Secret",
            "metadata": {"name": f"{name}-cookie-secret", "namespace": ns},
            "type": "Opaque",
            "data": {
                "secret": base64.b64encode(secrets.token_bytes(32)).decode()
            },
        }
        try:
            self.api.get("Secret", f"{name}-cookie-secret", ns)
        except NotFound:
            obj_util.set_controller_reference(cookie, notebook)
            self.api.create(cookie)
        # tls secret: in a real cluster the cert issuer fills this from
        # the service annotation; create a placeholder if absent so the
        # pod can mount (and the issuer can overwrite).
        try:
            self.api.get("Secret", f"{name}-tls", ns)
        except NotFound:
            tls = {
                "apiVersion": "v1",
                "kind": "Secret",
                "metadata": {"name": f"{name}-tls", "namespace": ns},
                "type": "kubernetes.io/tls",
                "data": {"tls.crt": "", "tls.key": ""},
            }
            obj_util.set_controller_reference(tls, notebook)
            self.api.create(tls)

    # -- network ------------------------------------------------------------

    def _reconcile_network_policies(self, notebook: Obj, auth: bool) -> None:
        name = obj_util.name_of(notebook)
        ns = obj_util.namespace_of(notebook)
        notebook_port_policy = {
            "apiVersion": "networking.k8s.io/v1",
            "kind": "NetworkPolicy",
            "metadata": {"name": f"{name}-ctrl-np", "namespace": ns},
            "spec": {
                "podSelector": {
                    "matchLabels": {"statefulset.kubernetes.io/pod-name": f"{name}-0"}
                },
                "policyTypes": ["Ingress"],
                "ingress": [
                    {
                        "from": [
                            {
                                "namespaceSelector": {
                                    "matchLabels": {
                                        "kubernetes.io/metadata.name": (
                                            self.platform_namespace
                                        )
                                    }
                                }
                            }
                        ],
                        "ports": [{"protocol": "TCP", "port": 8888}],
                    }
                ],
            },
        }
        reconcilehelper.reconcile_object(
            self.api, notebook_port_policy, owner=notebook
        )
        if auth:
            auth_port_policy = {
                "apiVersion": "networking.k8s.io/v1",
                "kind": "NetworkPolicy",
                "metadata": {"name": f"{name}-auth-np", "namespace": ns},
                "spec": {
                    "podSelector": {
                        "matchLabels": {
                            "statefulset.kubernetes.io/pod-name": f"{name}-0"
                        }
                    },
                    "policyTypes": ["Ingress"],
                    "ingress": [
                        {"ports": [{"protocol": "TCP", "port": AUTH_PROXY_PORT}]}
                    ],
                },
            }
            reconcilehelper.reconcile_object(
                self.api, auth_port_policy, owner=notebook
            )

    # -- route --------------------------------------------------------------

    def _reconcile_route(self, notebook: Obj, auth: bool) -> None:
        name = obj_util.name_of(notebook)
        ns = obj_util.namespace_of(notebook)
        backend = (
            {"name": f"{name}-tls", "port": AUTH_PROXY_PORT}
            if auth
            else {"name": name, "port": 80}
        )
        route = {
            "apiVersion": "gateway.networking.k8s.io/v1",
            "kind": "HTTPRoute",
            "metadata": {"name": name, "namespace": ns},
            "spec": {
                "parentRefs": [
                    {"name": GATEWAY_NAME, "namespace": GATEWAY_NAMESPACE}
                ],
                "rules": [
                    {
                        "matches": [
                            {
                                "path": {
                                    "type": "PathPrefix",
                                    "value": f"/notebook/{ns}/{name}",
                                }
                            }
                        ],
                        "backendRefs": [backend],
                    }
                ],
            },
        }
        reconcilehelper.reconcile_object(self.api, route, owner=notebook)

    # -- lock ---------------------------------------------------------------

    def _maybe_release_lock(self, notebook: Obj, auth: bool) -> None:
        ann = obj_util.annotations_of(notebook)
        # only release OUR lock — a user/culler stop annotation (any
        # other value) is not ours to remove
        if ann.get(LOCK_ANNOTATION) != LOCK_VALUE:
            return
        name = obj_util.name_of(notebook)
        ns = obj_util.namespace_of(notebook)
        if auth:
            try:
                # existence probes only — served zero-copy by the
                # informer cache when one fronts the api
                self.api.get("ServiceAccount", name, ns)
                self.api.get("Secret", f"{name}-cookie-secret", ns)
                self.api.get("Secret", f"{name}-tls", ns)
            except NotFound:
                return  # keep the lock; requeue happens via owns() events
        self.api.patch(
            "Notebook",
            name,
            {"metadata": {"annotations": {LOCK_ANNOTATION: None}}},
            ns,
        )


def main() -> None:
    """Split-process entrypoint: the second operator watching Notebook
    (manifests/odh-notebook-controller posture), reads fronted by the
    runner's informer cache."""
    import os

    from odh_kubeflow_tpu.machinery.runner import run_controller

    run_controller(
        "exposure-controller",
        lambda api, mgr: ExposureController(
            api,
            platform_namespace=os.environ.get(
                "PLATFORM_NAMESPACE", "kubeflow"
            ),
        ).register(mgr),
    )


if __name__ == "__main__":
    main()
