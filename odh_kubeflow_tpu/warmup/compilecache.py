"""Compilation-cache service: compile once, load everywhere.

The platform's answer to the 5-13s XLA compile every fresh kernel and
engine replica pays (BENCH_r03-r05; the 1B train-step compile alone is
~14s cold). A compiled program is a pure function of its
:class:`CompileKey` — (program fingerprint, topology/mesh shape,
compiler version) — so the artifact is content-addressed and shared
across sessions, trainer runs, and engine replicas:

- bytes live on a :class:`CompileArtifactStore` (atomic write +
  sha256-digest meta, the ``SessionCheckpointStore`` discipline) or its
  zone-replicated façade :class:`ReplicatedArtifactStore` (write-all
  save, read-from-any-verifying-zone — the PR-14
  ``ReplicatedCheckpointStore`` pattern, so entries survive a zone loss
  and leader failover);
- the index is the ``CompileCacheEntry`` kind on the platform API
  (cluster-scoped — programs are not namespace-local): digest, size,
  zones, lastAccessAt — which makes cache state observable, WAL-durable
  and replicated like every other platform object;
- :meth:`CompileCacheService.get_or_compile` is the one entrypoint:
  singleflight dedup (N concurrent compilers of the same key produce
  ONE compile; followers block on the leader's result), digest-verified
  loads (a corrupted/truncated artifact is detected and falls back to a
  fresh compile — never loaded as garbage), hit/miss/latency metrics,
  and LRU+TTL GC under ``COMPILE_CACHE_MAX_BYTES`` /
  ``COMPILE_CACHE_TTL_SECONDS``;
- :meth:`ingest_dir` / :meth:`materialize_dir` bridge jax's own
  persistent compilation cache: a cold process pointed at a staging
  ``JAX_COMPILATION_CACHE_DIR`` writes artifacts, ``ingest_dir``
  registers them with the service, and ``materialize_dir`` stages
  digest-verified artifacts into a fresh directory for the next
  process (notebook kernels get that directory as their
  ``JAX_COMPILATION_CACHE_DIR`` mount).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import tempfile
import threading
import time
from typing import Any, Callable, Optional

from odh_kubeflow_tpu.machinery import objects as obj_util
from odh_kubeflow_tpu.machinery.store import (
    AlreadyExists,
    Conflict,
    NotFound,
)
from odh_kubeflow_tpu.sessions.checkpoint import parse_zone_spec
from odh_kubeflow_tpu.utils import prometheus
from odh_kubeflow_tpu.warmup import WARMUP_API_VERSION

Obj = dict[str, Any]

_LOAD_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0)
_COMPILE_BUCKETS = (0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 20.0, 60.0)


def compiler_version() -> str:
    """The compiler identity axis of the cache key: artifacts from one
    jax/jaxlib (and hence XLA/libtpu) build must never serve another."""
    try:
        import jax
        import jaxlib

        return f"jax-{jax.__version__}+jaxlib-{jaxlib.__version__}"
    except Exception:  # noqa: BLE001 — key axis degrades, never raises
        return "unknown"


@dataclasses.dataclass(frozen=True)
class CompileKey:
    """Content address of one compiled program. ``fingerprint`` is the
    HLO/program hash (for jax-persistent-cache artifacts, the cache
    filename jax derives from the canonicalized computation + compile
    options); topology and compiler version complete the key — the same
    HLO compiled for a different mesh shape or by a different XLA build
    is a different artifact."""

    fingerprint: str
    topology: str = ""
    compiler_version: str = ""

    @property
    def key_id(self) -> str:
        raw = f"{self.fingerprint}|{self.topology}|{self.compiler_version}"
        return hashlib.sha256(raw.encode()).hexdigest()[:32]

    @property
    def entry_name(self) -> str:
        return f"cc-{self.key_id}"


class CompileArtifactStore:
    """Opaque-bytes artifact store, one file + one meta per key:
    ``<key>.bin`` written via temp-file + ``os.replace`` (never a torn
    artifact), ``<key>.meta.json`` holding the sha256 digest + size so
    every load can verify the bytes it is about to hand to XLA."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _bin(self, key_id: str) -> str:
        return os.path.join(self.root, f"{key_id}.bin")

    def _meta(self, key_id: str) -> str:
        return os.path.join(self.root, f"{key_id}.meta.json")

    @staticmethod
    def digest_of(data: bytes) -> str:
        return hashlib.sha256(data).hexdigest()

    def save(self, key_id: str, data: bytes) -> Obj:
        digest = self.digest_of(data)
        for path, payload in (
            (self._bin(key_id), data),
            (
                self._meta(key_id),
                json.dumps(
                    {"digest": digest, "sizeBytes": len(data)}
                ).encode(),
            ),
        ):
            fd, tmp = tempfile.mkstemp(dir=self.root, prefix=".tmp-")
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(payload)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        return {"digest": digest, "sizeBytes": len(data)}

    def saved_digest(self, key_id: str) -> Optional[str]:
        try:
            with open(self._meta(key_id), "rb") as f:
                return json.loads(f.read()).get("digest")
        except (OSError, ValueError):
            return None

    def load(
        self, key_id: str, expect_digest: Optional[str] = None
    ) -> Optional[tuple[bytes, str]]:
        """The bytes + their ACTUAL digest, or None when missing or —
        with ``expect_digest`` — when the bytes do not verify. The
        digest is always recomputed from the bytes read, not trusted
        from the meta file: a truncated/corrupted artifact must be
        caught here, before XLA deserializes it."""
        try:
            with open(self._bin(key_id), "rb") as f:
                data = f.read()
        except OSError:
            return None
        digest = self.digest_of(data)
        if expect_digest and digest != expect_digest:
            return None
        return data, digest

    def exists(self, key_id: str) -> bool:
        return os.path.exists(self._bin(key_id))

    def delete(self, key_id: str) -> None:
        for path in (self._bin(key_id), self._meta(key_id)):
            try:
                os.unlink(path)
            except OSError:
                pass


class ReplicatedArtifactStore:
    """Zone-replicated façade over N :class:`CompileArtifactStore`
    roots, one per failure domain — the PR-14 replicated-checkpoint
    discipline applied to compile artifacts:

    - ``save`` is write-all; at least one zone must land or it raises
      (an index entry with zero durable artifacts is a lie); the
      receipt records which zones hold the bytes and whether the write
      degraded;
    - ``load`` prefers a zone whose bytes VERIFY against the expected
      digest, so one zone's bitrot silently falls through to a healthy
      replica;
    - ``fail_zone``/``heal_zone`` simulate/repair domain loss (tests,
      zone drills); ``heal`` re-replicates a degraded key once its
      missing zones return.
    """

    def __init__(self, zones: dict[str, str]):
        if not zones:
            raise ValueError("ReplicatedArtifactStore needs >= 1 zone")
        self.stores = {z: CompileArtifactStore(p) for z, p in zones.items()}
        self._failed: set[str] = set()

    # -- failure-domain control (drills) ------------------------------------

    def fail_zone(self, zone: str) -> None:
        self._failed.add(zone)

    def heal_zone(self, zone: str) -> None:
        self._failed.discard(zone)

    def failed_zones(self) -> set[str]:
        return set(self._failed)

    # -- store duck type ----------------------------------------------------

    def save(self, key_id: str, data: bytes) -> Obj:
        landed: list[str] = []
        receipt: Obj = {}
        for zone, store in self.stores.items():
            if zone in self._failed:
                continue
            try:
                receipt = store.save(key_id, data)
            except OSError:
                continue
            landed.append(zone)
        if not landed:
            raise OSError(
                f"compile artifact {key_id}: no zone accepted the write"
            )
        receipt["zones"] = landed
        receipt["degraded"] = len(landed) < len(self.stores)
        return receipt

    def load(
        self, key_id: str, expect_digest: Optional[str] = None
    ) -> Optional[tuple[bytes, str]]:
        fallback: Optional[tuple[bytes, str]] = None
        for zone, store in self.stores.items():
            if zone in self._failed:
                continue
            got = store.load(key_id, expect_digest=expect_digest)
            if got is not None:
                return got
            if expect_digest and fallback is None:
                fallback = store.load(key_id)
        # no zone verifies: surface nothing rather than unverified
        # bytes — the caller treats it as a corrupt miss and recompiles
        del fallback
        return None

    def exists(self, key_id: str) -> bool:
        return any(
            s.exists(key_id)
            for z, s in self.stores.items()
            if z not in self._failed
        )

    def saved_digest(self, key_id: str) -> Optional[str]:
        for zone, store in self.stores.items():
            if zone in self._failed:
                continue
            digest = store.saved_digest(key_id)
            if digest:
                return digest
        return None

    def delete(self, key_id: str) -> None:
        for store in self.stores.values():
            store.delete(key_id)

    def heal(self, key_id: str, digest: str) -> Obj:
        """Re-replicate ``key_id`` to every healthy zone missing it,
        sourcing from a zone whose bytes verify."""
        got = self.load(key_id, expect_digest=digest)
        zones: list[str] = []
        if got is not None:
            data, _ = got
            for zone, store in self.stores.items():
                if zone in self._failed:
                    continue
                if store.saved_digest(key_id) != digest:
                    try:
                        store.save(key_id, data)
                    except OSError:
                        continue
                zones.append(zone)
        return {"zones": zones, "degraded": len(zones) < len(self.stores)}


@dataclasses.dataclass
class CompileCacheConfig:
    cache_dir: str = ""
    zones: str = ""
    max_bytes: int = 4 << 30
    ttl_seconds: float = 7 * 24 * 3600.0

    @staticmethod
    def from_env() -> "CompileCacheConfig":
        env = os.environ
        return CompileCacheConfig(
            cache_dir=env.get("COMPILE_CACHE_DIR", ""),
            zones=env.get("COMPILE_CACHE_ZONES", ""),
            max_bytes=int(env.get("COMPILE_CACHE_MAX_BYTES", str(4 << 30))),
            ttl_seconds=float(
                env.get("COMPILE_CACHE_TTL_SECONDS", str(7 * 24 * 3600))
            ),
        )


class _Inflight:
    __slots__ = ("event", "value", "error")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.value: Optional[bytes] = None
        self.error: Optional[BaseException] = None


class CompileCacheService:
    """The platform compilation cache. One instance per control plane;
    compilers (trainer precompile, engine decode compile, notebook
    kernels via their staged cache dir) all funnel through
    :meth:`get_or_compile`."""

    def __init__(
        self,
        api: Any,
        config: Optional[CompileCacheConfig] = None,
        registry: Optional[prometheus.Registry] = None,
        time_fn: Callable[[], float] = time.time,
    ):
        self.api = api
        self.config = config or CompileCacheConfig()
        self.now = time_fn
        root = self.config.cache_dir or tempfile.mkdtemp(
            prefix="compile-cache-"
        )
        self.root = root
        zones = parse_zone_spec(self.config.zones, root)
        self.store: Any = (
            ReplicatedArtifactStore(zones)
            if zones
            else CompileArtifactStore(root)
        )
        # singleflight table: entry name → the in-flight leader the
        # followers wait on. Compiles and store IO run OUTSIDE the lock
        # — it only guards the table itself.
        self._lock = threading.Lock()
        self._inflight: dict[str, _Inflight] = {}

        reg = registry or prometheus.default_registry
        self.m_hits = reg.counter(
            "compile_cache_hits_total",
            "Compilations served from the cache instead of XLA",
        )
        self.m_misses = reg.counter(
            "compile_cache_misses_total",
            "Cache misses by reason (cold / corrupt / expired)",
            labelnames=("reason",),
        )
        self.m_waits = reg.counter(
            "compile_cache_singleflight_waits_total",
            "Compilers that blocked on another replica's in-flight "
            "compile of the same key instead of compiling themselves",
        )
        self.m_evictions = reg.counter(
            "compile_cache_evictions_total",
            "Entries removed by GC, by reason (ttl / lru)",
            labelnames=("reason",),
        )
        self.m_bytes = reg.gauge(
            "compile_cache_bytes",
            "Total artifact bytes the cache currently retains",
        )
        self.m_load = reg.histogram(
            "compile_cache_load_seconds",
            "Digest-verified artifact load latency",
            buckets=_LOAD_BUCKETS,
        )
        self.m_compile = reg.histogram(
            "compile_cache_compile_seconds",
            "Leader compile latency on cache misses",
            buckets=_COMPILE_BUCKETS,
        )

    # -- index (CompileCacheEntry CRs) --------------------------------------

    def _entry(self, key: CompileKey) -> Optional[Obj]:
        try:
            return self.api.get("CompileCacheEntry", key.entry_name)
        except NotFound:
            return None

    def _ensure_entry(self, key: CompileKey, receipt: Obj) -> None:
        entry = {
            "apiVersion": WARMUP_API_VERSION,
            "kind": "CompileCacheEntry",
            "metadata": {"name": key.entry_name},
            "spec": {
                "fingerprint": key.fingerprint,
                "topology": key.topology,
                "compilerVersion": key.compiler_version,
            },
        }
        try:
            entry = self.api.create(entry)
        except AlreadyExists:
            entry = self._entry(key)
            if entry is None:
                return
        entry = obj_util.mutable(entry)
        now = obj_util.now_rfc3339()
        status = dict(entry.get("status") or {})
        status.update(
            {
                "digest": receipt["digest"],
                "sizeBytes": receipt["sizeBytes"],
                "createdAt": status.get("createdAt") or now,
                "lastAccessAt": now,
            }
        )
        if "zones" in receipt:
            status["zones"] = list(receipt["zones"])
            status["replicationDegraded"] = bool(receipt.get("degraded"))
        entry["status"] = status
        try:
            self.api.update_status(entry)
        except (Conflict, NotFound):
            pass  # another replica's put raced; either status is valid

    def _touch(self, entry: Obj) -> None:
        entry = obj_util.mutable(entry)
        status = dict(entry.get("status") or {})
        status["lastAccessAt"] = obj_util.now_rfc3339()
        entry["status"] = status
        try:
            self.api.update_status(entry)
        except (Conflict, NotFound):
            pass  # LRU ordering is advisory; a lost touch is harmless

    def entries(self) -> list[Obj]:
        try:
            return list(self.api.list("CompileCacheEntry"))  # uncached-ok: GC + materialize sweeps over a small cluster-scoped kind
        except NotFound:
            return []

    # -- hot path ------------------------------------------------------------

    def load(self, key: CompileKey) -> Optional[bytes]:
        """Cache lookup only (no compile): digest-verified bytes or
        None. A corrupted artifact (no replica verifies) is dropped so
        the next compiler repopulates it."""
        entry = self._entry(key)
        if entry is None:
            return None
        digest = obj_util.get_path(entry, "status", "digest", default="")
        t0 = self.now()
        got = self.store.load(key.key_id, expect_digest=digest or None)
        if got is None:
            # bytes missing or failed the digest check — never hand
            # garbage to XLA; purge so the index can't keep lying
            self.store.delete(key.key_id)
            try:
                self.api.delete("CompileCacheEntry", key.entry_name)
            except NotFound:
                pass
            return None
        self.m_load.observe(max(self.now() - t0, 0.0))
        self._touch(entry)
        return got[0]

    def get_or_compile(
        self, key: CompileKey, compile_fn: Callable[[], bytes]
    ) -> bytes:
        """THE service entrypoint: a digest-verified cache hit, or the
        singleflight-deduplicated compile. N concurrent callers of the
        same key produce exactly one ``compile_fn`` invocation — the
        leader compiles and publishes, followers block on its result.
        A failed leader propagates its error to that round's followers
        (the next caller starts a fresh round)."""
        name = key.entry_name
        while True:
            with self._lock:
                inflight = self._inflight.get(name)
                if inflight is None:
                    leader = _Inflight()
                    self._inflight[name] = leader
                    break
            self.m_waits.inc()
            inflight.event.wait()
            if inflight.error is not None:
                raise inflight.error
            assert inflight.value is not None
            return inflight.value
        try:
            entry = self._entry(key)
            data = self.load(key)
            if data is None:
                reason = "cold" if entry is None else "corrupt"
                if entry is not None and self._expired(entry):
                    reason = "expired"
                self.m_misses.inc({"reason": reason})
                t0 = self.now()
                data = compile_fn()
                self.m_compile.observe(max(self.now() - t0, 0.0))
                self.put(key, data)
            else:
                self.m_hits.inc()
            leader.value = data
            return data
        except BaseException as e:
            leader.error = e
            raise
        finally:
            with self._lock:
                self._inflight.pop(name, None)
            leader.event.set()

    def put(self, key: CompileKey, data: bytes) -> Obj:
        receipt = self.store.save(key.key_id, data)
        self._ensure_entry(key, receipt)
        self.gc()
        return receipt

    # -- retention -----------------------------------------------------------

    def _expired(self, entry: Obj, now: Optional[float] = None) -> bool:
        if self.config.ttl_seconds <= 0:
            return False
        last = obj_util.get_path(
            entry, "status", "lastAccessAt", default=""
        ) or obj_util.get_path(entry, "status", "createdAt", default="")
        if not last:
            return False
        now = self.now() if now is None else now
        return now - obj_util.parse_rfc3339(last) > self.config.ttl_seconds

    def _drop(self, entry: Obj, reason: str) -> None:
        spec = entry.get("spec") or {}
        key = CompileKey(
            fingerprint=spec.get("fingerprint", ""),
            topology=spec.get("topology", ""),
            compiler_version=spec.get("compilerVersion", ""),
        )
        self.store.delete(key.key_id)
        try:
            self.api.delete(
                "CompileCacheEntry", obj_util.name_of(entry)
            )
        except NotFound:
            pass
        self.m_evictions.inc({"reason": reason})

    def gc(self, now: Optional[float] = None) -> int:
        """TTL-expire, then LRU-evict down to ``max_bytes``. Returns
        the number of entries dropped. Runs after every put and from
        the WarmPool controller's periodic reconcile."""
        now = self.now() if now is None else now
        live: list[Obj] = []
        dropped = 0
        for entry in self.entries():
            if self._expired(entry, now=now):
                self._drop(entry, "ttl")
                dropped += 1
            else:
                live.append(entry)
        total = sum(
            int(
                obj_util.get_path(e, "status", "sizeBytes", default=0) or 0
            )
            for e in live
        )
        if self.config.max_bytes > 0 and total > self.config.max_bytes:
            # oldest access first — the LRU axis
            live.sort(
                key=lambda e: obj_util.get_path(
                    e, "status", "lastAccessAt", default=""
                )
                or ""
            )
            for entry in live:
                if total <= self.config.max_bytes:
                    break
                self._drop(entry, "lru")
                total -= int(
                    obj_util.get_path(
                        entry, "status", "sizeBytes", default=0
                    )
                    or 0
                )
                dropped += 1
        self.m_bytes.set(max(total, 0))
        return dropped

    def heal_pass(self) -> int:
        """Re-replicate degraded entries (a zone was down at put time)
        once their zones heal — the session checkpoint heal loop's
        analog, driven from the WarmPool controller's resync."""
        heal = getattr(self.store, "heal", None)
        if heal is None:
            return 0
        healed = 0
        for entry in self.entries():
            status = entry.get("status") or {}
            if not status.get("replicationDegraded"):
                continue
            digest = status.get("digest", "")
            spec = entry.get("spec") or {}
            key = CompileKey(
                fingerprint=spec.get("fingerprint", ""),
                topology=spec.get("topology", ""),
                compiler_version=spec.get("compilerVersion", ""),
            )
            if not digest:
                continue
            replication = heal(key.key_id, digest)
            if not replication["degraded"]:
                entry = obj_util.mutable(entry)
                merged = dict(entry.get("status") or {})
                merged.update(
                    {
                        "zones": list(replication["zones"]),
                        "replicationDegraded": False,
                    }
                )
                entry["status"] = merged
                try:
                    self.api.update_status(entry)
                except (Conflict, NotFound):
                    continue
                healed += 1
        return healed

    # -- jax persistent-cache bridge -----------------------------------------

    def staging_dir(self, tag: str) -> str:
        """A fresh directory a process can use as its
        ``JAX_COMPILATION_CACHE_DIR`` — cold compiles land here, then
        ``ingest_dir`` promotes them into the shared store."""
        path = os.path.join(self.root, "staging", tag)
        os.makedirs(path, exist_ok=True)
        return path

    def ingest_dir(
        self,
        path: str,
        topology: str = "",
        compiler_ver: Optional[str] = None,
    ) -> int:
        """Register every artifact a jax persistent cache wrote under
        ``path`` (one file per compiled program, filename = jax's own
        content fingerprint). Returns how many entered the cache."""
        ver = compiler_version() if compiler_ver is None else compiler_ver
        count = 0
        try:
            names = sorted(os.listdir(path))
        except OSError:
            return 0
        for fn in names:
            full = os.path.join(path, fn)
            if not os.path.isfile(full) or fn.startswith("."):
                continue
            with open(full, "rb") as f:
                data = f.read()
            key = CompileKey(
                fingerprint=fn, topology=topology, compiler_version=ver
            )
            digest = self.store.saved_digest(key.key_id)
            if digest == CompileArtifactStore.digest_of(data):
                continue  # already held, bit-identical
            self.put(key, data)
            count += 1
        return count

    def materialize_dir(
        self,
        path: str,
        topology: str = "",
        compiler_ver: Optional[str] = None,
    ) -> int:
        """Stage every digest-verified artifact matching (topology,
        compiler version) into ``path`` under its original jax cache
        filename — the directory a warm process (notebook kernel,
        engine replica) mounts as ``JAX_COMPILATION_CACHE_DIR`` so its
        first jit is a load, not a compile."""
        ver = compiler_version() if compiler_ver is None else compiler_ver
        os.makedirs(path, exist_ok=True)
        count = 0
        for entry in self.entries():
            spec = entry.get("spec") or {}
            if spec.get("topology", "") != topology:
                continue
            if spec.get("compilerVersion", "") != ver:
                continue
            fingerprint = spec.get("fingerprint", "")
            # the fingerprint becomes a filename — refuse anything that
            # could escape the staging directory
            if (
                not fingerprint
                or os.sep in fingerprint
                or fingerprint != os.path.basename(fingerprint)
                or fingerprint.startswith(".")
            ):
                continue
            key = CompileKey(
                fingerprint=fingerprint,
                topology=spec.get("topology", ""),
                compiler_version=spec.get("compilerVersion", ""),
            )
            data = self.load(key)
            if data is None:
                continue
            fd, tmp = tempfile.mkstemp(dir=path, prefix=".tmp-")
            with os.fdopen(fd, "wb") as f:
                f.write(data)
            os.replace(tmp, os.path.join(path, fingerprint))
            count += 1
        return count

    def stats(self) -> Obj:
        entries = self.entries()
        return {
            "entries": len(entries),
            "bytes": sum(
                int(
                    obj_util.get_path(e, "status", "sizeBytes", default=0)
                    or 0
                )
                for e in entries
            ),
            "degraded": sum(
                1
                for e in entries
                if obj_util.get_path(
                    e, "status", "replicationDegraded", default=False
                )
            ),
        }


def install_process_cache(cache_dir: Optional[str] = None) -> Optional[str]:
    """Point THIS process's jax persistent compilation cache at
    ``cache_dir`` (or ``$JAX_COMPILATION_CACHE_DIR``) with thresholds
    zeroed so every compile is eligible. The in-process half of the
    service: the trainer's precompile path and the engine's decode
    compile call it before their first jit, so a staged/materialized
    cache directory turns those compiles into loads. No-op (returns
    None) when no directory is configured or jax is absent."""
    path = cache_dir or os.environ.get("JAX_COMPILATION_CACHE_DIR", "")
    if not path:
        return None
    try:
        import jax

        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        return path
    except Exception:  # noqa: BLE001 — cache wiring must never break a run
        return None
