"""WarmPool: pre-admitted, pre-imaged, pre-compiled standby sessions.

NotebookOS's pre-warmed-container idea (arXiv 2503.20591) on the TPU
slice queue: a ``WarmPool`` keeps ``spec.size`` standby Notebooks per
(profile namespace, accelerator, image) template alive and ready. The
lifecycle:

    Backfilling ──(standby admitted + pod Running)──▶ Ready
        ▲                                              │ atomic claim
        │ standby died / zone kill / reclaimed         ▼
        └──────────── backfill ◀──────────────── Claimed ──▶ reaped

- **Backfill** rides the ordinary slice queue at the
  ``warm-pool-backfill`` PriorityClass (negative value): pending_order
  sorts standbys behind every real user, and the preemption planner's
  lowest-priority-first victim sort makes them the CHEAPEST victims
  under quota pressure — draining/reclaiming needs no scheduler
  special-casing.
- **Claim** (``claim_standby``) is a conditional update on the
  standby's resourceVersion: concurrent spawners racing for the last
  standby produce exactly one winner; losers fall through to the cold
  path. The claim lands in the WAL before the handout proceeds, so a
  spawner crash between claim and delete cannot double-hand-out — the
  controller reaps claimed leftovers after a grace window.
- **Warm restore**: the pool maintains a template kernel state in the
  session checkpoint store; a claimed notebook gets that state copied
  under its own UID plus a ``SessionCheckpoint`` in phase Suspended —
  the PR-6 suspend machinery then runs in REVERSE, restoring the
  warmed state (compile-cache manifest included) into the fresh pod.
- **Zone spread** falls out of the scheduler's zone-load-aware fit;
  the claimed user notebook carries ``PREFERRED_POOL_ANNOTATION`` so
  its gang lands on the slice pool its standby just freed (pre-pulled
  image, warm node).
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Callable, Optional

from odh_kubeflow_tpu.apis import (
    TPU_ACCELERATOR_ANNOTATION,
    TPU_RUNTIME_LABEL,
    TPU_TOPOLOGY_ANNOTATION,
)
from odh_kubeflow_tpu.controllers.runtime import Manager, Request, Result
from odh_kubeflow_tpu.machinery import objects as obj_util
from odh_kubeflow_tpu.machinery.events import EventRecorder
from odh_kubeflow_tpu.machinery.objects import mutable
from odh_kubeflow_tpu.machinery.store import (
    AlreadyExists,
    Conflict,
    NotFound,
)
from odh_kubeflow_tpu.scheduling import PRIORITY_CLASS_ANNOTATION
from odh_kubeflow_tpu.sessions import (
    PHASE_SUSPENDED,
    checkpoint_of,
    new_checkpoint,
)
from odh_kubeflow_tpu.utils import prometheus
from odh_kubeflow_tpu.warmup import (
    BACKFILL_PRIORITY_CLASS,
    CLAIMED_AT_ANNOTATION,
    CLAIMED_BY_ANNOTATION,
    POOL_LABEL,
    STANDBY_ANNOTATION,
    WARM_FROM_ANNOTATION,
    WARMUP_API_VERSION,
    is_claimed,
    pool_of,
)

Obj = dict[str, Any]

COMPONENT = "warm-pool-controller"


@dataclasses.dataclass
class WarmPoolConfig:
    enabled: bool = True
    backfill_priority: int = -100
    claim_grace_seconds: float = 60.0
    resync_seconds: float = 5.0

    @staticmethod
    def from_env() -> "WarmPoolConfig":
        env = os.environ
        return WarmPoolConfig(
            enabled=env.get("WARM_POOL_ENABLED", "true").lower() == "true",
            backfill_priority=int(
                env.get("WARM_POOL_BACKFILL_PRIORITY", "-100")
            ),
            claim_grace_seconds=float(
                env.get("WARM_POOL_CLAIM_GRACE_SECONDS", "60")
            ),
            resync_seconds=float(env.get("WARM_POOL_RESYNC_SECONDS", "5")),
        )


def new_warm_pool(
    name: str,
    namespace: str,
    *,
    size: int,
    accelerator: str,
    topology: str,
    image: str,
    cpu: str = "1",
    memory: str = "2Gi",
) -> Obj:
    """A WarmPool CR shell: one standby template per (namespace,
    accelerator, image)."""
    return {
        "apiVersion": WARMUP_API_VERSION,
        "kind": "WarmPool",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {
            "size": int(size),
            "accelerator": accelerator,
            "topology": topology,
            "image": image,
            "cpu": cpu,
            "memory": memory,
        },
    }


def standbys(api: Any, namespace: str, pool: str) -> list[Obj]:
    """The pool's standby Notebooks, stable name order."""
    try:
        rows = api.list("Notebook", namespace=namespace)  # uncached-ok: pool-sized sweep, label-filtered below
    except NotFound:
        return []
    out = [nb for nb in rows if pool_of(nb) == pool]
    out.sort(key=obj_util.name_of)
    return out


def standby_ready(api: Any, notebook: Obj) -> bool:
    """A standby is handoutable once unclaimed AND its pod-0 is
    Running — admitted, imaged, and warm. (Pre-pod standbys are still
    backfilling; claiming one would hand out a cold start.)"""
    if is_claimed(notebook):
        return False
    try:
        pod = api.get(
            "Pod",
            f"{obj_util.name_of(notebook)}-0",
            obj_util.namespace_of(notebook),
        )
    except NotFound:
        return False
    return obj_util.get_path(pod, "status", "phase", default="") == "Running"


def _assignment_of(api: Any, notebook: Obj) -> tuple[str, str]:
    """(slice pool, zone) the standby's gang is admitted to — the
    placement the claimed user notebook should prefer."""
    try:
        wl = api.get(
            "Workload",
            obj_util.name_of(notebook),
            obj_util.namespace_of(notebook),
        )
    except NotFound:
        return "", ""
    return (
        obj_util.get_path(wl, "status", "assignment", "pool", default="")
        or "",
        obj_util.get_path(wl, "status", "assignment", "zone", default="")
        or "",
    )


def claim_standby(
    api: Any,
    namespace: str,
    accelerator: str = "",
    topology: str = "",
    image: str = "",
    claimant: str = "",
) -> Optional[Obj]:
    """Atomically claim one ready standby matching the requested
    template, or None (cold path). The claim is a conditional update on
    the standby's resourceVersion: under concurrent spawns exactly one
    caller wins each standby — a Conflict means another spawner got
    there first, and the loser moves to the next candidate. The stamped
    annotation is WAL-durable before this returns, which is what makes
    crash recovery double-handout-free: a recovered control plane sees
    the claim and never hands that standby out again."""
    try:
        pools = api.list("WarmPool", namespace=namespace)  # uncached-ok: handful of pools per namespace
    except NotFound:
        return None
    for pool in sorted(pools, key=obj_util.name_of):
        spec = pool.get("spec") or {}
        if accelerator and spec.get("accelerator", "") != accelerator:
            continue
        if topology and spec.get("topology", "") != topology:
            continue
        if image and spec.get("image", "") != image:
            continue
        for nb in standbys(api, namespace, obj_util.name_of(pool)):
            if not standby_ready(api, nb):
                continue
            cand = mutable(nb)
            ann = cand["metadata"].setdefault("annotations", {})
            ann[CLAIMED_BY_ANNOTATION] = claimant or "spawner"
            ann[CLAIMED_AT_ANNOTATION] = obj_util.now_rfc3339()
            try:
                api.update(cand)
            except (Conflict, NotFound):
                continue  # raced — this standby went to another spawner
            slice_pool, zone = _assignment_of(api, nb)
            return {
                "pool": obj_util.name_of(pool),
                "standby": obj_util.name_of(nb),
                "slicePool": slice_pool,
                "zone": zone,
                "claimedAt": ann[CLAIMED_AT_ANNOTATION],
            }
    return None


class WarmPoolController:
    """Keeps every WarmPool at ``spec.size`` ready standbys: creates
    standby Notebooks (backfill through the slice queue at backfill
    priority), reaps claimed/orphaned standbys, maintains the template
    kernel state, warm-restores claimed user notebooks, and drives the
    compile cache's GC + heal passes on its resync tick."""

    def __init__(
        self,
        api: Any,
        config: Optional[WarmPoolConfig] = None,
        registry: Optional[prometheus.Registry] = None,
        session_store: Optional[Any] = None,
        compile_cache: Optional[Any] = None,
        time_fn: Callable[[], float] = time.time,
    ):
        self.api = api
        self.config = config or WarmPoolConfig()
        self.now = time_fn
        self.session_store = session_store
        self.compile_cache = compile_cache
        self.recorder = EventRecorder(api, COMPONENT)
        reg = registry or prometheus.default_registry
        self.m_ready = reg.gauge(
            "warm_pool_ready_standbys",
            "Standbys currently claimable, per WarmPool",
            labelnames=("pool",),
        )
        self.m_claims = reg.counter(
            "warm_pool_claims_total",
            "Standbys handed out to spawning notebooks",
        )
        self.m_backfills = reg.counter(
            "warm_pool_backfills_total",
            "Standby Notebooks created to refill a pool",
        )
        self.m_reaps = reg.counter(
            "warm_pool_reaps_total",
            "Standbys deleted by the controller, by reason",
            labelnames=("reason",),
        )

    # -- wiring --------------------------------------------------------------

    def register(self, mgr: Manager) -> None:
        ctrl = mgr.new_controller("warm-pool", "WarmPool", self.reconcile)
        ctrl.watches("Notebook", self._map_notebook)

    @staticmethod
    def _map_notebook(_etype: str, nb: Obj) -> list[Request]:
        pool = pool_of(nb) or obj_util.annotations_of(nb).get(
            WARM_FROM_ANNOTATION, ""
        )
        if not pool:
            return []
        return [Request(obj_util.namespace_of(nb), pool)]

    # -- reconcile -----------------------------------------------------------

    def reconcile(self, req: Request) -> Result:
        try:
            pool = self.api.get("WarmPool", req.name, req.namespace)
        except NotFound:
            return self._gc_pool(req)

        self._ensure_priority_class()
        spec = pool.get("spec") or {}
        size = int(spec.get("size", 0) or 0)
        self._ensure_template_state(pool)
        self._restore_claimed(pool)

        rows = standbys(self.api, req.namespace, req.name)
        live: list[Obj] = []
        for nb in rows:
            if is_claimed(nb):
                self._maybe_reap(nb)
            else:
                live.append(nb)

        ready = [nb for nb in live if standby_ready(self.api, nb)]
        if len(live) < size:
            taken = {obj_util.name_of(nb) for nb in rows}
            idx = 0
            for _ in range(size - len(live)):
                while f"{req.name}-standby-{idx}" in taken:
                    idx += 1
                self._create_standby(pool, idx)
                taken.add(f"{req.name}-standby-{idx}")
        elif len(live) > size:
            for nb in live[size:]:
                self._delete_standby(nb, "scale-down")

        zones = sorted(
            {
                zone
                for nb in ready
                for _, zone in (_assignment_of(self.api, nb),)
                if zone
            }
        )
        self._update_status(
            pool,
            {
                "readyStandbys": len(ready),
                "pendingStandbys": len(live) - len(ready),
                "zones": zones,
                "lastSyncAt": obj_util.now_rfc3339(),
            },
        )
        self.m_ready.set(len(ready), {"pool": req.name})
        # the cache service's retention + replication-heal loops ride
        # this resync tick (blocking store IO — reconcile body, no
        # locks held)
        if self.compile_cache is not None:
            self.compile_cache.gc()
            self.compile_cache.heal_pass()
        return Result(requeue_after=self.config.resync_seconds)

    # -- standby lifecycle ---------------------------------------------------

    def _ensure_priority_class(self) -> None:
        self.api.create_or_get(
            {
                "apiVersion": "scheduling.k8s.io/v1",
                "kind": "PriorityClass",
                "metadata": {"name": BACKFILL_PRIORITY_CLASS},
                "value": self.config.backfill_priority,
                "description": (
                    "warm-pool standby backfill: behind every real "
                    "user in the queue, first out under pressure"
                ),
            }
        )

    def _create_standby(self, pool: Obj, idx: int) -> None:
        spec = pool.get("spec") or {}
        name = f"{obj_util.name_of(pool)}-standby-{idx}"
        ns = obj_util.namespace_of(pool)
        notebook: Obj = {
            "apiVersion": "kubeflow.org/v1beta1",
            "kind": "Notebook",
            "metadata": {
                "name": name,
                "namespace": ns,
                "labels": {
                    "app": name,
                    POOL_LABEL: obj_util.name_of(pool),
                    TPU_RUNTIME_LABEL: "enabled",
                },
                "annotations": {
                    STANDBY_ANNOTATION: "true",
                    PRIORITY_CLASS_ANNOTATION: BACKFILL_PRIORITY_CLASS,
                    TPU_ACCELERATOR_ANNOTATION: spec.get("accelerator", ""),
                    TPU_TOPOLOGY_ANNOTATION: spec.get("topology", ""),
                },
            },
            "spec": {
                "template": {
                    "metadata": {"labels": {"app": name}},
                    "spec": {
                        "containers": [
                            {
                                "name": name,
                                "image": spec.get("image", ""),
                                "resources": {
                                    "requests": {
                                        "cpu": spec.get("cpu", "1"),
                                        "memory": spec.get(
                                            "memory", "2Gi"
                                        ),
                                    },
                                },
                                "volumeMounts": [],
                                "env": [],
                            }
                        ],
                        "volumes": [],
                    },
                }
            },
        }
        obj_util.set_controller_reference(notebook, pool)
        try:
            self.api.create(notebook)
        except AlreadyExists:
            return
        self.m_backfills.inc()
        self.recorder.normal(
            pool,
            "StandbyBackfill",
            f"created standby {name} (queued at "
            f"{BACKFILL_PRIORITY_CLASS})",
        )

    def _delete_standby(self, nb: Obj, reason: str) -> None:
        try:
            self.api.delete(
                "Notebook", obj_util.name_of(nb), obj_util.namespace_of(nb)
            )
        except NotFound:
            return
        self.m_reaps.inc({"reason": reason})

    def _maybe_reap(self, nb: Obj) -> None:
        """A claimed standby the claimant never deleted (spawner died
        between claim and delete): after the grace window the claim is
        abandoned — reap it so the pool backfills. It is NEVER handed
        out again either way (claimed standbys fail
        ``standby_ready``), so recovery cannot double-hand-out."""
        claimed_at = obj_util.annotations_of(nb).get(
            CLAIMED_AT_ANNOTATION, ""
        )
        age = (
            self.now() - obj_util.parse_rfc3339(claimed_at)
            if claimed_at
            else self.config.claim_grace_seconds + 1
        )
        if age >= self.config.claim_grace_seconds:
            self._delete_standby(nb, "claimed")

    def _gc_pool(self, req: Request) -> Result:
        """Pool deleted: its standbys go with it (they are pool
        furniture, not user sessions)."""
        for nb in standbys(self.api, req.namespace, req.name):
            self._delete_standby(nb, "pool-deleted")
        return Result()

    # -- template state + warm restore ---------------------------------------

    def _template_uid(self, pool: Obj) -> str:
        return (
            f"warmpool-{obj_util.namespace_of(pool)}-"
            f"{obj_util.name_of(pool)}-template"
        )

    def _ensure_template_state(self, pool: Obj) -> None:
        """The pool's template kernel state: what a claimed session
        wakes up holding — pool provenance plus the staged
        compile-cache manifest (which warmed artifacts its topology
        can load instead of compiling)."""
        if self.session_store is None:
            return
        uid = self._template_uid(pool)
        if self.session_store.exists(uid):
            return
        spec = pool.get("spec") or {}
        staged: list[str] = []
        if self.compile_cache is not None:
            staged = [
                obj_util.get_path(e, "spec", "fingerprint", default="")
                for e in self.compile_cache.entries()
                if obj_util.get_path(e, "spec", "topology", default="")
                == spec.get("topology", "")
            ]
        state = {
            "warmpool": obj_util.name_of(pool),
            "preheated": True,
            "compileCache": {
                "topology": spec.get("topology", ""),
                "staged": sorted(f for f in staged if f),
            },
        }
        receipt = self.session_store.save(uid, state)
        self._update_status(
            pool, {"templateDigest": receipt.get("digest", "")}
        )

    def _restore_claimed(self, pool: Obj) -> None:
        """Run the suspend machinery in reverse for claimed notebooks:
        copy the template state under the new notebook's UID and leave
        a SessionCheckpoint in phase Suspended — the SessionManager's
        ordinary resume path then restores the warmed state into the
        fresh pod."""
        if self.session_store is None:
            return
        ns = obj_util.namespace_of(pool)
        pool_name = obj_util.name_of(pool)
        try:
            rows = self.api.list("Notebook", namespace=ns)  # uncached-ok: pool-sized sweep, annotation-filtered below
        except NotFound:
            return
        for nb in rows:
            ann = obj_util.annotations_of(nb)
            if ann.get(WARM_FROM_ANNOTATION, "") != pool_name:
                continue
            if checkpoint_of(self.api, nb) is not None:
                continue  # restore already staged (or session live)
            uid = obj_util.meta(nb).get("uid", "")
            if not uid:
                continue
            loaded = self.session_store.load(self._template_uid(pool))
            if loaded is None:
                continue
            state, _ = loaded
            receipt = self.session_store.save(uid, state)
            spec = pool.get("spec") or {}
            from odh_kubeflow_tpu.controllers.notebook import tpu_request_of

            try:
                tpu = tpu_request_of(nb)
            except ValueError:
                tpu = None
            ckpt = new_checkpoint(
                nb,
                chips=tpu.chips if tpu else 0,
                accel=tpu.accelerator_type
                if tpu
                else spec.get("accelerator", ""),
                topo=tpu.topology if tpu else spec.get("topology", ""),
            )
            try:
                ckpt = self.api.create(ckpt)
            except AlreadyExists:
                continue
            ckpt = mutable(ckpt)
            ckpt["status"] = {
                "phase": PHASE_SUSPENDED,
                "suspendedAt": ann.get(CLAIMED_AT_ANNOTATION, "")
                or obj_util.now_rfc3339(),
                "checkpointStep": receipt.get("step", 0),
                "digest": receipt.get("digest", ""),
                "sizeBytes": receipt.get("sizeBytes", 0),
                "stateCaptured": True,
            }
            try:
                self.api.update_status(ckpt)
            except (Conflict, NotFound):
                continue
            self.m_claims.inc()
            self.recorder.normal(
                nb,
                "WarmHandout",
                f"warm template state staged from pool {pool_name}; "
                "resuming pre-warmed session into the fresh pod",
            )

    def _update_status(self, pool: Obj, patch: Obj) -> None:
        pool = mutable(pool)
        merged = dict(pool.get("status") or {})
        merged.update(patch)
        pool["status"] = merged
        try:
            self.api.update_status(pool)
        except (Conflict, NotFound):
            pass  # next resync rewrites from fresh state
