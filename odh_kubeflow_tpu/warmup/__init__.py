"""Warm-start subsystem: compilation cache + warm session pools.

Cold-start is the biggest per-user latency the platform controls: every
fresh kernel pays a 5-13s XLA compile (BENCH_r03-r05) and full container
start. This package kills both, in two cooperating halves:

- ``compilecache`` — a content-addressed compilation artifact store
  keyed by (program fingerprint, topology, compiler version), exposed
  through the platform API as ``CompileCacheEntry`` objects whose bytes
  live on a zone-replicated backing store. First compiler populates,
  everyone else loads; singleflight dedup collapses N concurrent
  compiles of the same program into ONE.
- ``pool`` — ``WarmPool``: ``spec.size`` pre-admitted, pre-imaged,
  pre-compiled standby sessions per (profile, accelerator, image)
  template. The spawner hands one out on notebook create with an
  atomic claim (conditional update on the standby's resourceVersion —
  no double-handout under concurrent spawns); the controller backfills
  asynchronously through the ordinary slice queue at LOW priority
  (standbys never starve real users, and preemption treats them as the
  cheapest victims); a template ``SessionCheckpoint`` restores warmed
  kernel state into the claimed session by running the suspend
  machinery in reverse.

Grounding: NotebookOS (arXiv 2503.20591, PAPERS.md) for pre-warmed
instantly-handed-out sessions; "Automatic Full Compilation of Julia
Programs and ML Models to Cloud TPUs" (PAPERS.md) for whole-program XLA
caching. See docs/GUIDE.md "Compilation cache & warm pools".
"""

from __future__ import annotations

from typing import Any, Optional

from odh_kubeflow_tpu.machinery import objects as obj_util

Obj = dict[str, Any]

GROUP = "warmup.kubeflow.org"
WARMUP_API_VERSION = f"{GROUP}/v1alpha1"

# label on standby Notebooks: which WarmPool owns them
POOL_LABEL = f"{GROUP}/pool"
# marks a Notebook as a pool standby (not a real user session): JWA
# hides the cold-start milestones for these and the pool controller is
# their only owner
STANDBY_ANNOTATION = f"{GROUP}/standby"
# the atomic claim: stamped onto a standby via a conditional update
# (resourceVersion-checked) — exactly one spawner wins a given standby
CLAIMED_BY_ANNOTATION = f"{GROUP}/claimed-by"
CLAIMED_AT_ANNOTATION = f"{GROUP}/claimed-at"
# on the user's claimed notebook: which pool served it (the JWA "warm"
# badge) and which standby's slice it inherited
WARM_FROM_ANNOTATION = f"{GROUP}/warm-from"
STANDBY_SOURCE_ANNOTATION = f"{GROUP}/standby-source"
# placement hint carried Notebook → Workload → SliceInventory.fit: the
# claimed session prefers the slice pool its standby just freed, so the
# pre-pulled image and warmed node are actually reused
PREFERRED_POOL_ANNOTATION = f"{GROUP}/preferred-pool"

# the PriorityClass pool backfill queues at (value from
# WARM_POOL_BACKFILL_PRIORITY, default negative): pending_order sorts
# standbys behind every real user, and _plan_preemption picks the
# lowest priority first — standbys are automatically the cheapest
# victims under quota pressure, with no scheduler special-casing
BACKFILL_PRIORITY_CLASS = "warm-pool-backfill"


def register_warmup(api: Any) -> None:
    """Register the warmup kinds on an APIServer-shaped api (embedded
    store or RemoteAPIServer)."""
    api.register_kind(
        WARMUP_API_VERSION, "CompileCacheEntry", "compilecacheentries", False
    )
    api.register_kind(WARMUP_API_VERSION, "WarmPool", "warmpools", True)


def pool_of(notebook: Obj) -> str:
    """The WarmPool a standby Notebook belongs to ("" for real
    sessions)."""
    return obj_util.labels_of(notebook).get(POOL_LABEL, "")


def is_standby(notebook: Obj) -> bool:
    return STANDBY_ANNOTATION in obj_util.annotations_of(notebook)


def is_claimed(notebook: Obj) -> bool:
    return CLAIMED_BY_ANNOTATION in obj_util.annotations_of(notebook)


def warm_source(notebook: Obj) -> Optional[dict[str, str]]:
    """The warm-handout provenance of a claimed user notebook (the JWA
    badge's data), or None for cold-spawned sessions."""
    ann = obj_util.annotations_of(notebook)
    pool = ann.get(WARM_FROM_ANNOTATION, "")
    if not pool:
        return None
    return {
        "pool": pool,
        "standby": ann.get(STANDBY_SOURCE_ANNOTATION, ""),
        "claimedAt": ann.get(CLAIMED_AT_ANNOTATION, ""),
    }
