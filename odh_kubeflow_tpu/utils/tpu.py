"""TPU device introspection: peak-FLOPs table for MFU accounting and
generation→topology metadata used by both bench.py and the platform's
spawner config (``web/jwa``: accelerator type + topology dropdowns)."""

from __future__ import annotations

import jax

# bf16 peak matmul TFLOP/s per chip (public spec sheets).
_PEAK_TFLOPS_BY_KIND = {
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,  # v5e
    "TPU v5e": 197.0,
    "TPU v5": 459.0,  # v5p
    "TPU v5p": 459.0,
    "TPU v6 lite": 918.0,  # v6e / Trillium
    "TPU v6e": 918.0,
    "TPU v7": 4614.0,
}


def peak_flops_per_chip(device: jax.Device | None = None) -> float:
    """Peak bf16 FLOP/s for one chip; 0.0 when unknown (e.g. CPU)."""
    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "")
    for name, tflops in _PEAK_TFLOPS_BY_KIND.items():
        if kind.startswith(name):
            return tflops * 1e12
    return 0.0


# GKE scheduling metadata: accelerator-type string (the
# ``cloud.google.com/gke-tpu-accelerator`` nodeSelector value) →
# the topologies a user may request and chips-per-host. This drives the
# platform side: the notebook-controller turns (type, topology) into
# ``google.com/tpu`` limits + topology nodeSelectors, and multi-host
# topologies into StatefulSet replicas == host count.
TPU_TOPOLOGIES = {
    "tpu-v5-lite-podslice": {  # v5e
        "chips_per_host": 4,
        "topologies": ["1x1", "2x2", "2x4", "4x4", "4x8", "8x8", "8x16", "16x16"],
    },
    "tpu-v5p-slice": {
        "chips_per_host": 4,
        "topologies": ["2x2x1", "2x2x2", "2x4x4", "4x4x4", "4x4x8", "8x8x8"],
    },
    "tpu-v6e-slice": {
        "chips_per_host": 4,
        "topologies": ["1x1", "2x2", "2x4", "4x4", "4x8", "8x8", "8x16", "16x16"],
    },
}


def chips_in_topology(topology: str) -> int:
    n = 1
    for part in topology.split("x"):
        n *= int(part)
    return n


def hosts_in_slice(accelerator_type: str, topology: str) -> int:
    meta = TPU_TOPOLOGIES[accelerator_type]
    chips = chips_in_topology(topology)
    return max(1, chips // meta["chips_per_host"])
