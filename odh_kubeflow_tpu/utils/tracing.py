"""Trace propagation + structured logging for the platform.

The reference gets request correlation for free from controller-runtime
zap logs and kube-apiserver audit IDs; this from-scratch runtime needs
its own: W3C ``traceparent``-style context carried over the embedded
REST façade, a contextvar-propagated span so any code (admission hook,
store write, reconcile) can ask "what request am I part of", and a JSON
log formatter that stamps every record with ``trace_id``/``span_id``
plus span attributes (``controller``, ``reconcile_key``).

The trace crosses the async apiserver→controller hop via an object
annotation: the store stamps ``TRACE_ANNOTATION`` on CREATE when a span
is active, and the controller runtime picks it up from the watch event
so the reconcile's log records share the originating request's
trace_id (webhook admission → apiserver write → reconcile is one
trace).
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import logging
import re
import time
import uuid
from contextvars import ContextVar
from typing import Any, Iterator, Mapping, Optional

# stamped by the embedded store on CREATE (see machinery/store.py)
TRACE_ANNOTATION = "odh.kubeflow.org/trace-id"

_TRACEPARENT_RE = re.compile(
    r"^[0-9a-f]{2}-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$"
)


def new_trace_id() -> str:
    return uuid.uuid4().hex  # 32 hex chars


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


@dataclasses.dataclass(frozen=True)
class SpanContext:
    trace_id: str
    span_id: str
    parent_span_id: str = ""
    name: str = ""
    # searchable log dimensions (controller, reconcile_key, ...)
    attrs: Mapping[str, str] = dataclasses.field(default_factory=dict)

    def traceparent(self) -> str:
        """W3C trace-context header value (version 00, sampled)."""
        return f"00-{self.trace_id}-{self.span_id}-01"


_current: ContextVar[Optional[SpanContext]] = ContextVar(
    "odh_current_span", default=None
)


def current() -> Optional[SpanContext]:
    return _current.get()


def traceparent() -> Optional[str]:
    span = _current.get()
    return span.traceparent() if span is not None else None


def parse_traceparent(header: Optional[str]) -> Optional[SpanContext]:
    """Remote context from a ``traceparent`` header value (or None for
    absent/malformed — a bad header must never fail the request)."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip())
    if not m:
        return None
    return SpanContext(trace_id=m.group(1), span_id=m.group(2), name="remote")


@contextlib.contextmanager
def span(
    name: str,
    trace_id: Optional[str] = None,
    parent: Optional[SpanContext] = None,
    **attrs: str,
) -> Iterator[SpanContext]:
    """Enter a span: child of ``parent`` (explicit, or the contextvar's
    current span), or a fresh trace root. ``trace_id`` forces the trace
    (the annotation-carried cross-process hop); attrs merge over the
    parent's when staying in the same trace."""
    if parent is None:
        parent = _current.get()
    if trace_id is not None and parent is not None and parent.trace_id != trace_id:
        parent = None  # forced onto a different trace: not a child
    tid = trace_id or (parent.trace_id if parent is not None else new_trace_id())
    merged: dict[str, str] = dict(parent.attrs) if parent is not None else {}
    merged.update(attrs)
    ctx = SpanContext(
        trace_id=tid,
        span_id=new_span_id(),
        parent_span_id=parent.span_id if parent is not None else "",
        name=name,
        attrs=merged,
    )
    token = _current.set(ctx)
    try:
        yield ctx
    finally:
        _current.reset(token)


@contextlib.contextmanager
def use_span(ctx: Optional[SpanContext]) -> Iterator[Optional[SpanContext]]:
    """Install an existing (e.g. header-parsed) context as current; a
    None ctx is a no-op so callers needn't branch."""
    if ctx is None:
        yield None
        return
    token = _current.set(ctx)
    try:
        yield ctx
    finally:
        _current.reset(token)


def traced(fn=None, *, name: Optional[str] = None):
    """Decorator: run the function inside a span named after it."""

    def deco(f):
        import functools

        span_name = name or f.__qualname__

        @functools.wraps(f)
        def wrapper(*args: Any, **kwargs: Any):
            with span(span_name):
                return f(*args, **kwargs)

        return wrapper

    return deco(fn) if fn is not None else deco


def trace_id_of(obj: Mapping[str, Any]) -> Optional[str]:
    """The trace annotation stamped on a stored object, if any."""
    meta = obj.get("metadata") or {}
    ann = meta.get("annotations") or {}
    tid = ann.get(TRACE_ANNOTATION)
    return tid if isinstance(tid, str) and tid else None


# ---------------------------------------------------------------------------
# structured logging


class JsonLogFormatter(logging.Formatter):
    """One JSON object per record, trace-correlated: ``trace_id``/
    ``span_id``/``span`` plus span attrs (``controller``,
    ``reconcile_key``) come from the contextvar at emit time — handlers
    format synchronously on the emitting thread, so the context is the
    record's."""

    def format(self, record: logging.LogRecord) -> str:
        out: dict[str, Any] = {
            "ts": time.strftime(
                "%Y-%m-%dT%H:%M:%S", time.gmtime(record.created)
            )
            + f".{int(record.msecs):03d}Z",
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        ctx = _current.get()
        if ctx is not None:
            out["trace_id"] = ctx.trace_id
            out["span_id"] = ctx.span_id
            if ctx.name:
                out["span"] = ctx.name
            out.update(ctx.attrs)
        if record.exc_info and record.exc_info[0] is not None:
            out["exception"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)


def configure_json_logging(level: int = logging.INFO) -> logging.Handler:
    """Install a JSON-formatted stderr handler on the root logger (the
    split-process entrypoints' default posture)."""
    handler = logging.StreamHandler()
    handler.setFormatter(JsonLogFormatter())
    root = logging.getLogger()
    root.addHandler(handler)
    root.setLevel(level)
    return handler
