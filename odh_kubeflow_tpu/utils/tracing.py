"""Trace propagation, span recording + structured logging.

The reference gets request correlation for free from controller-runtime
zap logs and kube-apiserver audit IDs; this from-scratch runtime needs
its own: W3C ``traceparent``-style context carried over the embedded
REST façade, a contextvar-propagated span so any code (admission hook,
store write, reconcile) can ask "what request am I part of", and a JSON
log formatter that stamps every record with ``trace_id``/``span_id``
plus span attributes (``controller``, ``reconcile_key``).

The trace crosses the async apiserver→controller hop via an object
annotation: the store stamps ``TRACE_ANNOTATION`` on CREATE when a span
is active, and the controller runtime picks it up from the watch event
so the reconcile's log records share the originating request's
trace_id (webhook admission → apiserver write → reconcile is one
trace).

Beyond propagation, spans are *recorded*: every ``span()`` exit emits a
:class:`SpanRecord` (wall start, duration, ok/error status with the
exception captured, span events) into the process
:class:`SpanCollector` — a bounded ring buffer with **tail-based keep
rules**: error traces and traces whose root span exceeds its latency
threshold are promoted out of the ring into a kept-trace store, pulling
their already-recorded child spans with them (the decision is made at
the *tail* of the trace, when the outcome is known). Split-process
components ship finished spans to the apiserver's
``/debug/traces/ingest`` with :class:`RemoteSpanExporter`, so a trace
assembled from webhook→store→reconcile→scheduler→kubelet hops renders
as one tree on the apiserver's ``/debug/traces`` zpage.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import logging
import re
import threading
import time
import uuid
from collections import OrderedDict, deque
from contextvars import ContextVar
from typing import Any, Callable, Iterator, Mapping, Optional

# stamped by the embedded store on CREATE (see machinery/store.py)
TRACE_ANNOTATION = "odh.kubeflow.org/trace-id"

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)


def new_trace_id() -> str:
    return uuid.uuid4().hex  # 32 hex chars


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


@dataclasses.dataclass(frozen=True)
class SpanContext:
    trace_id: str
    span_id: str
    parent_span_id: str = ""
    name: str = ""
    # searchable log dimensions (controller, reconcile_key, ...)
    attrs: Mapping[str, str] = dataclasses.field(default_factory=dict)
    trace_flags: str = "01"
    # recording state: the dataclass binding is frozen, the CONTENTS
    # mutate while the span is open (events appended, status set) —
    # compare/hash never look at them
    events: list = dataclasses.field(
        default_factory=list, compare=False, repr=False
    )
    _mut: dict = dataclasses.field(
        default_factory=dict, compare=False, repr=False
    )

    def traceparent(self) -> str:
        """W3C trace-context header value (version 00)."""
        return f"00-{self.trace_id}-{self.span_id}-{self.trace_flags}"


_current: ContextVar[Optional[SpanContext]] = ContextVar(
    "odh_current_span", default=None
)


def current() -> Optional[SpanContext]:
    return _current.get()


def traceparent() -> Optional[str]:
    span = _current.get()
    return span.traceparent() if span is not None else None


def parse_traceparent(header: Optional[str]) -> Optional[SpanContext]:
    """Remote context from a ``traceparent`` header value (or None for
    absent/malformed — a bad header must never fail the request).
    Per W3C trace-context: version ``ff`` is forbidden, and all-zero
    trace/parent ids are invalid."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip())
    if not m:
        return None
    version, trace_id, span_id, flags = m.groups()
    if version == "ff":
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return SpanContext(
        trace_id=trace_id, span_id=span_id, name="remote", trace_flags=flags
    )


# ---------------------------------------------------------------------------
# span recording


@dataclasses.dataclass
class SpanRecord:
    """One finished span — what the collector stores and the ingest
    endpoint ships. ``start`` is wall-clock epoch seconds (cross-process
    assembly orders by it), ``duration`` comes from a monotonic clock."""

    trace_id: str
    span_id: str
    parent_span_id: str
    name: str
    start: float
    duration: float
    status: str = "ok"  # "ok" | "error"
    error: str = ""
    attrs: dict = dataclasses.field(default_factory=dict)
    events: list = dataclasses.field(default_factory=list)

    @property
    def end(self) -> float:
        return self.start + self.duration

    def to_dict(self) -> dict:
        return {
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "parentSpanId": self.parent_span_id,
            "name": self.name,
            "start": self.start,
            "duration": self.duration,
            "status": self.status,
            "error": self.error,
            "attrs": dict(self.attrs),
            "events": [list(e) for e in self.events],
        }

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "SpanRecord":
        return cls(
            trace_id=str(d.get("traceId", "")),
            span_id=str(d.get("spanId", "")),
            parent_span_id=str(d.get("parentSpanId", "")),
            name=str(d.get("name", "")),
            start=float(d.get("start", 0.0)),
            duration=float(d.get("duration", 0.0)),
            status=str(d.get("status", "ok")),
            error=str(d.get("error", "")),
            attrs=dict(d.get("attrs") or {}),
            events=[list(e) for e in (d.get("events") or [])],
        )


class SpanCollector:
    """Bounded in-process span store with tail-based keep rules.

    Finished spans land in a ring buffer (``capacity`` newest spans).
    When a span finishes with an error, or a ROOT span (no parent)
    finishes over its latency threshold, its whole trace is promoted
    into the kept store — including child spans already sitting in the
    ring (that is what makes the sampling *tail-based*: the decision
    happens when the outcome is known, and the history is still
    around). The kept store holds the ``max_kept`` newest interesting
    traces; later spans of a kept trace append to it directly.

    Per-root-name latency thresholds (``set_threshold``) let the spawn
    path keep a tighter bar than, say, a bulk list endpoint."""

    def __init__(
        self,
        capacity: int = 4096,
        max_kept: int = 128,
        default_threshold_s: float = 1.0,
        max_spans_per_trace: int = 512,
    ):
        self.capacity = capacity
        self.max_kept = max_kept
        self.default_threshold_s = default_threshold_s
        # a kept trace is bounded too: a crash-looping reconcile keeps
        # retrying under ONE trace id (the retry is the same unit of
        # work) and would otherwise grow its kept entry forever
        self.max_spans_per_trace = max_spans_per_trace
        self.trace_spans_dropped_total = 0
        self._thresholds: dict[str, float] = {}
        self._ring: deque[SpanRecord] = deque(maxlen=capacity)
        self._kept: "OrderedDict[str, list[SpanRecord]]" = OrderedDict()
        self._kept_reason: dict[str, str] = {}
        self._lock = threading.Lock()
        self.recorded_total = 0

    def set_threshold(self, root_name: str, seconds: float) -> None:
        with self._lock:
            self._thresholds[root_name] = float(seconds)

    def threshold_for(self, name: str) -> float:
        return self._thresholds.get(name, self.default_threshold_s)

    def record(self, rec: SpanRecord) -> None:
        if not rec.trace_id:
            return
        with self._lock:
            self.recorded_total += 1
            kept = self._kept.get(rec.trace_id)
            if kept is not None:
                if len(kept) < self.max_spans_per_trace:
                    kept.append(rec)
                else:
                    self.trace_spans_dropped_total += 1
                return
            self._ring.append(rec)
            reason = None
            if rec.status == "error":
                reason = "error"
            elif (
                not rec.parent_span_id
                and rec.duration >= self.threshold_for(rec.name)
            ):
                reason = "slow"
            if reason is not None:
                self._promote(rec.trace_id, reason)

    def _promote(self, trace_id: str, reason: str) -> None:
        # pull every span of the trace still in the ring; they stay in
        # the ring too (it ages them out naturally) but reads prefer
        # the kept copy
        spans = [r for r in self._ring if r.trace_id == trace_id][
            : self.max_spans_per_trace
        ]
        while len(self._kept) >= self.max_kept:
            old, _ = self._kept.popitem(last=False)
            self._kept_reason.pop(old, None)
        self._kept[trace_id] = spans
        self._kept_reason[trace_id] = reason

    def trace(self, trace_id: str) -> list[SpanRecord]:
        """Every recorded span of a trace — kept store first, then the
        recent ring (a trace needn't be slow/error to be fetched by
        id; the spawn bench reads its own trace this way)."""
        with self._lock:
            kept = self._kept.get(trace_id)
            if kept is not None:
                return list(kept)
            return [r for r in self._ring if r.trace_id == trace_id]

    def keep_reason(self, trace_id: str) -> Optional[str]:
        with self._lock:
            return self._kept_reason.get(trace_id)

    def kept_traces(self, limit: int = 50) -> list[tuple[str, str, list[SpanRecord]]]:
        """Newest-first kept (slow/error) traces as
        ``(trace_id, reason, spans)``."""
        with self._lock:
            out = [
                (tid, self._kept_reason.get(tid, ""), list(spans))
                for tid, spans in reversed(self._kept.items())
            ]
        return out[:limit]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._kept.clear()
            self._kept_reason.clear()


_collector = SpanCollector()
_sinks: list[Callable[[SpanRecord], None]] = []


def collector() -> SpanCollector:
    return _collector


def set_collector(c: SpanCollector) -> SpanCollector:
    global _collector
    old, _collector = _collector, c
    return old


def add_sink(fn: Callable[[SpanRecord], None]) -> None:
    """Register an extra consumer of finished spans (the remote
    exporter). Sinks must never raise into the traced code path."""
    _sinks.append(fn)


def remove_sink(fn: Callable[[SpanRecord], None]) -> None:
    with contextlib.suppress(ValueError):
        _sinks.remove(fn)


def record_span(rec: SpanRecord) -> None:
    _collector.record(rec)
    for fn in list(_sinks):
        try:
            fn(rec)
        except Exception:  # noqa: BLE001 — telemetry must not break callers
            pass


def add_event(name: str, **attrs: str) -> None:
    """Attach a timestamped event to the current span (no-op outside
    any span)."""
    ctx = _current.get()
    if ctx is not None:
        ctx.events.append((time.time(), name, attrs))


def set_status(status: str, message: str = "") -> None:
    """Set the current span's status explicitly ('ok'/'error'). An
    exception escaping the span still wins (always 'error')."""
    ctx = _current.get()
    if ctx is not None:
        ctx._mut["status"] = status
        if message:
            ctx._mut["error"] = message


def discard() -> None:
    """Mark the current span as not worth recording (e.g. a retried
    gang-bind attempt that didn't land — only the landed one is the
    trace's bind)."""
    ctx = _current.get()
    if ctx is not None:
        ctx._mut["discard"] = True


@contextlib.contextmanager
def span(
    name: str,
    trace_id: Optional[str] = None,
    parent: Optional[SpanContext] = None,
    **attrs: str,
) -> Iterator[SpanContext]:
    """Enter a span: child of ``parent`` (explicit, or the contextvar's
    current span), or a fresh trace root. ``trace_id`` forces the trace
    (the annotation-carried cross-process hop); attrs merge over the
    parent's when staying in the same trace.

    On exit the span is *recorded*: wall start + monotonic duration,
    status (an escaping exception ⇒ 'error' with the exception
    captured), and any ``add_event`` events flow into the process
    collector and sinks."""
    if parent is None:
        parent = _current.get()
    if trace_id is not None and parent is not None and parent.trace_id != trace_id:
        parent = None  # forced onto a different trace: not a child
    tid = trace_id or (parent.trace_id if parent is not None else new_trace_id())
    merged: dict[str, str] = dict(parent.attrs) if parent is not None else {}
    merged.update(attrs)
    ctx = SpanContext(
        trace_id=tid,
        span_id=new_span_id(),
        parent_span_id=parent.span_id if parent is not None else "",
        name=name,
        attrs=merged,
    )
    token = _current.set(ctx)
    start_wall = time.time()
    t0 = time.perf_counter()
    status, error = "ok", ""
    try:
        yield ctx
    except BaseException as e:
        status, error = "error", f"{type(e).__name__}: {e}"
        raise
    finally:
        _current.reset(token)
        if not ctx._mut.get("discard"):
            if status != "error":
                status = ctx._mut.get("status", status)
                error = ctx._mut.get("error", error)
            record_span(
                SpanRecord(
                    trace_id=ctx.trace_id,
                    span_id=ctx.span_id,
                    parent_span_id=ctx.parent_span_id,
                    name=name,
                    start=start_wall,
                    duration=time.perf_counter() - t0,
                    status=status,
                    error=error,
                    attrs=dict(attrs),
                    events=[
                        (ts, ename, dict(eattrs))
                        for ts, ename, eattrs in ctx.events
                    ],
                )
            )


def child_span(name: str, **attrs: str):
    """A span only when a trace is already active — hot paths (store
    mutations) use this so untraced operations pay one contextvar read
    and nothing else."""
    if _current.get() is None:
        return contextlib.nullcontext(None)
    return span(name, **attrs)


def nested_parent(remote: Optional[SpanContext]) -> Optional[SpanContext]:
    """The parent a request span should use for an inbound remote
    context: when an in-process wrapper (the event-loop dispatch span)
    already continued the SAME trace, nest under it instead of forking
    a sibling off the remote parent. One home for the rule, shared by
    every server front end (microweb, httpapi)."""
    cur = _current.get()
    if (
        cur is not None
        and remote is not None
        and cur.trace_id == remote.trace_id
    ):
        return cur
    return remote


@contextlib.contextmanager
def use_span(ctx: Optional[SpanContext]) -> Iterator[Optional[SpanContext]]:
    """Install an existing (e.g. header-parsed) context as current; a
    None ctx is a no-op so callers needn't branch. Installation only —
    nothing is recorded on exit (the remote end records its own)."""
    if ctx is None:
        yield None
        return
    token = _current.set(ctx)
    try:
        yield ctx
    finally:
        _current.reset(token)


def traced(fn=None, *, name: Optional[str] = None):
    """Decorator: run the function inside a span named after it."""

    def deco(f):
        import functools

        span_name = name or f.__qualname__

        @functools.wraps(f)
        def wrapper(*args: Any, **kwargs: Any):
            with span(span_name):
                return f(*args, **kwargs)

        return wrapper

    return deco(fn) if fn is not None else deco


def trace_id_of(obj: Mapping[str, Any]) -> Optional[str]:
    """The trace annotation stamped on a stored object, if any."""
    meta = obj.get("metadata") or {}
    ann = meta.get("annotations") or {}
    tid = ann.get(TRACE_ANNOTATION)
    return tid if isinstance(tid, str) and tid else None


# ---------------------------------------------------------------------------
# trace assembly + rendering (the /debug/traces zpage and the spawn
# bench's breakdown both consume these)


def assemble(spans: list[SpanRecord]) -> Optional[dict]:
    """One tree from a trace's flat spans: ``{"span": SpanRecord,
    "children": [...]}``. Cross-process traces routinely contain spans
    whose parent was recorded in another process (or is the caller's
    unrecorded client span) — every such orphan attaches under the
    PRIMARY root (the earliest-starting orphan), so the trace renders
    as one tree, not a forest.

    Defensive against malformed input (the ingest endpoint accepts
    spans from anywhere): self-parented spans, parent cycles, and
    duplicate ids can never crash assembly or drop spans — cycle
    members break at their first revisit and re-attach under the
    root, and a trace with no orphan at all (pure cycle) roots at the
    earliest span."""
    if not spans:
        return None
    by_id = {s.span_id: s for s in spans}
    children: dict[str, list[SpanRecord]] = {}
    orphans: list[SpanRecord] = []
    for s in spans:
        if (
            s.parent_span_id
            and s.parent_span_id in by_id
            and s.parent_span_id != s.span_id
        ):
            children.setdefault(s.parent_span_id, []).append(s)
        else:
            orphans.append(s)
    orphans.sort(key=lambda s: s.start)
    root = orphans[0] if orphans else min(spans, key=lambda s: s.start)
    for s in orphans[1:]:
        children.setdefault(root.span_id, []).append(s)

    visited: set[int] = set()  # by object identity: ids may collide

    def node(s: SpanRecord) -> dict:
        visited.add(id(s))
        kids = []
        for c in sorted(children.get(s.span_id, []), key=lambda c: c.start):
            if id(c) in visited:
                continue  # cycle edge: already placed elsewhere
            kids.append(node(c))
        return {"span": s, "children": kids}

    tree = node(root)
    # cycle islands unreachable from the root attach under it, so the
    # tree always covers every span exactly once
    for s in sorted(spans, key=lambda s: s.start):
        if id(s) not in visited:
            tree["children"].append(node(s))
    return tree


def render_trace(spans: list[SpanRecord], reason: str = "") -> str:
    """Indented text tree with durations — the zpage's human view."""
    tree = assemble(spans)
    if tree is None:
        return "(no spans)\n"
    root: SpanRecord = tree["span"]
    total = max((s.end for s in spans), default=root.end) - root.start
    lines = [
        f"trace {root.trace_id}  spans={len(spans)}  "
        f"span_total={total * 1000:.1f}ms"
        + (f"  keep={reason}" if reason else "")
    ]

    def walk(node: dict, depth: int) -> None:
        s: SpanRecord = node["span"]
        mark = "  !ERROR" if s.status == "error" else ""
        attrs = "".join(
            f" {k}={v}" for k, v in sorted(s.attrs.items())
        )
        lines.append(
            f"{'  ' * (depth + 1)}{s.name}  {s.duration * 1000:.2f}ms"
            f"  +{(s.start - root.start) * 1000:.1f}ms{attrs}{mark}"
            + (f"  ({s.error})" if s.error else "")
        )
        for ev in s.events:
            ts, ename = ev[0], ev[1]
            lines.append(
                f"{'  ' * (depth + 2)}@ +{(ts - root.start) * 1000:.1f}ms "
                f"{ename}"
            )
        for child in node["children"]:
            walk(child, depth + 1)

    walk(tree, 0)
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# cross-process span shipping


class RemoteSpanExporter:
    """Ships finished spans to an apiserver's ``/debug/traces/ingest``
    in background batches, so split-process components' spans assemble
    into one tree on the apiserver's zpage. Best-effort by design: a
    down endpoint drops batches (counted) — telemetry must never
    backpressure the traced work."""

    def __init__(
        self,
        base_url: str,
        flush_interval: float = 1.0,
        max_batch: int = 512,
        max_buffer: int = 8192,
    ):
        self.base_url = base_url.rstrip("/")
        self.flush_interval = flush_interval
        self.max_batch = max_batch
        self.max_buffer = max_buffer
        self.dropped_total = 0
        self.shipped_total = 0
        self._buf: list[SpanRecord] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def __call__(self, rec: SpanRecord) -> None:  # the sink interface
        with self._lock:
            if len(self._buf) >= self.max_buffer:
                self.dropped_total += 1
                return
            self._buf.append(rec)

    def install(self) -> "RemoteSpanExporter":
        add_sink(self)
        self._thread = threading.Thread(
            target=self._loop, name="span-exporter", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.flush_interval):
            self.flush()

    def flush(self) -> None:
        while True:
            with self._lock:
                batch, self._buf = (
                    self._buf[: self.max_batch],
                    self._buf[self.max_batch :],
                )
            if not batch:
                return
            try:
                self._post(batch)
                self.shipped_total += len(batch)
            except Exception:  # noqa: BLE001 — drop, never raise
                self.dropped_total += len(batch)
            if len(batch) < self.max_batch:
                return

    def _post(self, batch: list[SpanRecord]) -> None:
        import urllib.request

        body = json.dumps(
            {"spans": [r.to_dict() for r in batch]}
        ).encode()
        req = urllib.request.Request(
            self.base_url + "/debug/traces/ingest",
            data=body,
            method="POST",
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=5) as resp:
            resp.read()

    def close(self) -> None:
        remove_sink(self)
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.flush()


# ---------------------------------------------------------------------------
# structured logging


class JsonLogFormatter(logging.Formatter):
    """One JSON object per record, trace-correlated: ``trace_id``/
    ``span_id``/``span``/``trace_flags`` plus span attrs
    (``controller``, ``reconcile_key``) come from the contextvar at
    emit time — handlers format synchronously on the emitting thread,
    so the context is the record's. A span status set via
    :func:`set_status` is stamped as ``span.status``."""

    def format(self, record: logging.LogRecord) -> str:
        out: dict[str, Any] = {
            "ts": time.strftime(
                "%Y-%m-%dT%H:%M:%S", time.gmtime(record.created)
            )
            + f".{int(record.msecs):03d}Z",
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        ctx = _current.get()
        if ctx is not None:
            out["trace_id"] = ctx.trace_id
            out["span_id"] = ctx.span_id
            out["trace_flags"] = ctx.trace_flags
            if ctx.name:
                out["span"] = ctx.name
            if ctx._mut.get("status"):
                out["span.status"] = ctx._mut["status"]
            out.update(ctx.attrs)
        if record.exc_info and record.exc_info[0] is not None:
            out["exception"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)


def configure_json_logging(level: int = logging.INFO) -> logging.Handler:
    """Install a JSON-formatted stderr handler on the root logger (the
    split-process entrypoints' default posture). Idempotent: repeat
    calls return the already-installed handler instead of stacking
    duplicates (every log line would otherwise print once per call)."""
    root = logging.getLogger()
    for h in root.handlers:
        if getattr(h, "_odh_json_handler", False):
            root.setLevel(level)
            return h
    handler = logging.StreamHandler()
    handler.setFormatter(JsonLogFormatter())
    handler._odh_json_handler = True  # type: ignore[attr-defined]
    root.addHandler(handler)
    root.setLevel(level)
    return handler
