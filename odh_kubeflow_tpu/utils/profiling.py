"""XLA/TPU profiler integration (BASELINE config #3's client half).

The platform story: a user captures traces from their notebook with
:func:`capture_trace` (or serves live with :func:`start_server` for
on-demand capture), writes them to a PVC or ``gs://`` bucket, and the
tensorboard-controller serves them (``controllers/tensorboard.py``
treats ``gs://`` as primary — that's where XLA traces land on TPU
pods). The layout produced here is exactly TensorBoard's profile
plugin contract: ``<logdir>/plugins/profile/<session>/<host>.xplane.pb``
plus ``.trace.json.gz``.

``jupyter-jax-tpu`` images auto-start the profiler server in every
IPython kernel (images/jupyter/start-jupyter.sh seeds the startup
file), so TensorBoard's "capture profile" button works against a
running notebook with zero user code.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
from contextlib import contextmanager
from typing import Any, Optional

DEFAULT_PORT = int(os.environ.get("JAX_PROFILER_PORT", "9999"))


def start_server(port: Optional[int] = None):
    """Start the in-process profiler gRPC server TensorBoard's
    profile plugin captures from. Idempotent-ish: a second call in the
    same process raises inside jax; callers (the kernel-startup hook)
    guard with :func:`server_started`."""
    import jax

    port = port or DEFAULT_PORT
    server = jax.profiler.start_server(port)
    _STATE["server"] = server
    _STATE["port"] = port
    return server


def server_started() -> bool:
    return _STATE.get("server") is not None


_STATE: dict[str, Any] = {}


@contextmanager
def capture_trace(logdir: str):
    """Capture one profiling session into TensorBoard layout."""
    import jax

    os.makedirs(logdir, exist_ok=True)
    with jax.profiler.trace(logdir):
        yield
    # jax writes plugins/profile/<ts>/ under logdir


def trace_sessions(logdir: str) -> list[str]:
    """Session directories in TensorBoard profile-plugin layout,
    newest last."""
    return sorted(glob.glob(os.path.join(logdir, "plugins", "profile", "*")))


def latest_trace_events(logdir: str) -> list[dict]:
    """Parse the newest session's ``.trace.json.gz`` (the Chrome
    trace-event format TensorBoard's trace viewer renders) — the
    cheap validity check that what we captured is servable."""
    sessions = trace_sessions(logdir)
    if not sessions:
        return []
    files = glob.glob(os.path.join(sessions[-1], "*.trace.json.gz"))
    if not files:
        return []
    with gzip.open(files[0], "rt") as f:
        doc = json.load(f)
    return doc.get("traceEvents", [])


def kernel_startup_snippet() -> str:
    """The IPython-startup hook baked into TPU notebook images
    (images/jupyter/start-jupyter.sh seeds it into
    ``~/.ipython/profile_default/startup/``)."""
    return (
        "# auto-start the JAX profiler server so TensorBoard's\n"
        "# 'capture profile' works against this kernel (set\n"
        "# TPU_PROFILER_AUTOSTART=false to disable)\n"
        "import os as _os\n"
        "if _os.environ.get('TPU_PROFILER_AUTOSTART', 'true') == 'true':\n"
        "    try:\n"
        "        from odh_kubeflow_tpu.utils import profiling as _prof\n"
        "        if not _prof.server_started():\n"
        "            _prof.start_server()\n"
        "    except Exception:\n"
        "        pass\n"
    )
