"""Minimal Prometheus client: counters/gauges/histograms + custom
collectors with text exposition, served by the manager's metrics
endpoint.

Replaces the reference's use of prometheus/client_golang
(notebook-controller pkg/metrics/metrics.go:13-99, profile-controller
controllers/monitoring.go:19-75) — same metric surface, no dependency.

Exposition follows the Prometheus text format spec: label values are
escaped (``\\``, ``"``, newline), HELP text is escaped (``\\``,
newline), histograms emit cumulative ``le`` buckets ending in ``+Inf``
plus ``_sum``/``_count``. Metrics declared with ``labelnames`` never
emit a phantom unlabelled ``{name} 0`` sample; unlabelled counters and
gauges still expose their zero value on registration (client_golang
behaviour both ways).

Two exposition dialects from one registry:

- plain Prometheus text (``version=0.0.4``) — byte-stable with the
  pre-exemplar output, what every existing scrape sees;
- OpenMetrics (``exposition(openmetrics=True)``, negotiated via the
  ``Accept`` header — see :func:`negotiate_openmetrics`) — counter
  families drop the ``_total`` suffix from HELP/TYPE, the stream is
  ``# EOF``-terminated, and histogram buckets carry **exemplars**: at
  observe time the current trace id (``utils.tracing``) is attached to
  the bucket the value landed in, so a Grafana-style metric→trace
  pivot (bad p99 bucket → the request that caused it) works natively.
"""

from __future__ import annotations

import bisect
import re as _re
import threading
import time as _time
from typing import Any, Callable, Iterable, Optional, Sequence

OPENMETRICS_CONTENT_TYPE = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8"
)
PLAIN_CONTENT_TYPE = "text/plain; version=0.0.4"


def negotiate_openmetrics(accept: Optional[str]) -> bool:
    """Whether an ``Accept`` header asks for OpenMetrics (the
    content-negotiation Prometheus itself performs when scraping)."""
    return bool(accept) and "application/openmetrics-text" in accept


def _escape_label_value(v: str) -> str:
    """Text-format label-value escaping: backslash, double-quote,
    line-feed (in that order — escaping the escapes first)."""
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _escape_help(v: str) -> str:
    """HELP text escaping: backslash and line-feed only (quotes are
    legal in HELP)."""
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label_value(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    """Integral floats print without the trailing .0 (the conventional
    exposition shape for counters)."""
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


class _Child:
    """A metric bound to one label set — ``metric.labels(name="x")``
    returns one, so hot paths resolve their label dict once."""

    __slots__ = ("_metric", "_labels")

    def __init__(self, metric: "Metric", labels: dict[str, str]):
        self._metric = metric
        self._labels = labels

    def inc(self, by: float = 1.0) -> None:
        self._metric.inc(self._labels, by)

    def set(self, value: float) -> None:
        self._metric.set(value, self._labels)

    def observe(self, value: float) -> None:
        self._metric.observe(value, self._labels)

    def value(self) -> float:
        return self._metric.value(self._labels)


class Metric:
    def __init__(
        self,
        name: str,
        help_: str,
        typ: str,
        labelnames: Sequence[str] = (),
    ):
        self.name = name
        self.help = help_
        self.type = typ
        self.labelnames = tuple(labelnames)
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def _key(self, labels: Optional[dict[str, str]]):
        return tuple(sorted((labels or {}).items()))

    def labels(self, **labels: str) -> _Child:
        return _Child(self, labels)

    def samples(self) -> list[tuple[dict[str, str], float]]:
        """Every (labels, value) series — zpages and the SLO engine
        read live state through this instead of poking ``_values``."""
        with self._lock:
            return [
                (dict(k), v) for k, v in sorted(self._values.items())
            ]

    def sum_matching(self, match: Optional[dict[str, str]] = None) -> float:
        """Sum of all series whose labels are a superset of ``match``
        (empty match ⇒ the whole family). The SLO engine's total/bad
        counts aggregate label dimensions this way."""
        want = (match or {}).items()
        with self._lock:
            return sum(
                v
                for k, v in self._values.items()
                if all(item in k for item in want)
            )

    def _family_name(self, openmetrics: bool) -> str:
        # OpenMetrics names the counter FAMILY without the _total
        # suffix; the sample line keeps it
        if (
            openmetrics
            and self.type == "counter"
            and self.name.endswith("_total")
        ):
            return self.name[: -len("_total")]
        return self.name

    def collect(self, openmetrics: bool = False) -> Iterable[str]:
        fam = self._family_name(openmetrics)
        yield f"# HELP {fam} {_escape_help(self.help)}"
        yield f"# TYPE {fam} {self.type}"
        with self._lock:
            if not self._values and not self.labelnames:
                # an unlabelled metric exposes its zero value from
                # registration; a labelled family starts empty (no
                # phantom series)
                yield f"{self.name} 0"
            for key, value in sorted(self._values.items()):
                yield f"{self.name}{_fmt_labels(dict(key))} {_fmt_value(value)}"


class Counter(Metric):
    def __init__(self, name: str, help_: str, labelnames: Sequence[str] = ()):
        super().__init__(name, help_, "counter", labelnames)

    def inc(self, labels: Optional[dict[str, str]] = None, by: float = 1.0) -> None:
        with self._lock:
            key = self._key(labels)
            self._values[key] = self._values.get(key, 0.0) + by

    def value(self, labels: Optional[dict[str, str]] = None) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)


class Gauge(Metric):
    def __init__(self, name: str, help_: str, labelnames: Sequence[str] = ()):
        super().__init__(name, help_, "gauge", labelnames)

    def set(self, value: float, labels: Optional[dict[str, str]] = None) -> None:
        with self._lock:
            self._values[self._key(labels)] = value

    def inc(self, labels: Optional[dict[str, str]] = None, by: float = 1.0) -> None:
        with self._lock:
            key = self._key(labels)
            self._values[key] = self._values.get(key, 0.0) + by

    def value(self, labels: Optional[dict[str, str]] = None) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)


# client_golang's DefBuckets — latency-shaped, seconds
DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def _fmt_le(b: float) -> str:
    return str(int(b)) if float(b).is_integer() else repr(float(b))


def _current_trace_id() -> Optional[str]:
    """The active trace id (``utils.tracing`` contextvar) — the
    exemplar every histogram observation inside a traced request
    carries. Deferred import keeps this module importable standalone."""
    from odh_kubeflow_tpu.utils import tracing

    ctx = tracing.current()
    return ctx.trace_id if ctx is not None else None


class Histogram(Metric):
    """Cumulative-bucket histogram. Per label set it tracks one count
    per configured bucket plus sum/count; exposition emits the
    cumulative ``le`` series terminated by ``+Inf`` (== ``_count``).

    With ``exemplars`` on (the default), each observation made inside
    an active trace records ``(trace_id, value, timestamp)`` on the
    bucket it landed in (last-write-wins, the client_golang policy);
    OpenMetrics exposition renders them so a metric→trace pivot works.
    Plain-text exposition never shows them — it stays byte-stable."""

    def __init__(
        self,
        name: str,
        help_: str,
        buckets: Sequence[float] = DEFAULT_BUCKETS,
        labelnames: Sequence[str] = (),
        exemplars: bool = True,
    ):
        super().__init__(name, help_, "histogram", labelnames)
        if not buckets:
            raise ValueError(f"{name}: histogram needs at least one bucket")
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self.exemplars = exemplars
        # per key: [per-bucket non-cumulative counts, sum, count,
        #           per-bucket exemplar (trace_id, value, ts) or None]
        self._series: dict[tuple, list] = {}

    def observe(self, value: float, labels: Optional[dict[str, str]] = None) -> None:
        value = float(value)
        tid = _current_trace_id() if self.exemplars else None
        with self._lock:
            key = self._key(labels)
            st = self._series.get(key)
            if st is None:
                n = len(self.buckets) + 1
                st = self._series[key] = [[0] * n, 0.0, 0, [None] * n]
            # index of the first bucket >= value; the last slot is +Inf
            idx = bisect.bisect_left(self.buckets, value)
            st[0][idx] += 1
            st[1] += value
            st[2] += 1
            if tid is not None:
                st[3][idx] = (tid, value, _time.time())

    def value(self, labels: Optional[dict[str, str]] = None) -> float:
        """Observation count (the natural scalar view of a histogram)."""
        with self._lock:
            st = self._series.get(self._key(labels))
            return float(st[2]) if st is not None else 0.0

    def sum(self, labels: Optional[dict[str, str]] = None) -> float:
        with self._lock:
            st = self._series.get(self._key(labels))
            return float(st[1]) if st is not None else 0.0

    def samples(self) -> list[tuple[dict[str, str], float]]:
        """(labels, observation count) per series."""
        with self._lock:
            return [
                (dict(k), float(st[2]))
                for k, st in sorted(self._series.items())
            ]

    def count_matching(self, match: Optional[dict[str, str]] = None) -> float:
        """Total observations across series whose labels ⊇ ``match``."""
        want = (match or {}).items()
        with self._lock:
            return float(
                sum(
                    st[2]
                    for k, st in self._series.items()
                    if all(item in k for item in want)
                )
            )

    def count_le(
        self, le: float, match: Optional[dict[str, str]] = None
    ) -> float:
        """Cumulative observations ≤ the largest bucket boundary not
        exceeding ``le``, summed across series whose labels ⊇
        ``match`` — the "good events" count of a latency SLI. ``le``
        should be an exact bucket boundary (the SLO lint enforces it);
        a value between boundaries counts conservatively (the bucket
        below)."""
        # number of buckets whose boundary is <= le
        nbuckets = bisect.bisect_right(self.buckets, float(le))
        want = (match or {}).items()
        with self._lock:
            total = 0
            for k, st in self._series.items():
                if all(item in k for item in want):
                    total += sum(st[0][:nbuckets])
            return float(total)

    @staticmethod
    def _fmt_exemplar(ex) -> str:
        tid, value, ts = ex
        return (
            f' # {{trace_id="{_escape_label_value(tid)}"}} '
            f"{_fmt_value(value)} {ts:.3f}"
        )

    def _emit_series(
        self, labels: dict[str, str], st, openmetrics: bool
    ) -> Iterable[str]:
        counts, total, count, exs = st
        cum = 0
        for i, (b, c) in enumerate(zip(self.buckets, counts)):
            cum += c
            lab = _fmt_labels({**labels, "le": _fmt_le(b)})
            line = f"{self.name}_bucket{lab} {cum}"
            if openmetrics and exs[i] is not None:
                line += self._fmt_exemplar(exs[i])
            yield line
        lab = _fmt_labels({**labels, "le": "+Inf"})
        line = f"{self.name}_bucket{lab} {count}"
        if openmetrics and exs[-1] is not None:
            line += self._fmt_exemplar(exs[-1])
        yield line
        yield f"{self.name}_sum{_fmt_labels(labels)} {_fmt_value(total)}"
        yield f"{self.name}_count{_fmt_labels(labels)} {count}"

    def collect(self, openmetrics: bool = False) -> Iterable[str]:
        yield f"# HELP {self.name} {_escape_help(self.help)}"
        yield f"# TYPE {self.name} {self.type}"
        with self._lock:
            series = sorted(
                (k, [list(st[0]), st[1], st[2], list(st[3])])
                for k, st in self._series.items()
            )
        if not series and not self.labelnames:
            n = len(self.buckets) + 1
            series = [((), [[0] * n, 0.0, 0, [None] * n])]
        for key, st in series:
            yield from self._emit_series(dict(key), st, openmetrics)


class Registry:
    def __init__(self):
        self._metrics: list[Metric] = []
        self._by_name: dict[str, Metric] = {}
        self._collect_fns: list[Callable[[], Iterable[str]]] = []
        self._lock = threading.Lock()

    def register(self, metric: Metric) -> Metric:
        """Get-or-create by name: re-registering an existing family
        returns the live instance (so independently constructed
        components sharing one registry share the series — the
        client_golang AlreadyRegisteredError-recovery idiom)."""
        with self._lock:
            existing = self._by_name.get(metric.name)
            if existing is not None:
                if existing.type != metric.type:
                    raise ValueError(
                        f"metric {metric.name!r} already registered as "
                        f"{existing.type}, not {metric.type}"
                    )
                if existing.labelnames != metric.labelnames:
                    raise ValueError(
                        f"metric {metric.name!r} already registered with "
                        f"labelnames {existing.labelnames}, not "
                        f"{metric.labelnames}"
                    )
                if isinstance(metric, Histogram) and (
                    existing.buckets != metric.buckets  # type: ignore[attr-defined]
                ):
                    raise ValueError(
                        f"histogram {metric.name!r} already registered "
                        f"with buckets {existing.buckets}; a second "  # type: ignore[attr-defined]
                        "registration would silently mis-bucket"
                    )
                return existing
            self._metrics.append(metric)
            self._by_name[metric.name] = metric
        return metric

    def register_collector(self, fn: Callable[[], Iterable[str]]) -> None:
        """A custom collector producing exposition lines at scrape time
        (the reference uses this for the live running-notebook gauge)."""
        with self._lock:
            self._collect_fns.append(fn)

    def counter(
        self, name: str, help_: str, labelnames: Sequence[str] = ()
    ) -> Counter:
        return self.register(Counter(name, help_, labelnames))  # type: ignore[return-value]

    def gauge(
        self, name: str, help_: str, labelnames: Sequence[str] = ()
    ) -> Gauge:
        return self.register(Gauge(name, help_, labelnames))  # type: ignore[return-value]

    def histogram(
        self,
        name: str,
        help_: str,
        buckets: Sequence[float] = DEFAULT_BUCKETS,
        labelnames: Sequence[str] = (),
        exemplars: bool = True,
    ) -> Histogram:
        return self.register(  # type: ignore[return-value]
            Histogram(name, help_, buckets, labelnames, exemplars=exemplars)
        )

    def metrics(self) -> list[Metric]:
        with self._lock:
            return list(self._metrics)

    def metric(self, name: str) -> Optional[Metric]:
        """The registered family by name (the SLO engine resolves its
        spec references through this)."""
        with self._lock:
            return self._by_name.get(name)

    def exposition(self, openmetrics: bool = False) -> str:
        lines: list[str] = []
        with self._lock:
            metrics = list(self._metrics)
            fns = list(self._collect_fns)
        # custom collectors run FIRST (their lines still render last):
        # some flush batched hot-path counts into registered families
        # (informer cache hits/misses), which must land before those
        # families render
        collector_lines: list[str] = []
        for fn in fns:
            collector_lines.extend(fn())
        for m in metrics:
            lines.extend(m.collect(openmetrics=openmetrics))
        lines.extend(collector_lines)
        if openmetrics:
            lines.append("# EOF")
        return "\n".join(lines) + "\n"


default_registry = Registry()


# ---------------------------------------------------------------------------
# naming lint (tier-1 guard: new metrics can't drift from conventions)


# histogram names must end in their unit; _seconds is the default
# (latency histograms), _bytes/_records/_size cover the WAL/commit
# pipeline's size-shaped distributions
HISTOGRAM_UNIT_SUFFIXES = ("_seconds", "_bytes", "_records", "_size")


def metric_name_violations(
    name: str, typ: str, labelnames: Sequence[str] = ()
) -> list[str]:
    """Prometheus naming conventions for ONE metric family:
    - names are ``[a-z_][a-z0-9_]*`` (no uppercase, no leading digit);
    - counters end in ``_total``;
    - histograms end in their unit (``_seconds`` for durations,
      ``_bytes``/``_records``/``_size`` for size distributions);
    - nothing but counters claims the ``_total`` suffix.
    Shared by the live-registry lint below and graftlint's static
    ``metric-naming`` rule (analysis/rules.py), so the conventions
    cannot drift between the two checkers."""
    import re

    violations = []
    if not re.fullmatch(r"[a-z_][a-z0-9_]*", name):
        violations.append(
            f"{name}: must match [a-z_][a-z0-9_]* (lowercase only)"
        )
    if typ == "counter" and not name.endswith("_total"):
        violations.append(f"{name}: counter names must end in _total")
    if typ != "counter" and name.endswith("_total"):
        violations.append(f"{name}: _total suffix is reserved for counters")
    if typ == "histogram" and not name.endswith(HISTOGRAM_UNIT_SUFFIXES):
        violations.append(
            f"{name}: histograms must end in a unit suffix "
            f"{'/'.join(HISTOGRAM_UNIT_SUFFIXES)}"
        )
    for ln in labelnames:
        if not re.fullmatch(r"[a-z_][a-z0-9_]*", ln):
            violations.append(f"{name}: label {ln!r} must be lowercase")
    return violations


def lint_metric_names(registry: Registry) -> list[str]:
    """Naming conventions over a LIVE registry (what a process actually
    registered), complementing the static definition-site rule.
    Returns human-readable violations (empty == clean)."""
    violations = []
    for m in registry.metrics():
        violations.extend(
            metric_name_violations(m.name, m.type, m.labelnames)
        )
    return violations


# ---------------------------------------------------------------------------
# OpenMetrics parsing (tests + the SLO/exemplar tier-1 lint round-trip
# exposition through this, so the emitter can't drift from the format)

_SAMPLE_RE = _re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r"\s+(?P<value>[^ #]+)"
    r"(?:\s+(?P<ts>[0-9.e+-]+))?"
    r"(?:\s*#\s*\{(?P<exlabels>[^}]*)\}\s+(?P<exvalue>\S+)(?:\s+(?P<exts>\S+))?)?"
    r"\s*$"
)
_LABEL_RE = _re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape_label_value(v: str) -> str:
    return (
        v.replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")
    )


def _parse_labels(raw: Optional[str]) -> dict[str, str]:
    if not raw:
        return {}
    return {
        k: _unescape_label_value(v) for k, v in _LABEL_RE.findall(raw)
    }


def parse_openmetrics(text: str) -> dict[str, dict[str, Any]]:
    """Parse an OpenMetrics exposition into
    ``{family: {"type", "help", "samples": [(sample_name, labels,
    value, exemplar|None)]}}`` where an exemplar is ``(labels, value,
    timestamp|None)``. Validates the structural contract: ``# EOF``
    terminal, HELP/TYPE before samples, no content after EOF."""
    families: dict[str, dict[str, Any]] = {}
    lines = text.splitlines()
    saw_eof = False
    for line in lines:
        if not line.strip():
            continue
        if saw_eof:
            raise ValueError(f"content after # EOF: {line!r}")
        if line == "# EOF":
            saw_eof = True
            continue
        if line.startswith("# HELP "):
            name = line.split(" ", 3)[2]
            families.setdefault(
                name, {"help": None, "type": None, "samples": []}
            )["help"] = line.split(" ", 3)[3] if len(line.split(" ", 3)) > 3 else ""
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ", 3)
            if len(parts) < 4:
                raise ValueError(f"malformed TYPE line: {line!r}")
            families.setdefault(
                parts[2], {"help": None, "type": None, "samples": []}
            )["type"] = parts[3]
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"unparseable sample line: {line!r}")
        sample = m.group("name")
        # attribute the sample to its family (counter samples carry
        # _total; histogram samples carry _bucket/_sum/_count)
        base = sample
        for suffix in ("_bucket", "_sum", "_count", "_total"):
            if base.endswith(suffix) and base[: -len(suffix)] in families:
                base = base[: -len(suffix)]
                break
        if base not in families:
            raise ValueError(
                f"sample {sample!r} before its HELP/TYPE: {line!r}"
            )
        if families[base]["type"] is None:
            raise ValueError(f"sample {sample!r} with no TYPE")
        exemplar = None
        if m.group("exlabels") is not None:
            exemplar = (
                _parse_labels(m.group("exlabels")),
                float(m.group("exvalue")),
                float(m.group("exts")) if m.group("exts") else None,
            )
        families[base]["samples"].append(
            (sample, _parse_labels(m.group("labels")), float(m.group("value")), exemplar)
        )
    if not saw_eof:
        raise ValueError("OpenMetrics exposition must end with # EOF")
    return families


# ---------------------------------------------------------------------------
# serving


def metrics_app(registry: Registry):
    """WSGI app exposing ``registry`` at ``/metrics`` (and ``/``, the
    scrape-anything posture controller-runtime's metrics listener
    has). Content-negotiated: an ``Accept`` asking for
    ``application/openmetrics-text`` gets the exemplar-bearing
    OpenMetrics dialect; everything else gets byte-stable plain text."""

    def app(environ, start_response):
        if environ.get("PATH_INFO", "/") not in ("/", "/metrics"):
            start_response("404 Not Found", [("Content-Type", "text/plain")])
            return [b"not found"]
        om = negotiate_openmetrics(environ.get("HTTP_ACCEPT"))
        payload = registry.exposition(openmetrics=om).encode()
        start_response(
            "200 OK",
            [
                (
                    "Content-Type",
                    OPENMETRICS_CONTENT_TYPE if om else PLAIN_CONTENT_TYPE,
                ),
                ("Content-Length", str(len(payload))),
            ],
        )
        return [payload]

    return app


def serve_metrics(registry: Registry, host: str = "0.0.0.0", port: int = 8080):
    """Serve ``/metrics`` on a daemon thread (the controller-runtime
    metrics-bind-address equivalent for split-process components).
    Returns (thread, bound_port, httpd)."""
    from wsgiref.simple_server import WSGIRequestHandler, make_server

    class _Quiet(WSGIRequestHandler):
        def log_message(self, *args):  # noqa: D102 — stdlib signature
            pass

    httpd = make_server(host, port, metrics_app(registry), handler_class=_Quiet)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    return t, httpd.server_address[1], httpd
