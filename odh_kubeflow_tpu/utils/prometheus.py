"""Minimal Prometheus client: counters/gauges + custom collectors with
text exposition, served by the manager's metrics endpoint.

Replaces the reference's use of prometheus/client_golang
(notebook-controller pkg/metrics/metrics.go:13-99, profile-controller
controllers/monitoring.go:19-75) — same metric surface, no dependency.
"""

from __future__ import annotations

import threading
from typing import Callable, Iterable, Optional


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class Metric:
    def __init__(self, name: str, help_: str, typ: str):
        self.name = name
        self.help = help_
        self.type = typ
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def _key(self, labels: Optional[dict[str, str]]):
        return tuple(sorted((labels or {}).items()))

    def collect(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} {self.type}"
        with self._lock:
            if not self._values:
                yield f"{self.name} 0"
            for key, value in sorted(self._values.items()):
                yield f"{self.name}{_fmt_labels(dict(key))} {value}"


class Counter(Metric):
    def __init__(self, name: str, help_: str):
        super().__init__(name, help_, "counter")

    def inc(self, labels: Optional[dict[str, str]] = None, by: float = 1.0) -> None:
        with self._lock:
            key = self._key(labels)
            self._values[key] = self._values.get(key, 0.0) + by

    def value(self, labels: Optional[dict[str, str]] = None) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)


class Gauge(Metric):
    def __init__(self, name: str, help_: str):
        super().__init__(name, help_, "gauge")

    def set(self, value: float, labels: Optional[dict[str, str]] = None) -> None:
        with self._lock:
            self._values[self._key(labels)] = value

    def value(self, labels: Optional[dict[str, str]] = None) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)


class Registry:
    def __init__(self):
        self._metrics: list[Metric] = []
        self._collect_fns: list[Callable[[], Iterable[str]]] = []
        self._lock = threading.Lock()

    def register(self, metric: Metric) -> Metric:
        with self._lock:
            self._metrics.append(metric)
        return metric

    def register_collector(self, fn: Callable[[], Iterable[str]]) -> None:
        """A custom collector producing exposition lines at scrape time
        (the reference uses this for the live running-notebook gauge)."""
        with self._lock:
            self._collect_fns.append(fn)

    def counter(self, name: str, help_: str) -> Counter:
        return self.register(Counter(name, help_))  # type: ignore[return-value]

    def gauge(self, name: str, help_: str) -> Gauge:
        return self.register(Gauge(name, help_))  # type: ignore[return-value]

    def exposition(self) -> str:
        lines: list[str] = []
        with self._lock:
            metrics = list(self._metrics)
            fns = list(self._collect_fns)
        for m in metrics:
            lines.extend(m.collect())
        for fn in fns:
            lines.extend(fn())
        return "\n".join(lines) + "\n"


default_registry = Registry()
