"""Minimal Prometheus client: counters/gauges/histograms + custom
collectors with text exposition, served by the manager's metrics
endpoint.

Replaces the reference's use of prometheus/client_golang
(notebook-controller pkg/metrics/metrics.go:13-99, profile-controller
controllers/monitoring.go:19-75) — same metric surface, no dependency.

Exposition follows the Prometheus text format spec: label values are
escaped (``\\``, ``"``, newline), HELP text is escaped (``\\``,
newline), histograms emit cumulative ``le`` buckets ending in ``+Inf``
plus ``_sum``/``_count``. Metrics declared with ``labelnames`` never
emit a phantom unlabelled ``{name} 0`` sample; unlabelled counters and
gauges still expose their zero value on registration (client_golang
behaviour both ways).
"""

from __future__ import annotations

import bisect
import threading
from typing import Callable, Iterable, Optional, Sequence


def _escape_label_value(v: str) -> str:
    """Text-format label-value escaping: backslash, double-quote,
    line-feed (in that order — escaping the escapes first)."""
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _escape_help(v: str) -> str:
    """HELP text escaping: backslash and line-feed only (quotes are
    legal in HELP)."""
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label_value(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    """Integral floats print without the trailing .0 (the conventional
    exposition shape for counters)."""
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


class _Child:
    """A metric bound to one label set — ``metric.labels(name="x")``
    returns one, so hot paths resolve their label dict once."""

    __slots__ = ("_metric", "_labels")

    def __init__(self, metric: "Metric", labels: dict[str, str]):
        self._metric = metric
        self._labels = labels

    def inc(self, by: float = 1.0) -> None:
        self._metric.inc(self._labels, by)

    def set(self, value: float) -> None:
        self._metric.set(value, self._labels)

    def observe(self, value: float) -> None:
        self._metric.observe(value, self._labels)

    def value(self) -> float:
        return self._metric.value(self._labels)


class Metric:
    def __init__(
        self,
        name: str,
        help_: str,
        typ: str,
        labelnames: Sequence[str] = (),
    ):
        self.name = name
        self.help = help_
        self.type = typ
        self.labelnames = tuple(labelnames)
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def _key(self, labels: Optional[dict[str, str]]):
        return tuple(sorted((labels or {}).items()))

    def labels(self, **labels: str) -> _Child:
        return _Child(self, labels)

    def collect(self) -> Iterable[str]:
        yield f"# HELP {self.name} {_escape_help(self.help)}"
        yield f"# TYPE {self.name} {self.type}"
        with self._lock:
            if not self._values and not self.labelnames:
                # an unlabelled metric exposes its zero value from
                # registration; a labelled family starts empty (no
                # phantom series)
                yield f"{self.name} 0"
            for key, value in sorted(self._values.items()):
                yield f"{self.name}{_fmt_labels(dict(key))} {_fmt_value(value)}"


class Counter(Metric):
    def __init__(self, name: str, help_: str, labelnames: Sequence[str] = ()):
        super().__init__(name, help_, "counter", labelnames)

    def inc(self, labels: Optional[dict[str, str]] = None, by: float = 1.0) -> None:
        with self._lock:
            key = self._key(labels)
            self._values[key] = self._values.get(key, 0.0) + by

    def value(self, labels: Optional[dict[str, str]] = None) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)


class Gauge(Metric):
    def __init__(self, name: str, help_: str, labelnames: Sequence[str] = ()):
        super().__init__(name, help_, "gauge", labelnames)

    def set(self, value: float, labels: Optional[dict[str, str]] = None) -> None:
        with self._lock:
            self._values[self._key(labels)] = value

    def inc(self, labels: Optional[dict[str, str]] = None, by: float = 1.0) -> None:
        with self._lock:
            key = self._key(labels)
            self._values[key] = self._values.get(key, 0.0) + by

    def value(self, labels: Optional[dict[str, str]] = None) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)


# client_golang's DefBuckets — latency-shaped, seconds
DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def _fmt_le(b: float) -> str:
    return str(int(b)) if float(b).is_integer() else repr(float(b))


class Histogram(Metric):
    """Cumulative-bucket histogram. Per label set it tracks one count
    per configured bucket plus sum/count; exposition emits the
    cumulative ``le`` series terminated by ``+Inf`` (== ``_count``)."""

    def __init__(
        self,
        name: str,
        help_: str,
        buckets: Sequence[float] = DEFAULT_BUCKETS,
        labelnames: Sequence[str] = (),
    ):
        super().__init__(name, help_, "histogram", labelnames)
        if not buckets:
            raise ValueError(f"{name}: histogram needs at least one bucket")
        self.buckets = tuple(sorted(float(b) for b in buckets))
        # per key: (per-bucket non-cumulative counts, sum, count)
        self._series: dict[tuple, list] = {}

    def observe(self, value: float, labels: Optional[dict[str, str]] = None) -> None:
        value = float(value)
        with self._lock:
            key = self._key(labels)
            st = self._series.get(key)
            if st is None:
                st = self._series[key] = [[0] * (len(self.buckets) + 1), 0.0, 0]
            # index of the first bucket >= value; the last slot is +Inf
            st[0][bisect.bisect_left(self.buckets, value)] += 1
            st[1] += value
            st[2] += 1

    def value(self, labels: Optional[dict[str, str]] = None) -> float:
        """Observation count (the natural scalar view of a histogram)."""
        with self._lock:
            st = self._series.get(self._key(labels))
            return float(st[2]) if st is not None else 0.0

    def sum(self, labels: Optional[dict[str, str]] = None) -> float:
        with self._lock:
            st = self._series.get(self._key(labels))
            return float(st[1]) if st is not None else 0.0

    def _emit_series(self, labels: dict[str, str], st) -> Iterable[str]:
        counts, total, count = st
        cum = 0
        for b, c in zip(self.buckets, counts):
            cum += c
            lab = _fmt_labels({**labels, "le": _fmt_le(b)})
            yield f"{self.name}_bucket{lab} {cum}"
        lab = _fmt_labels({**labels, "le": "+Inf"})
        yield f"{self.name}_bucket{lab} {count}"
        yield f"{self.name}_sum{_fmt_labels(labels)} {_fmt_value(total)}"
        yield f"{self.name}_count{_fmt_labels(labels)} {count}"

    def collect(self) -> Iterable[str]:
        yield f"# HELP {self.name} {_escape_help(self.help)}"
        yield f"# TYPE {self.name} {self.type}"
        with self._lock:
            series = sorted(
                (k, [list(st[0]), st[1], st[2]])
                for k, st in self._series.items()
            )
        if not series and not self.labelnames:
            series = [((), [[0] * (len(self.buckets) + 1), 0.0, 0])]
        for key, st in series:
            yield from self._emit_series(dict(key), st)


class Registry:
    def __init__(self):
        self._metrics: list[Metric] = []
        self._by_name: dict[str, Metric] = {}
        self._collect_fns: list[Callable[[], Iterable[str]]] = []
        self._lock = threading.Lock()

    def register(self, metric: Metric) -> Metric:
        """Get-or-create by name: re-registering an existing family
        returns the live instance (so independently constructed
        components sharing one registry share the series — the
        client_golang AlreadyRegisteredError-recovery idiom)."""
        with self._lock:
            existing = self._by_name.get(metric.name)
            if existing is not None:
                if existing.type != metric.type:
                    raise ValueError(
                        f"metric {metric.name!r} already registered as "
                        f"{existing.type}, not {metric.type}"
                    )
                if existing.labelnames != metric.labelnames:
                    raise ValueError(
                        f"metric {metric.name!r} already registered with "
                        f"labelnames {existing.labelnames}, not "
                        f"{metric.labelnames}"
                    )
                if isinstance(metric, Histogram) and (
                    existing.buckets != metric.buckets  # type: ignore[attr-defined]
                ):
                    raise ValueError(
                        f"histogram {metric.name!r} already registered "
                        f"with buckets {existing.buckets}; a second "  # type: ignore[attr-defined]
                        "registration would silently mis-bucket"
                    )
                return existing
            self._metrics.append(metric)
            self._by_name[metric.name] = metric
        return metric

    def register_collector(self, fn: Callable[[], Iterable[str]]) -> None:
        """A custom collector producing exposition lines at scrape time
        (the reference uses this for the live running-notebook gauge)."""
        with self._lock:
            self._collect_fns.append(fn)

    def counter(
        self, name: str, help_: str, labelnames: Sequence[str] = ()
    ) -> Counter:
        return self.register(Counter(name, help_, labelnames))  # type: ignore[return-value]

    def gauge(
        self, name: str, help_: str, labelnames: Sequence[str] = ()
    ) -> Gauge:
        return self.register(Gauge(name, help_, labelnames))  # type: ignore[return-value]

    def histogram(
        self,
        name: str,
        help_: str,
        buckets: Sequence[float] = DEFAULT_BUCKETS,
        labelnames: Sequence[str] = (),
    ) -> Histogram:
        return self.register(Histogram(name, help_, buckets, labelnames))  # type: ignore[return-value]

    def metrics(self) -> list[Metric]:
        with self._lock:
            return list(self._metrics)

    def exposition(self) -> str:
        lines: list[str] = []
        with self._lock:
            metrics = list(self._metrics)
            fns = list(self._collect_fns)
        # custom collectors run FIRST (their lines still render last):
        # some flush batched hot-path counts into registered families
        # (informer cache hits/misses), which must land before those
        # families render
        collector_lines: list[str] = []
        for fn in fns:
            collector_lines.extend(fn())
        for m in metrics:
            lines.extend(m.collect())
        lines.extend(collector_lines)
        return "\n".join(lines) + "\n"


default_registry = Registry()


# ---------------------------------------------------------------------------
# naming lint (tier-1 guard: new metrics can't drift from conventions)


def metric_name_violations(
    name: str, typ: str, labelnames: Sequence[str] = ()
) -> list[str]:
    """Prometheus naming conventions for ONE metric family:
    - names are ``[a-z_][a-z0-9_]*`` (no uppercase, no leading digit);
    - counters end in ``_total``;
    - histograms record durations and end in ``_seconds``;
    - nothing but counters claims the ``_total`` suffix.
    Shared by the live-registry lint below and graftlint's static
    ``metric-naming`` rule (analysis/rules.py), so the conventions
    cannot drift between the two checkers."""
    import re

    violations = []
    if not re.fullmatch(r"[a-z_][a-z0-9_]*", name):
        violations.append(
            f"{name}: must match [a-z_][a-z0-9_]* (lowercase only)"
        )
    if typ == "counter" and not name.endswith("_total"):
        violations.append(f"{name}: counter names must end in _total")
    if typ != "counter" and name.endswith("_total"):
        violations.append(f"{name}: _total suffix is reserved for counters")
    if typ == "histogram" and not name.endswith("_seconds"):
        violations.append(f"{name}: duration histograms must end in _seconds")
    for ln in labelnames:
        if not re.fullmatch(r"[a-z_][a-z0-9_]*", ln):
            violations.append(f"{name}: label {ln!r} must be lowercase")
    return violations


def lint_metric_names(registry: Registry) -> list[str]:
    """Naming conventions over a LIVE registry (what a process actually
    registered), complementing the static definition-site rule.
    Returns human-readable violations (empty == clean)."""
    violations = []
    for m in registry.metrics():
        violations.extend(
            metric_name_violations(m.name, m.type, m.labelnames)
        )
    return violations


# ---------------------------------------------------------------------------
# serving


def metrics_app(registry: Registry):
    """WSGI app exposing ``registry`` at ``/metrics`` (and ``/``, the
    scrape-anything posture controller-runtime's metrics listener
    has)."""

    def app(environ, start_response):
        if environ.get("PATH_INFO", "/") not in ("/", "/metrics"):
            start_response("404 Not Found", [("Content-Type", "text/plain")])
            return [b"not found"]
        payload = registry.exposition().encode()
        start_response(
            "200 OK",
            [
                ("Content-Type", "text/plain; version=0.0.4"),
                ("Content-Length", str(len(payload))),
            ],
        )
        return [payload]

    return app


def serve_metrics(registry: Registry, host: str = "0.0.0.0", port: int = 8080):
    """Serve ``/metrics`` on a daemon thread (the controller-runtime
    metrics-bind-address equivalent for split-process components).
    Returns (thread, bound_port, httpd)."""
    from wsgiref.simple_server import WSGIRequestHandler, make_server

    class _Quiet(WSGIRequestHandler):
        def log_message(self, *args):  # noqa: D102 — stdlib signature
            pass

    httpd = make_server(host, port, metrics_app(registry), handler_class=_Quiet)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    return t, httpd.server_address[1], httpd
