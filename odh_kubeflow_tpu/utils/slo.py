"""Declarative SLOs evaluated as multi-window burn rates.

ROADMAP items 1/3/5 all gate on latency SLOs (TTFT p99, warm-start
<1s, spawn-ready); this module turns the live Prometheus registry into
the operator surface those gates need: each :class:`SLO` names a good/
total event pair (a latency histogram bucket, or a bad/total counter
pair), and the :class:`SLOEngine` samples the cumulative counts on a
cadence and computes **burn rates** over multiple windows — the
Google SRE-workbook multi-window multi-burn-rate alerting model:

    burn_rate(W) = (bad_W / total_W) / (1 - objective)

A burn rate of 1.0 consumes exactly the error budget over the SLO
period; 14.4 over a 5-minute window is the classic fast-burn page,
~1–6 over an hour the slow-burn ticket. The engine exposes every
(slo, window) pair as the ``slo_burn_rate`` gauge and as structured
rows for the dashboard's ``/api/slo``.

Cumulative counters can't answer "in the last 5 minutes" by
themselves, so the engine keeps a bounded ring of (timestamp, good,
total) samples per SLO and differences against the sample closest to
the window's left edge. ``time_fn`` is injectable; tests drive the
clock and call :meth:`SLOEngine.tick` directly."""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Any, Callable, Mapping, Optional

from odh_kubeflow_tpu.utils import prometheus

# the SRE-workbook pair: a fast window that pages on budget-torching
# regressions and a slow window that catches steady leaks
DEFAULT_WINDOWS: dict[str, float] = {"5m": 300.0, "1h": 3600.0}

# conventional alerting thresholds, applied per window: short windows
# (≤ FAST_WINDOW_MAX_SECONDS) page at the fast-burn rate, long windows
# ticket at the slow-burn rate
FAST_BURN_THRESHOLD = 14.4  # 5m window: 2% of a 30d budget in 1h
SLOW_BURN_THRESHOLD = 3.0  # 1h window: 10% of a 30d budget in ~10h
FAST_WINDOW_MAX_SECONDS = 900.0


def burn_threshold(window_seconds: float) -> float:
    """The alerting threshold appropriate to a window's length."""
    return (
        FAST_BURN_THRESHOLD
        if window_seconds <= FAST_WINDOW_MAX_SECONDS
        else SLOW_BURN_THRESHOLD
    )


@dataclasses.dataclass(frozen=True)
class SLO:
    """One objective. Exactly one SLI style is set:

    - **latency**: ``histogram`` + ``threshold_s`` — good events are
      observations ≤ threshold (which must be an exact bucket
      boundary; the tier-1 lint enforces it), total is the
      observation count. ``labels`` filters series (subset match).
    - **ratio**: ``total_metric``/``bad_metric`` counters (each with
      an optional label subset) — good = total − bad.
    """

    name: str
    description: str
    objective: float  # e.g. 0.99 → error budget 0.01
    histogram: str = ""
    threshold_s: float = 0.0
    labels: Mapping[str, str] = dataclasses.field(default_factory=dict)
    total_metric: str = ""
    total_labels: Mapping[str, str] = dataclasses.field(default_factory=dict)
    bad_metric: str = ""
    bad_labels: Mapping[str, str] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"SLO {self.name}: objective must be in (0, 1), "
                f"got {self.objective}"
            )
        if bool(self.histogram) == bool(self.total_metric):
            raise ValueError(
                f"SLO {self.name}: set exactly one of histogram= "
                "(latency SLI) or total_metric=/bad_metric= (ratio SLI)"
            )

    @property
    def budget(self) -> float:
        return 1.0 - self.objective

    def referenced_histograms(self) -> list[str]:
        return [self.histogram] if self.histogram else []


def default_slos() -> list[SLO]:
    """The platform's burn-rate surface. Every referenced histogram
    must exist in the platform registry with exemplars enabled and
    every ``threshold_s`` must be an exact bucket boundary — tier-1
    lint ``tests/test_slo.py::test_slo_specs_resolve_against_platform_registry``."""
    return [
        SLO(
            name="spawn-ready-p99",
            description=(
                "99% of notebook spawns reach Ready within 30s "
                "(platform path on the sim kubelet; excludes image pull)"
            ),
            objective=0.99,
            histogram="notebook_spawn_ready_seconds",
            threshold_s=30.0,
        ),
        SLO(
            name="web-serial-p99",
            description="99% of web/BFF requests answer within 250ms",
            objective=0.99,
            histogram="http_request_duration_seconds",
            threshold_s=0.25,
        ),
        SLO(
            name="reconcile-errors",
            description=(
                "99.9% of reconciles across every controller succeed"
            ),
            objective=0.999,
            total_metric="controller_runtime_reconcile_total",
            bad_metric="controller_runtime_reconcile_errors_total",
        ),
        SLO(
            name="warm-resume-p95",
            description=(
                "95% of suspended-session resumes restore state "
                "within 5s of re-admission"
            ),
            objective=0.95,
            histogram="session_resume_seconds",
            threshold_s=5.0,
        ),
        SLO(
            name="idle-waste",
            description=(
                "at least 50% of duty-sampled chip-seconds are active "
                "compute (fleet utilization — the chip-hour economics "
                "signal from the usage ledger; unsampled allocation "
                "is excluded so a wedged agent cannot burn budget)"
            ),
            objective=0.5,
            # one counter family, subset-label semantics: total sums
            # both phases, bad selects phase="idle" (good = active)
            total_metric="tpu_chip_seconds_total",
            bad_metric="tpu_chip_seconds_total",
            bad_labels={"phase": "idle"},
        ),
    ]


class SLOEngine:
    """Samples SLI counters from a live registry and evaluates
    multi-window burn rates.

    ``tick()`` appends one (t, good, total) sample per SLO;
    ``evaluate()`` computes burn rates per window from the ring and
    sets the ``slo_burn_rate{slo,window}`` gauges. ``start()`` runs
    both on a daemon-thread cadence for serving deployments; tests
    call them directly with an injected clock."""

    def __init__(
        self,
        registry: prometheus.Registry,
        specs: Optional[list[SLO]] = None,
        windows: Optional[Mapping[str, float]] = None,
        time_fn: Callable[[], float] = time.time,
    ):
        self.registry = registry
        self.specs = list(specs) if specs is not None else default_slos()
        self.windows = dict(windows or DEFAULT_WINDOWS)
        self.now = time_fn
        self.m_burn = registry.gauge(
            "slo_burn_rate",
            "Error-budget burn rate per SLO and window "
            "(1.0 = budget consumed exactly over the SLO period)",
            labelnames=("slo", "window"),
        )
        max_window = max(self.windows.values(), default=3600.0)
        self._max_window = max_window
        self._samples: dict[str, deque] = {
            s.name: deque() for s in self.specs
        }
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- SLI counts ----------------------------------------------------------

    def _counts(self, spec: SLO) -> tuple[float, float]:
        """Current cumulative (good, total) for a spec — 0s when the
        metric isn't registered (a split-process deployment may not
        run every subsystem)."""
        if spec.histogram:
            m = self.registry.metric(spec.histogram)
            if not isinstance(m, prometheus.Histogram):
                return 0.0, 0.0
            labels = dict(spec.labels)
            return (
                m.count_le(spec.threshold_s, labels),
                m.count_matching(labels),
            )
        total_m = self.registry.metric(spec.total_metric)
        bad_m = self.registry.metric(spec.bad_metric)
        total = (
            total_m.sum_matching(dict(spec.total_labels))
            if total_m is not None
            else 0.0
        )
        bad = (
            bad_m.sum_matching(dict(spec.bad_labels))
            if bad_m is not None
            else 0.0
        )
        return max(total - bad, 0.0), total

    # -- sampling + evaluation ----------------------------------------------

    def tick(self) -> None:
        """Record one sample per SLO and trim the ring to the largest
        window (plus one sample of slack for the left-edge diff)."""
        t = self.now()
        with self._lock:
            for spec in self.specs:
                good, total = self._counts(spec)
                ring = self._samples[spec.name]
                ring.append((t, good, total))
                while len(ring) > 2 and ring[1][0] <= t - self._max_window:
                    ring.popleft()

    @staticmethod
    def _at_window_start(ring, cutoff: float):
        """The newest sample at or before ``cutoff`` (else the oldest
        — a short history evaluates over what it has)."""
        base = ring[0]
        for s in ring:
            if s[0] <= cutoff:
                base = s
            else:
                break
        return base

    def evaluate(self) -> list[dict[str, Any]]:
        """Burn-rate rows for every (slo, window), gauges updated.
        Each row: slo, window, burn_rate, bad/good/total deltas in the
        window, objective, and the window actually covered."""
        t = self.now()
        rows: list[dict[str, Any]] = []
        with self._lock:
            for spec in self.specs:
                ring = self._samples[spec.name]
                if not ring:
                    continue
                cur_t, cur_good, cur_total = ring[-1]
                for wname, wsecs in sorted(
                    self.windows.items(), key=lambda kv: kv[1]
                ):
                    base_t, base_good, base_total = self._at_window_start(
                        ring, t - wsecs
                    )
                    d_total = max(cur_total - base_total, 0.0)
                    d_good = max(cur_good - base_good, 0.0)
                    d_bad = max(d_total - d_good, 0.0)
                    bad_ratio = (d_bad / d_total) if d_total > 0 else 0.0
                    burn = bad_ratio / spec.budget
                    threshold = burn_threshold(wsecs)
                    self.m_burn.set(
                        burn, {"slo": spec.name, "window": wname}
                    )
                    rows.append(
                        {
                            "slo": spec.name,
                            "description": spec.description,
                            "objective": spec.objective,
                            "window": wname,
                            "windowSeconds": wsecs,
                            "coveredSeconds": round(
                                max(cur_t - base_t, 0.0), 3
                            ),
                            "total": d_total,
                            "bad": d_bad,
                            "badRatio": round(bad_ratio, 6),
                            "burnRate": round(burn, 4),
                            # per-window alert: short windows page at
                            # the fast-burn rate, long windows ticket
                            # at the slow-burn rate (the SRE-workbook
                            # multi-window recipe fires when BOTH do)
                            "burnThreshold": threshold,
                            # epsilon absorbs float noise in the
                            # budget (1 − objective): a true 3.0 burn
                            # must not read 2.999…96 and stay silent
                            "alerting": burn >= threshold - 1e-9,
                        }
                    )
        return rows

    # -- serving cadence -----------------------------------------------------

    def start(self, interval: float = 15.0) -> None:
        if self._thread is not None:
            return
        # a stopped engine must be restartable: stop() leaves the
        # event set, and an un-cleared flag would make this thread
        # exit on its first wait with no error anywhere
        self._stop.clear()
        self.tick()  # seed the ring so the first evaluate has a base

        def loop():
            while not self._stop.wait(interval):
                try:
                    self.tick()
                    self.evaluate()
                except Exception:  # noqa: BLE001 — telemetry must not die
                    pass

        self._thread = threading.Thread(
            target=loop, name="slo-engine", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
