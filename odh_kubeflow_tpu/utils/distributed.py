"""jax.distributed bring-up from the platform's injected env contract.

The notebook controller provisions multi-host TPU slices as a
StatefulSet + headless service and injects per-worker identity env
(``controllers/notebook.py:480-499``): ``TPU_WORKER_HOSTNAMES`` (comma
list, stable DNS names), ``TPU_WORKER_ID`` (pod index), and
``JAX_COORDINATOR_ADDRESS`` (worker 0's DNS name + port). The in-image
``tpu-init`` script (images/*/tpu-init) consumes that contract before
the lab starts; this module is the *library* entry for user code and
tests — same contract, importable.

The reference platform has no analog: its multi-node training story is
user-space NCCL inside images (SURVEY.md §5 "distributed communication
backend"); here multi-host bring-up is a platform contract, and the
collectives ride XLA (ICI within a slice, Gloo/DCN across hosts).
"""

from __future__ import annotations

import os


def env_contract() -> dict:
    """The parsed contract, without side effects."""
    hostnames = [
        h for h in os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",") if h
    ]
    worker_id = int(os.environ.get("TPU_WORKER_ID", "0") or "0")
    port = os.environ.get("JAX_COORDINATOR_PORT", "8476")
    coordinator = os.environ.get("JAX_COORDINATOR_ADDRESS", "")
    if not coordinator and hostnames:
        coordinator = f"{hostnames[0]}:{port}"
    elif coordinator and ":" not in coordinator:
        coordinator = f"{coordinator}:{port}"
    return {
        "hostnames": hostnames,
        "num_processes": len(hostnames),
        "process_id": worker_id,
        "coordinator_address": coordinator,
    }


def initialize_from_env() -> bool:
    """Run ``jax.distributed.initialize`` when the platform injected a
    multi-host contract; no-op (False) on single-host spawns, where
    libtpu wires ICI by itself once the pod holds the whole slice.

    Idempotent per process only in the no-op case — call once, before
    any backend use, like ``tpu-init`` does.
    """
    c = env_contract()
    if c["num_processes"] <= 1:
        return False
    import jax

    jax.distributed.initialize(
        coordinator_address=c["coordinator_address"],
        num_processes=c["num_processes"],
        process_id=c["process_id"],
    )
    return True
