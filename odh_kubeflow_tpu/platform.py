"""All-in-one platform server (and the split-process building blocks).

``python -m odh_kubeflow_tpu.platform`` boots the whole control plane in
one process — the standalone analog of the reference's full deployment
(SURVEY.md §1 control flow):

- embedded APIServer with the kubeflow CRDs + admission webhooks
  registered in-process, served over REST (``machinery.httpapi``) so
  out-of-process components (``python -m odh_kubeflow_tpu.controllers.
  notebook`` et al., as the manifests deploy them) can attach via
  ``machinery.client.RemoteAPIServer``;
- controller manager running the notebook / profile / tensorboard
  reconcilers + culler;
- the web layer (central dashboard, JWA, VWA, TWA, kfam) on one port
  behind a prefix router — the same path layout the Istio
  VirtualServices give the reference (`/jupyter/...`, `/volumes/...`);
- optionally (``--sim``) the fake kubelet/scheduler so spawned
  notebooks actually "run" without a cluster — the local-dev story.

Config: flags + the reference's env contract
(USE_ISTIO/ENABLE_CULLING/CULL_IDLE_TIME/..., SURVEY.md §5 config).
"""

from __future__ import annotations

import argparse
import os
import threading
import time
from typing import Any, Optional

from odh_kubeflow_tpu.apis import install_default_cluster_roles, register_crds
from odh_kubeflow_tpu.controllers.culler import Culler, CullerConfig
from odh_kubeflow_tpu.controllers.notebook import (
    NotebookController,
    NotebookControllerConfig,
)
from odh_kubeflow_tpu.controllers.profile import ProfileController
from odh_kubeflow_tpu.controllers.runtime import Manager
from odh_kubeflow_tpu.controllers.tensorboard import TensorboardController
from odh_kubeflow_tpu.machinery import httpapi
from odh_kubeflow_tpu.machinery.cache import (
    CachedClient,
    InformerCache,
    register_platform_indexers,
)
from odh_kubeflow_tpu.machinery.kubelet import FakeCluster
from odh_kubeflow_tpu.machinery.partition import (
    build_partitions,
    partitions_from_env,
)
from odh_kubeflow_tpu.machinery.store import APIServer
from odh_kubeflow_tpu.machinery.usage import (
    UsageConfig,
    UsageMeter,
    register_usage,
)
from odh_kubeflow_tpu.scheduling import register_scheduling
from odh_kubeflow_tpu.scheduling.scheduler import SliceScheduler
from odh_kubeflow_tpu.sessions import register_sessions
from odh_kubeflow_tpu.sessions.manager import SessionConfig, SessionManager
from odh_kubeflow_tpu.utils import prometheus
from odh_kubeflow_tpu.utils.slo import SLOEngine
from odh_kubeflow_tpu.warmup import register_warmup
from odh_kubeflow_tpu.warmup.compilecache import (
    CompileCacheConfig,
    CompileCacheService,
)
from odh_kubeflow_tpu.warmup.pool import WarmPoolConfig, WarmPoolController
from odh_kubeflow_tpu.web.dashboard import DashboardApp
from odh_kubeflow_tpu.web.jwa import JupyterWebApp
from odh_kubeflow_tpu.web.kfam_app import KfamApp
from odh_kubeflow_tpu.web.twa import TensorboardsWebApp
from odh_kubeflow_tpu.web.vwa import VolumesWebApp
from odh_kubeflow_tpu.webhooks.notebook import NotebookWebhook
from odh_kubeflow_tpu.webhooks.poddefault import PodDefaultWebhook

Obj = dict[str, Any]


class PrefixRouter:
    """WSGI dispatcher: longest-prefix match; ``strip=True`` mounts an
    app that thinks it lives at ``/`` (JWA/VWA/TWA), ``strip=False``
    mounts one whose routes already carry the prefix (kfam)."""

    def __init__(self, fallback):
        self.fallback = fallback
        self._mounts: list[tuple[str, Any, bool]] = []

    def mount(self, prefix: str, app, strip: bool = True) -> "PrefixRouter":
        self._mounts.append((prefix.rstrip("/"), app, strip))
        self._mounts.sort(key=lambda m: -len(m[0]))
        return self

    def __call__(self, environ, start_response):
        path = environ.get("PATH_INFO", "/")
        for prefix, app, strip in self._mounts:
            if path == prefix or path.startswith(prefix + "/"):
                if strip:
                    environ = dict(environ)
                    environ["SCRIPT_NAME"] = (
                        environ.get("SCRIPT_NAME", "") + prefix
                    )
                    environ["PATH_INFO"] = path[len(prefix):] or "/"
                return app(environ, start_response)
        return self.fallback(environ, start_response)


class Platform:
    """Owns every in-process component; ``start()``/``stop()`` for
    serving, or use the components directly in tests."""

    def __init__(
        self,
        *,
        nb_config: Optional[NotebookControllerConfig] = None,
        sim: bool = False,
        spawner_config_path: Optional[str] = None,
    ):
        # WAL_DIR=<path> makes the embedded apiserver durable: every
        # mutation is WAL-logged + fsync'd before it is acked, a
        # snapshot is cut every SNAPSHOT_INTERVAL mutations, and boot
        # recovers the previous incarnation's objects, rv history, and
        # watch-resume window from disk (see docs/GUIDE.md
        # "Durability & failover"). Unset = the in-memory-only store.
        wal_dir = os.environ.get("WAL_DIR", "")
        # STORE_PARTITIONS=N shards the write path by namespace into N
        # independent WAL+group-commit stacks behind a PartitionRouter
        # (docs/GUIDE.md "Partitioned write path"); 1 = the classic
        # single-leader store, no router in the path.
        n_partitions = partitions_from_env()
        snap_every = int(os.environ.get("SNAPSHOT_INTERVAL", "1024"))
        # byte-based cadence rides alongside the count-based one
        # (SNAPSHOT_BYTES=0 disables); GROUP_COMMIT=false pins the
        # committer to one fsync per record (debug/bench baseline)
        snap_bytes = int(os.environ.get("SNAPSHOT_BYTES", "0"))
        group = os.environ.get("GROUP_COMMIT", "true").lower() == "true"
        durable_kwargs = dict(
            snapshot_interval=snap_every,
            snapshot_bytes=snap_bytes,
            group_commit=group,
        )
        if n_partitions > 1:
            # each partition recovers its own WAL under <WAL_DIR>/p<i>
            # (in-memory partitions when WAL_DIR is unset)
            self.api = build_partitions(
                n_partitions,
                wal_dir=wal_dir,
                **(durable_kwargs if wal_dir else {}),
            )
        elif wal_dir:
            from odh_kubeflow_tpu.machinery.wal import WriteAheadLog

            self.api = APIServer.recover(
                WriteAheadLog(wal_dir), **durable_kwargs
            )
        else:
            self.api = APIServer()
        register_crds(self.api)
        register_scheduling(self.api)
        register_sessions(self.api)
        register_usage(self.api)
        register_warmup(self.api)
        install_default_cluster_roles(self.api)
        PodDefaultWebhook(self.api).register()
        NotebookWebhook(self.api).register()

        # one platform-wide registry: controller-runtime metrics, the
        # notebook controller's counters, and anything components add
        # all scrape from the apiserver's /metrics
        self.metrics_registry = prometheus.Registry()
        # WAL/commit-pipeline instruments (fsyncs per batch, batch
        # size, ack latency) — no-op for the in-memory store
        self.api.attach_metrics(self.metrics_registry)
        # declarative SLOs evaluated as multi-window burn rates from
        # the live histograms (utils/slo.py): slo_burn_rate gauges on
        # /metrics, rows on the dashboard's /api/slo
        self.slo_engine = SLOEngine(self.metrics_registry)

        # the shared informer cache + indexed zero-copy client: every
        # controller and web backend reads through it; writes and
        # watches pass straight to the store. The webhooks and the
        # kubelet sim stay on the raw store (they run INSIDE the write
        # path and must see uncached truth).
        self.cache = InformerCache(self.api, registry=self.metrics_registry)
        register_platform_indexers(self.cache)
        self.cached_api = CachedClient(self.api, self.cache)

        self.nb_config = nb_config or NotebookControllerConfig.from_env()
        culler_cfg = CullerConfig(
            cull_idle_seconds=self.nb_config.cull_idle_seconds,
            idleness_check_seconds=self.nb_config.idleness_check_seconds,
            cluster_domain=self.nb_config.cluster_domain,
            # with sessions on, culls suspend-to-checkpoint instead of
            # stopping cold — the idle slice frees, the kernel survives
            suspend_on_cull=self.nb_config.enable_sessions,
        )
        self.manager = Manager(
            self.api, registry=self.metrics_registry, cache=self.cache
        )
        # the sim cluster is built before the controllers so its
        # checkpoint/restore container hooks can back the SessionManager
        self.cluster = FakeCluster(self.api) if sim else None
        # chip-hour metering (machinery/usage.py): ALWAYS constructed —
        # its counter families anchor the idle-waste SLO and the
        # dashboard showback even when the USAGE_METERING flag only
        # gates the background sampling thread. In sim mode the sampler
        # reads the cluster's deterministic duty-cycle waveforms; in a
        # real deployment it probes the in-pod activity agent.
        self.usage_config = UsageConfig.from_env()
        self.usage_meter = UsageMeter(
            self.cached_api,
            self.usage_config,
            registry=self.metrics_registry,
            sample_fn=(
                (lambda ns, nb: self.cluster.duty_cycle(ns, nb))
                if sim
                else None
            ),
        )
        self.usage_meter.recover()
        self.culler = Culler(
            self.cached_api, culler_cfg, meter=self.usage_meter
        )
        self.notebook_controller = NotebookController(
            self.cached_api,
            self.nb_config,
            registry=self.metrics_registry,
            culler=self.culler if self.nb_config.enable_culling else None,
            meter=self.usage_meter,
        )
        self.notebook_controller.register(self.manager)
        # suspend-to-checkpoint sessions (sessions/): snapshots kernels
        # on cull/preempt, restores on resume, and gives the scheduler
        # its checkpoint-then-preempt hooks
        self.session_manager = None
        if self.nb_config.enable_sessions:
            self.session_manager = SessionManager(
                self.cached_api,
                SessionConfig.from_env(),
                registry=self.metrics_registry,
                runtime=(
                    self.cluster.session_runtime
                    if self.cluster is not None
                    else None
                ),
                meter=self.usage_meter,
            )
            self.session_manager.register(self.manager)
        # gang admission for TPU slices (scheduling/): the notebook
        # controller only creates Workloads when queueing is on, and
        # without a scheduler they would pend forever
        self.scheduler = (
            SliceScheduler(
                self.cached_api,
                registry=self.metrics_registry,
                suspender=self.session_manager,
                meter=self.usage_meter,
            )
            if self.nb_config.enable_queueing
            else None
        )
        if self.scheduler is not None:
            self.scheduler.register(self.manager)
        # warm-start subsystem (warmup/): the compilation-cache service
        # is always constructed (its metrics anchor the warm-compile
        # gate, and trainer/engine precompile routes through it); the
        # warm-pool controller only runs when queueing is on — standbys
        # are admitted through the slice queue, and without a scheduler
        # they would pend forever (same gate as the scheduler itself).
        self.compile_cache = CompileCacheService(
            self.cached_api,
            CompileCacheConfig.from_env(),
            registry=self.metrics_registry,
        )
        self.warm_pool_config = WarmPoolConfig.from_env()
        self.warm_pool_controller = None
        if self.nb_config.enable_queueing and self.warm_pool_config.enabled:
            self.warm_pool_controller = WarmPoolController(
                self.cached_api,
                self.warm_pool_config,
                registry=self.metrics_registry,
                session_store=(
                    self.session_manager.store
                    if self.session_manager is not None
                    else None
                ),
                compile_cache=self.compile_cache,
            )
            self.warm_pool_controller.register(self.manager)
        self.profile_controller = ProfileController(self.cached_api)
        self.profile_controller.register(self.manager)
        self.tensorboard_controller = TensorboardController(self.cached_api)
        self.tensorboard_controller.register(self.manager)

        self.jwa = JupyterWebApp(
            self.cached_api,
            config_path=spawner_config_path,
            registry=self.metrics_registry,
            meter=self.usage_meter,
        )
        self.vwa = VolumesWebApp(self.cached_api, registry=self.metrics_registry)
        self.twa = TensorboardsWebApp(
            self.cached_api, registry=self.metrics_registry
        )
        self.kfam = KfamApp(self.cached_api, registry=self.metrics_registry)
        self.dashboard = DashboardApp(
            self.cached_api,
            kfam=self.kfam.service,
            registry=self.metrics_registry,
            slo_engine=self.slo_engine,
            meter=self.usage_meter,
        )

        self.web = PrefixRouter(self.dashboard.app)
        self.web.mount("/jupyter", self.jwa.app)
        self.web.mount("/volumes", self.vwa.app)
        self.web.mount("/tensorboards", self.twa.app)
        self.web.mount("/kfam", self.kfam.app, strip=False)

        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._api_httpd = None
        self._web_httpd = None

    # -- lifecycle ----------------------------------------------------------

    def start(
        self, api_port: int = 8001, web_port: int = 8080, host: str = "127.0.0.1"
    ) -> tuple[int, int]:
        """Starts controllers + servers on daemon threads; returns the
        bound (api_port, web_port)."""
        self.manager.start()
        self.slo_engine.start(
            interval=float(os.environ.get("SLO_TICK_SECONDS", "5"))
        )
        # duty-cycle sampling + ledger flush loop (no-op when
        # USAGE_METERING=false — the meter still exists for its hooks)
        self.usage_meter.start()
        _, api_port, self._api_httpd = httpapi.serve(
            self.api,
            host,
            api_port,
            metrics_registry=self.metrics_registry,
            usage_meter=self.usage_meter,
        )

        web_thread, web_port, self._web_httpd = _serve_wsgi(
            self.web, host, web_port
        )
        self._threads.append(web_thread)

        if self.cluster is not None:
            t = threading.Thread(target=self._sim_loop, daemon=True)
            t.start()
            self._threads.append(t)
        return api_port, web_port

    def _sim_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.cluster.step()
            except Exception:  # noqa: BLE001 — sim must keep ticking
                pass
            self._stop.wait(0.5)

    def stop(self) -> None:
        self._stop.set()
        self.usage_meter.stop()
        self.slo_engine.stop()
        self.manager.stop()
        for httpd in (self._api_httpd, self._web_httpd):
            if httpd is not None:
                httpd.shutdown()


def _serve_wsgi(app, host: str, port: int) -> tuple[threading.Thread, int, Any]:
    from odh_kubeflow_tpu.machinery import eventloop

    if eventloop.event_loop_enabled():
        srv = eventloop.serve_wsgi(app, host, port)
        return srv._thread, srv.server_address[1], srv

    from wsgiref.simple_server import make_server

    httpd = make_server(
        host,
        port,
        app,
        server_class=httpapi._ThreadingServer,
        handler_class=httpapi._QuietHandler,
    )
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    return t, httpd.server_address[1], httpd


def main(argv: Optional[list[str]] = None) -> None:
    parser = argparse.ArgumentParser(description="odh-kubeflow-tpu platform")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--api-port", type=int, default=8001)
    parser.add_argument("--web-port", type=int, default=8080)
    parser.add_argument(
        "--sim",
        action="store_true",
        help="run the fake kubelet/scheduler (local dev: notebooks 'run')",
    )
    parser.add_argument(
        "--sim-tpu-nodes",
        type=int,
        default=int(os.environ.get("SIM_TPU_NODES", "1")),
        help="with --sim: v5e TPU nodes to register",
    )
    parser.add_argument("--spawner-config", default=os.environ.get("UI_CONFIG"))
    args = parser.parse_args(argv)

    platform = Platform(sim=args.sim, spawner_config_path=args.spawner_config)
    if platform.cluster is not None:
        platform.cluster.add_node("cpu-0", cpu="32", memory="128Gi")
        for i in range(args.sim_tpu_nodes):
            platform.cluster.add_tpu_node_pool(
                f"tpu-v5e-{i}",
                accelerator_type="tpu-v5-lite-podslice",
                topology="2x2",
            )
    api_port, web_port = platform.start(args.api_port, args.web_port, args.host)
    print(
        f"platform up: api http://{args.host}:{api_port} "
        f"web http://{args.host}:{web_port}"
        + (" (sim cluster)" if platform.cluster else ""),
        flush=True,
    )
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        platform.stop()


if __name__ == "__main__":
    main()
