"""SessionManager: the suspend/resume controller.

Runs on the runtime Manager next to the notebook controller and drives
the SessionCheckpoint state machine:

- **suspend** (``SUSPENDED_AT_ANNOTATION`` stamped by the culler, the
  slice scheduler's checkpoint-then-preempt, or JWA): while the
  notebook controller holds the scale-down (``sessions.suspend_pending``),
  snapshot the kernel state out of the live pod through the runtime
  hook, write it durably through the ``CheckpointManager``-backed store
  keyed by notebook UID, then mark the SessionCheckpoint ``Suspended``
  — at which point the StatefulSet scales to zero, the gang Workload is
  deleted, and the slice reservation is freed;
- **resume** (stop/suspend annotations cleared by JWA connect or the
  resume API): the Workload re-enqueues through normal reconcile; once
  the fresh pod is Running the manager restores the stored state into
  it (digest-checked — bit-identical or it warns), records the
  warm-resume latency histogram, and only then clears the notebook's
  ``Resuming`` phase so JWA reports ready;
- **suspender hooks** for the SliceScheduler: ``is_suspendable`` /
  ``suspend_in_flight`` / ``request_suspend`` implement
  checkpoint-then-preempt — idle sessions yield their slice via a
  durable snapshot instead of a hard kill.

Snapshot/restore IO is blocking (checkpoint writes, HTTP hooks) and
runs only from reconcile bodies — never under store/cache locks
(graftlint blocking-under-lock covers this file).
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
import time
import urllib.error
import urllib.request
from typing import Any, Callable, Optional

from odh_kubeflow_tpu.apis import (
    RESUME_REQUESTED_ANNOTATION,
    STOP_ANNOTATION,
    SUSPEND_REASON_ANNOTATION,
    SUSPENDED_AT_ANNOTATION,
    LAST_ACTIVITY_ANNOTATION,
)
from odh_kubeflow_tpu.controllers.runtime import Manager, Request, Result
from odh_kubeflow_tpu.machinery import objects as obj_util
from odh_kubeflow_tpu.machinery.events import EventRecorder
from odh_kubeflow_tpu.machinery.objects import mutable
from odh_kubeflow_tpu.machinery.store import (
    AlreadyExists,
    Conflict,
    NotFound,
)
from odh_kubeflow_tpu.sessions import (
    PHASE_RESTORED,
    PHASE_RESUMING,
    PHASE_SUSPENDED,
    PHASE_SUSPENDING,
    checkpoint_durable,
    checkpoint_of,
    new_checkpoint,
)
from odh_kubeflow_tpu.sessions.checkpoint import (
    ReplicatedCheckpointStore,
    SessionCheckpointStore,
    parse_zone_spec,
)
from odh_kubeflow_tpu.utils import prometheus, tracing

Obj = dict[str, Any]

COMPONENT = "session-manager"

# suspend spans ms (sim snapshot) to minutes (a big kernel to GCS);
# warm resumes must land in seconds — the buckets resolve the SLO
_LATENCY_BUCKETS = (
    0.01, 0.05, 0.1, 0.5, 1.0, 2.0, 5.0, 10.0, 30.0, 60.0, 300.0,
)
@dataclasses.dataclass
class SessionConfig:
    # where checkpoints live (PVC path or gs:// prefix); empty → a
    # process-local temp dir (sim / tests)
    checkpoint_dir: str = ""
    backend: str = "auto"  # orbax | json | auto
    # zone-replicated checkpoints: comma-separated ``zone=path`` (one
    # independent volume per failure domain) or bare zone names
    # (subdirs of checkpoint_dir — sim/dev). ≥2 zones turns every
    # suspend into a write-all across them; empty keeps the single
    # store exactly as before.
    zones: str = ""
    # how often a degraded checkpoint (fewer zones than configured
    # hold its bytes) retries re-replication
    zone_heal_retry_seconds: float = 30.0
    # how long a session must be idle before the scheduler may reclaim
    # its slice via suspend (checkpoint-then-preempt at equal priority)
    reclaim_idle_seconds: float = 300.0
    # where the default HTTP snapshot/restore hooks reach the in-pod
    # session agent (must track the cluster's real domain or every
    # suspend silently degrades to a cold stop)
    cluster_domain: str = "cluster.local"
    agent_port: int = 8890
    # how long a transiently-unreachable snapshot hook is retried
    # against a still-Running pod before the suspend degrades to an
    # empty checkpoint (same window the notebook controller holds the
    # scale-down for)
    suspend_grace_seconds: float = 600.0
    # how long a failed restore hook is retried against a Running pod
    # (agent not listening yet) before the resume degrades to cold
    restore_retry_seconds: float = 120.0

    @staticmethod
    def from_env() -> "SessionConfig":
        env = os.environ
        return SessionConfig(
            checkpoint_dir=env.get("SESSION_CHECKPOINT_DIR", ""),
            backend=env.get("SESSION_CHECKPOINT_BACKEND", "auto"),
            zones=env.get("SESSION_CHECKPOINT_ZONES", ""),
            zone_heal_retry_seconds=float(
                env.get("SESSION_ZONE_HEAL_RETRY_SECONDS", "30")
            ),
            reclaim_idle_seconds=float(
                env.get("SESSION_RECLAIM_IDLE_SECONDS", "300")
            ),
            cluster_domain=env.get("CLUSTER_DOMAIN", "cluster.local"),
            agent_port=int(env.get("SESSION_AGENT_PORT", "8890")),
            suspend_grace_seconds=float(
                env.get("SESSION_SUSPEND_GRACE_SECONDS", "600")
            ),
            restore_retry_seconds=float(
                env.get("SESSION_RESTORE_RETRY_SECONDS", "120")
            ),
        )


class HttpSessionRuntime:
    """Real-cluster checkpoint/restore hooks: the in-image session
    agent (same sidecar family as the tpu-activity agent) serves the
    kernel snapshot on the agent port. The kubelet sim provides the
    in-process equivalent (``machinery.kubelet.SimSessionRuntime``)."""

    def __init__(
        self,
        cluster_domain: str = "cluster.local",
        port: int = 8890,
        timeout: float = 10.0,
    ):
        self.cluster_domain = cluster_domain
        self.port = port
        self.timeout = timeout

    def _base(self, notebook: Obj) -> str:
        from odh_kubeflow_tpu.apis import notebook_agent_url

        return (
            notebook_agent_url(notebook, self.cluster_domain, self.port)
            + "/api/session"
        )

    def snapshot(self, notebook: Obj, pod: Obj) -> Optional[Obj]:
        try:
            with urllib.request.urlopen(
                self._base(notebook) + "/state", timeout=self.timeout
            ) as r:
                return json.loads(r.read().decode())
        except (urllib.error.URLError, OSError, ValueError):
            return None

    def restore(self, notebook: Obj, pod: Obj, state: Obj) -> bool:
        req = urllib.request.Request(
            self._base(notebook) + "/restore",
            data=json.dumps(state).encode(),
            method="POST",
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout):
                return True
        except (urllib.error.URLError, OSError):
            return False


class SessionManager:
    def __init__(
        self,
        api: Any,
        config: Optional[SessionConfig] = None,
        registry: Optional[prometheus.Registry] = None,
        runtime: Optional[Any] = None,
        store: Optional[SessionCheckpointStore] = None,
        time_fn: Callable[[], float] = time.time,
        meter: Optional[Any] = None,
    ):
        self.api = api
        self.config = config or SessionConfig()
        self.now = time_fn
        # chip-hour ledger (machinery.usage.UsageMeter duck): suspend/
        # restore transitions annotate the duty-cycle timeline so the
        # /debug/usage view reads alongside the session state machine
        self.meter = meter
        self.runtime = runtime or HttpSessionRuntime(
            cluster_domain=self.config.cluster_domain,
            port=self.config.agent_port,
        )
        root = self.config.checkpoint_dir or tempfile.mkdtemp(
            prefix="session-ckpt-"
        )
        if store is not None:
            self.store = store
        else:
            zones = parse_zone_spec(self.config.zones, root)
            self.store = (
                ReplicatedCheckpointStore(zones, backend=self.config.backend)
                if zones
                else SessionCheckpointStore(root, backend=self.config.backend)
            )
        self.recorder = EventRecorder(api, COMPONENT)
        reg = registry or prometheus.default_registry
        self.m_suspend = reg.histogram(
            "session_suspend_seconds",
            "Suspend request to durable checkpoint",
            buckets=_LATENCY_BUCKETS,
        )
        self.m_resume = reg.histogram(
            "session_resume_seconds",
            "Warm resume: reopen request to state restored in the fresh pod",
            buckets=_LATENCY_BUCKETS,
        )
        self.m_suspends = reg.counter(
            "session_suspends_total",
            "Completed suspend-to-checkpoint operations by trigger",
            labelnames=("reason",),
        )
        self.m_resumes = reg.counter(
            "session_resumes_total",
            "Completed session resumes by outcome",
            labelnames=("result",),
        )
        self.m_bytes = reg.gauge(
            "session_checkpoint_size_bytes",
            "Serialized size of the most recent kernel snapshot",
        )
        self.m_heals = reg.counter(
            "session_checkpoint_heals_total",
            "Degraded checkpoints re-replicated to their full zone set",
        )
        self.m_degraded = reg.gauge(
            "session_checkpoints_degraded",
            "Checkpoints (any phase) currently held by fewer zones "
            "than configured",
        )
        reg.register_collector(self._collect_suspended)

    def _collect_suspended(self):
        counts: dict[str, int] = {}
        degraded = 0
        try:
            rows = self.api.list("SessionCheckpoint")  # uncached-ok: metrics scrape over a small kind
        except NotFound:
            rows = []
        for ck in rows:
            if obj_util.get_path(ck, "status", "phase") == PHASE_SUSPENDED:
                ns = obj_util.namespace_of(ck)
                counts[ns] = counts.get(ns, 0) + 1
            if obj_util.get_path(ck, "status", "replicationDegraded"):
                degraded += 1
        self.m_degraded.set(degraded)
        yield (
            "# HELP suspended_sessions Sessions suspended to checkpoint, "
            "holding no chips, per quota pool"
        )
        yield "# TYPE suspended_sessions gauge"
        for ns in sorted(counts):
            yield f'suspended_sessions{{queue="{ns}"}} {counts[ns]}'

    # -- wiring -------------------------------------------------------------

    def register(self, mgr: Manager) -> None:
        ctrl = mgr.new_controller("session-manager", "Notebook", self.reconcile)
        ctrl.watches("SessionCheckpoint", self._map_checkpoint)
        ctrl.watches("Pod", self._map_pod, predicate=self._pod_predicate)

    @staticmethod
    def _map_checkpoint(_etype: str, ckpt: Obj) -> list[Request]:
        name = obj_util.get_path(
            ckpt, "spec", "notebook", default=obj_util.name_of(ckpt)
        )
        return [Request(obj_util.namespace_of(ckpt), name)]

    @staticmethod
    def _pod_predicate(_etype: str, pod: Obj) -> bool:
        return "notebook-name" in obj_util.labels_of(pod)

    @staticmethod
    def _map_pod(_etype: str, pod: Obj) -> list[Request]:
        name = obj_util.labels_of(pod).get("notebook-name", "")
        return [Request(obj_util.namespace_of(pod), name)] if name else []

    # -- reconcile ----------------------------------------------------------

    def reconcile(self, req: Request) -> Result:
        try:
            notebook = mutable(
                self.api.get("Notebook", req.name, req.namespace)
            )
        except NotFound:
            return self._gc(req)

        # one read serves the whole reconcile (suspend path, resume
        # path, upserts); a checkpoint left by a DELETED same-named
        # notebook (delete coalesced with the recreate) is dropped here
        # or it would pin phantom chips in the committed ledger forever
        ckpt = self._gc_stale_generation(
            notebook, checkpoint_of(self.api, notebook)
        )

        ann = obj_util.annotations_of(notebook)
        suspended_at = ann.get(SUSPENDED_AT_ANNOTATION)
        if suspended_at:
            return self._reconcile_suspend(notebook, suspended_at, ckpt)
        if ckpt is not None:
            phase = obj_util.get_path(ckpt, "status", "phase", default="")
            if phase in (PHASE_SUSPENDED, PHASE_RESUMING):
                return self._reconcile_resume(notebook, ckpt)
        # terminal sweep: no suspend in progress and no resume owed —
        # a session phase left behind by a Conflict-swallowed clear
        # (the notebook controller mirrors status concurrently) would
        # otherwise pin JWA at "resuming" forever
        if obj_util.get_path(notebook, "status", "phase", default="") in (
            PHASE_SUSPENDING,
            PHASE_SUSPENDED,
            PHASE_RESUMING,
        ):
            self._set_phase(notebook, "")
        if ckpt is not None:
            # a checkpoint degraded at suspend time keeps healing even
            # after the session resumed (the retained bytes are still
            # single-zone until every configured zone holds them)
            return self._reconcile_replication(notebook, ckpt)
        return Result()

    # -- suspend ------------------------------------------------------------

    def _reconcile_suspend(
        self, notebook: Obj, suspended_at: str, ckpt: Optional[Obj]
    ) -> Result:
        if checkpoint_durable(ckpt, suspended_at):
            # snapshot durable — the notebook controller scales down /
            # deletes the Workload; keep the phase honest and, when
            # the checkpoint landed in fewer zones than configured,
            # keep re-replicating until every zone holds the bytes
            self._set_phase(notebook, PHASE_SUSPENDED)
            return self._reconcile_replication(notebook, ckpt)

        self._set_phase(notebook, PHASE_SUSPENDING)
        uid = obj_util.meta(notebook).get("uid", "")
        prev_status = (ckpt.get("status") or {}) if ckpt is not None else {}
        if (
            prev_status.get("stateCaptured")
            and prev_status.get("phase") in (PHASE_SUSPENDED, PHASE_RESUMING)
            and self.store.exists(uid)
        ):
            # re-suspended before the last restore completed: the
            # durable checkpoint from the previous epoch is STILL the
            # kernel's truth — a fresh pod that came up meanwhile holds
            # an empty kernel (state was never restored into it), so
            # snapshotting it would destroy real state. Carry forward.
            receipt = {
                "step": prev_status.get("checkpointStep", 0),
                "digest": prev_status.get("digest", ""),
                "sizeBytes": prev_status.get("sizeBytes", 0),
            }
            if "zones" in prev_status:
                receipt["zones"] = prev_status["zones"]
                receipt["degraded"] = bool(
                    prev_status.get("replicationDegraded")
                )
            captured = True
        else:
            pod = self._running_pod0(notebook)
            state: Optional[Obj] = None
            if pod is not None:
                # blocking snapshot IO (HTTP hook / checkpoint write):
                # runs here in the reconcile body, never under
                # store/cache locks
                state = self.runtime.snapshot(notebook, pod)
            captured = state is not None
            if not captured:
                if (
                    pod is not None
                    and self.now() - obj_util.parse_rfc3339(suspended_at)
                    < self.config.suspend_grace_seconds
                ):
                    # the kernel is alive but its snapshot hook didn't
                    # answer (agent restarting, transient network):
                    # retry inside the grace window — one flaky probe
                    # must not discard a living kernel by finalizing an
                    # empty checkpoint and releasing the slice
                    self.recorder.warning(
                        notebook,
                        "SessionSnapshotRetry",
                        "kernel snapshot hook unreachable; retrying "
                        "before releasing the slice",
                    )
                    return Result(requeue_after=2.0)
                self.recorder.warning(
                    notebook,
                    "SessionStateUnavailable",
                    "no live kernel state to snapshot (pod gone or "
                    "snapshot hook unreachable); suspending without state",
                )
            receipt = self.store.save(uid, state if captured else {})
        status_patch = {
            "phase": PHASE_SUSPENDED,
            "suspendedAt": suspended_at,
            "checkpointStep": receipt["step"],
            "digest": receipt["digest"],
            "sizeBytes": receipt["sizeBytes"],
            "stateCaptured": captured,
            "resumedAt": None,
        }
        if "zones" in receipt:
            # zone-replicated store: the CR status is the operator's
            # replication surface — which zones hold the bytes, and
            # whether the write degraded to fewer than configured
            status_patch["zones"] = list(receipt["zones"])
            status_patch["replicationDegraded"] = bool(
                receipt.get("degraded")
            )
        self._upsert_checkpoint(notebook, status_patch, ckpt=ckpt)
        reason = (
            obj_util.annotations_of(notebook).get(
                SUSPEND_REASON_ANNOTATION
            )
            or "user"
        )
        wait = self.now() - obj_util.parse_rfc3339(suspended_at)
        self.m_suspend.observe(max(wait, 0.0))
        self.m_suspends.inc({"reason": reason})
        self.m_bytes.set(receipt["sizeBytes"])
        self.recorder.normal(
            notebook,
            "SuspendCheckpointed",
            f"session state checkpointed (step {receipt['step']}, "
            f"{receipt['sizeBytes']} bytes); releasing the slice "
            "reservation",
        )
        self._set_phase(notebook, PHASE_SUSPENDED)
        if self.meter is not None:
            self.meter.mark_event(
                obj_util.namespace_of(notebook),
                obj_util.name_of(notebook),
                "suspended",
            )
        if receipt.get("degraded"):
            self.recorder.warning(
                notebook,
                "CheckpointReplicationDegraded",
                f"checkpoint durable in zone(s) "
                f"{', '.join(receipt.get('zones', []))} only; "
                "re-replicating when the missing zone(s) heal",
            )
            return Result(
                requeue_after=self.config.zone_heal_retry_seconds
            )
        return Result()

    def _reconcile_replication(self, notebook: Obj, ckpt: Obj) -> Result:
        """Re-replicate a degraded checkpoint (its bytes live in fewer
        zones than configured — a zone was down at suspend time) once
        the missing zones heal. Level-triggered: retried every
        ``zone_heal_retry_seconds`` while degraded, a no-op for
        fully-replicated checkpoints and non-replicated stores."""
        status = ckpt.get("status") or {}
        if not status.get("replicationDegraded"):
            return Result()
        heal = getattr(self.store, "heal", None)
        digest = status.get("digest", "")
        uid = obj_util.get_path(ckpt, "spec", "notebookUID", default="")
        if heal is None or not digest or not uid:
            return Result()
        # blocking checkpoint IO — reconcile body, no locks held
        replication = heal(uid, digest)
        if replication["degraded"]:
            return Result(
                requeue_after=self.config.zone_heal_retry_seconds
            )
        self._upsert_checkpoint(
            notebook,
            {
                "zones": list(replication["zones"]),
                "replicationDegraded": False,
            },
            ckpt=ckpt,
        )
        self.m_heals.inc()
        self.recorder.normal(
            notebook,
            "CheckpointReplicated",
            "checkpoint re-replicated; every configured zone holds "
            "bit-identical bytes "
            f"({', '.join(replication['zones'])})",
        )
        return Result()

    # -- resume -------------------------------------------------------------

    def _reconcile_resume(self, notebook: Obj, ckpt: Obj) -> Result:
        if STOP_ANNOTATION in obj_util.annotations_of(notebook):
            # still stopped (suspend annotation cleared by hand): the
            # checkpoint keeps waiting — resume starts when the stop
            # lifts and the Workload re-enqueues
            return Result()
        phase = obj_util.get_path(ckpt, "status", "phase", default="")
        if phase == PHASE_SUSPENDED:
            self._upsert_checkpoint(
                notebook,
                {
                    "phase": PHASE_RESUMING,
                    "resumeStartedAt": obj_util.now_rfc3339(),
                },
                ckpt=ckpt,
            )
            self._set_phase(notebook, PHASE_RESUMING)
        pod = self._running_pod0(notebook)
        if pod is None:
            # the Workload is still queueing/binding; stay Resuming
            self._set_phase(notebook, PHASE_RESUMING)
            return Result()

        uid = obj_util.meta(notebook).get("uid", "")
        # the restore milestone of the spawn/resume trace: load +
        # digest check + the restore hook, recorded as a child of the
        # reconcile span (which carries the notebook's trace). A
        # not-yet-serving agent retry is discarded — only the landed
        # restore is the trace's restore; a cold outcome is an error
        # span, so the trace is tail-kept for the operator.
        with tracing.span(
            "session.restore", notebook=obj_util.name_of(notebook)
        ):
            saved_digest = obj_util.get_path(
                ckpt, "status", "digest", default=""
            )
            # the receipt digest steers a replicated store to a zone
            # whose bytes verify (read-from-any-SURVIVING-zone)
            loaded = self.store.load(uid, expect_digest=saved_digest or None)
            result = "restored"
            if loaded is None:
                result = "empty"
                self.recorder.warning(
                    notebook,
                    "SessionStateMissing",
                    "no stored session state for this notebook; resuming cold",
                )
            else:
                state, read_digest = loaded
                if saved_digest and read_digest != saved_digest:
                    result = "corrupt"
                    self.recorder.warning(
                        notebook,
                        "SessionChecksumMismatch",
                        f"restored bytes digest {read_digest[:12]} != "
                        f"checkpointed {saved_digest[:12]}; resuming cold",
                    )
                elif not self.runtime.restore(notebook, pod, state):
                    started = obj_util.get_path(
                        ckpt, "status", "resumeStartedAt", default=""
                    ) or obj_util.annotations_of(notebook).get(
                        RESUME_REQUESTED_ANNOTATION, ""
                    )
                    if (
                        started
                        and self.now() - obj_util.parse_rfc3339(started)
                        < self.config.restore_retry_seconds
                    ):
                        # pod is Running but the agent inside isn't
                        # serving yet (normal startup ordering): retry —
                        # finalizing now would strand an intact,
                        # digest-valid checkpoint and turn every real
                        # resume cold
                        self.recorder.warning(
                            notebook,
                            "SessionRestoreRetry",
                            "restore hook not answering yet; retrying with "
                            "the checkpoint intact",
                        )
                        tracing.discard()
                        return Result(requeue_after=2.0)
                    result = "error"
                    self.recorder.warning(
                        notebook,
                        "SessionRestoreFailed",
                        "restore hook rejected the session state; resuming cold",
                    )
            if result != "restored":
                tracing.set_status("error", f"cold resume: {result}")
            requested = obj_util.annotations_of(notebook).get(
                RESUME_REQUESTED_ANNOTATION, ""
            )
            if requested:
                # observed inside the span: the warm-resume histogram's
                # exemplar carries this trace
                self.m_resume.observe(
                    max(self.now() - obj_util.parse_rfc3339(requested), 0.0)
                )
            self.m_resumes.inc({"result": result})
        self._upsert_checkpoint(
            notebook,
            {"phase": PHASE_RESTORED, "resumedAt": obj_util.now_rfc3339()},
            ckpt=ckpt,
        )
        self.recorder.normal(
            notebook,
            "Resumed",
            "warm resume complete: session state restored into the "
            "fresh pod"
            if result == "restored"
            else f"session resumed without state ({result})",
        )
        self._set_phase(notebook, "")
        if self.meter is not None:
            self.meter.mark_event(
                obj_util.namespace_of(notebook),
                obj_util.name_of(notebook),
                f"resumed:{result}",
            )
        return Result()

    # -- scheduler suspender hooks (checkpoint-then-preempt) ----------------

    def _notebook_for(self, wl: Obj) -> Optional[Obj]:
        try:
            return self.api.get(
                "Notebook", obj_util.name_of(wl), obj_util.namespace_of(wl)
            )
        except NotFound:
            return None

    def suspend_in_flight(self, wl: Obj) -> bool:
        """A suspend was requested for this workload's notebook and its
        slice release is coming — the scheduler counts it as pending
        capacity instead of requesting more suspends."""
        nb = self._notebook_for(wl)
        return nb is not None and SUSPENDED_AT_ANNOTATION in (
            obj_util.annotations_of(nb)
        )

    def is_suspendable(self, wl: Obj, require_idle: bool = False) -> bool:
        """Whether the workload's session can yield its slice via a
        checkpoint. ``require_idle`` (equal-priority oversubscription
        reclaim) additionally demands the kernel has been quiet for
        ``reclaim_idle_seconds`` — preempting a running computation to
        densify is worse than queueing."""
        nb = self._notebook_for(wl)
        if nb is None:
            return False
        ann = obj_util.annotations_of(nb)
        if SUSPENDED_AT_ANNOTATION in ann or STOP_ANNOTATION in ann:
            return False
        if require_idle:
            idle_since = ann.get(LAST_ACTIVITY_ANNOTATION) or obj_util.meta(
                nb
            ).get("creationTimestamp", "")
            if not idle_since:
                return False
            if (
                self.now() - obj_util.parse_rfc3339(idle_since)
                < self.config.reclaim_idle_seconds
            ):
                return False
        return True

    def request_suspend(
        self, wl: Obj, message: str, reason: str = "preempt"
    ) -> bool:
        """Stamp the suspend contract onto the workload's notebook.
        Returns True only when this call initiated the suspend (the
        caller counts the preemption metric off it). ``reason`` lands
        in ``SUSPEND_REASON_ANNOTATION`` — the scheduler's zone drain
        passes ``zone-drain`` so its migrate step can tell its own
        suspends from user/preempt ones."""
        nb = self._notebook_for(wl)
        if nb is None:
            return False
        if SUSPENDED_AT_ANNOTATION in obj_util.annotations_of(nb):
            return False
        now = obj_util.now_rfc3339()
        try:
            self.api.patch(
                "Notebook",
                obj_util.name_of(nb),
                {
                    "metadata": {
                        "annotations": {
                            STOP_ANNOTATION: now,
                            SUSPENDED_AT_ANNOTATION: now,
                            SUSPEND_REASON_ANNOTATION: reason,
                        }
                    }
                },
                obj_util.namespace_of(nb),
            )
        except (Conflict, NotFound):
            return False
        self.recorder.normal(nb, "Suspending", message)
        return True

    # -- plumbing -----------------------------------------------------------

    def _running_pod0(self, notebook: Obj) -> Optional[Obj]:
        try:
            pod = self.api.get(
                "Pod",
                f"{obj_util.name_of(notebook)}-0",
                obj_util.namespace_of(notebook),
            )
        except NotFound:
            return None
        if obj_util.get_path(pod, "status", "phase") != "Running":
            return None
        return pod

    def _set_phase(self, notebook: Obj, phase: str) -> None:
        """The notebook's session phase lives in ``status.phase``
        (preserved by the notebook controller's status mirror); JWA
        reads it to gate "ready" behind the state restore. The write
        goes against a FRESH read — the in-hand object's rv is usually
        stale by now (this reconcile did store IO in between, and the
        notebook controller mirrors status concurrently), and a
        swallowed Conflict on the terminal clear would pin the phase."""
        try:
            fresh = mutable(
                self.api.get(
                    "Notebook",
                    obj_util.name_of(notebook),
                    obj_util.namespace_of(notebook),
                )
            )
        except NotFound:
            return
        current = obj_util.get_path(fresh, "status", "phase", default="")
        if current == phase:
            return
        fresh.setdefault("status", {})["phase"] = phase
        try:
            updated = self.api.update_status(fresh)
            notebook["metadata"]["resourceVersion"] = updated["metadata"][
                "resourceVersion"
            ]
            notebook.setdefault("status", {})["phase"] = phase
        except (Conflict, NotFound):
            pass  # the reconcile retriggers and re-drives the phase

    def _upsert_checkpoint(
        self, notebook: Obj, status: Obj, ckpt: Optional[Obj] = None
    ) -> None:
        from odh_kubeflow_tpu.controllers.notebook import tpu_request_of

        if ckpt is None:
            ckpt = checkpoint_of(self.api, notebook)
        if ckpt is None:
            try:
                tpu = tpu_request_of(notebook)
            except ValueError:
                tpu = None
            ckpt = new_checkpoint(
                notebook,
                chips=tpu.chips if tpu else 0,
                accel=tpu.accelerator_type if tpu else "",
                topo=tpu.topology if tpu else "",
            )
            try:
                ckpt = self.api.create(ckpt)
            except AlreadyExists:
                ckpt = checkpoint_of(self.api, notebook)
                if ckpt is None:
                    return
        ckpt = mutable(ckpt)
        merged = dict(ckpt.get("status") or {})
        merged.update(status)
        ckpt["status"] = merged
        try:
            self.api.update_status(ckpt)
        except (Conflict, NotFound):
            pass  # next reconcile rewrites from fresh state

    def verify_receipts(self) -> list[dict[str, Any]]:
        """Post-recovery audit: cross-check every SessionCheckpoint
        CR's digest receipt against the bytes actually in the durable
        store. The CRs live in the (now WAL-backed) control plane and
        the bytes on the checkpoint volume — a crash must never split
        them. Returns one row per checkpoint:
        ``{key, uid, ok, detail}``; the durability drills assert
        ``all(r["ok"])`` after killing and recovering the apiserver."""
        rows: list[dict[str, Any]] = []
        for ckpt in self.api.list("SessionCheckpoint"):  # uncached-ok: cold audit
            key = (
                f"{obj_util.namespace_of(ckpt)}/{obj_util.name_of(ckpt)}"
            )
            uid = obj_util.get_path(ckpt, "spec", "notebookUID", default="")
            saved = obj_util.get_path(ckpt, "status", "digest", default="")
            if not uid or not saved:
                continue  # never checkpointed (or receipt not yet cut)
            loaded = self.store.load(uid)
            if loaded is None:
                rows.append(
                    {
                        "key": key,
                        "uid": uid,
                        "ok": False,
                        "detail": "receipt present but bytes missing",
                    }
                )
                continue
            _, digest = loaded
            ok = digest == saved
            rows.append(
                {
                    "key": key,
                    "uid": uid,
                    "ok": ok,
                    "detail": "bit-identical"
                    if ok
                    else f"digest {digest[:12]} != receipt {saved[:12]}",
                }
            )
        return rows

    def _gc_stale_generation(
        self, notebook: Obj, ckpt: Optional[Obj]
    ) -> Optional[Obj]:
        """Drop a checkpoint whose recorded UID belongs to a previous
        notebook of the same name, along with its stored bytes.
        Returns the checkpoint if it belongs to THIS notebook, else
        None (dropped or absent)."""
        if ckpt is None:
            return None
        old_uid = obj_util.get_path(ckpt, "spec", "notebookUID", default="")
        if old_uid == obj_util.meta(notebook).get("uid", ""):
            return ckpt
        if old_uid:
            self.store.delete(old_uid)
        try:
            self.api.delete(
                "SessionCheckpoint",
                obj_util.name_of(ckpt),
                obj_util.namespace_of(ckpt),
            )
        except NotFound:
            pass
        return None

    def _gc(self, req: Request) -> Result:
        """Notebook gone: drop its checkpoint object AND the stored
        bytes (the object is deliberately not owner-referenced so the
        UID survives long enough to clean the store). A zone that is
        dark at delete time may still hold the bytes — the CR is the
        ONLY uid→bytes record, so it stays (and this reconcile
        requeues) until the delete lands in every zone; dropping it
        early would orphan a checkpoint on the healed volume forever."""
        try:
            ckpt = self.api.get("SessionCheckpoint", req.name, req.namespace)
        except NotFound:
            return Result()
        uid = obj_util.get_path(ckpt, "spec", "notebookUID", default="")
        if uid and self.store.delete(uid) is False:
            return Result(
                requeue_after=self.config.zone_heal_retry_seconds
            )
        try:
            self.api.delete("SessionCheckpoint", req.name, req.namespace)
        except NotFound:
            pass
        return Result()


def main() -> None:
    """Split-process entrypoint: attach to $KUBE_API_URL and run the
    session manager forever (manifests deploy it inside the
    notebook-controller process by default; this standalone mode exists
    for dedicated scaling)."""
    from odh_kubeflow_tpu.machinery.runner import run_controller
    from odh_kubeflow_tpu.sessions import register_sessions

    def register(api, mgr):
        register_sessions(api)
        SessionManager(
            api, SessionConfig.from_env(), registry=mgr.metrics_registry
        ).register(mgr)

    run_controller("session-manager", register)


if __name__ == "__main__":
    main()
