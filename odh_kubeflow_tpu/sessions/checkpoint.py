"""The durable byte store behind SessionCheckpoints, keyed by notebook
UID.

Session state is an opaque JSON-able tree (kernel variables, execution
counters — whatever the in-pod snapshot hook hands over). It is
canonically serialized once, digested (sha256 — the bit-identity
receipt the resume path and the property tests verify), and written
through ``train.checkpoint.CheckpointManager`` — the same orbax-backed
manager training state uses, so session snapshots inherit its
async-capable IO, ``max_to_keep`` GC, and fsspec path support (PVC
paths and ``gs://`` buckets alike). Where orbax/jax is unavailable the
store degrades to plain JSON files with the same layout and receipts.

Checkpoint IO is blocking filesystem/network work: it must NEVER run
under store/cache locks (graftlint's blocking-under-lock scope covers
this package; the SessionManager only calls the store from reconcile
bodies, which hold none).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
from typing import Any, Optional

Obj = dict[str, Any]

_META = "session-meta.json"


def _canonical(state: Obj) -> bytes:
    return json.dumps(
        state, sort_keys=True, separators=(",", ":")
    ).encode()


class SessionCheckpointStore:
    """``save(uid, state) → receipt`` / ``load(uid) → (state, digest)``
    / ``delete(uid)``. One subdirectory per notebook UID; re-suspends
    write monotonically increasing steps and old steps are GC'd."""

    def __init__(
        self,
        root: str,
        *,
        backend: str = "auto",
        max_to_keep: int = 2,
    ):
        self.root = root
        self.max_to_keep = max_to_keep
        # "auto" resolves lazily at first IO — constructing the store
        # (e.g. at Platform boot) must not pay the jax/orbax import
        self._backend = backend
        self._managers: dict[str, Any] = {}

    @property
    def backend(self) -> str:
        if self._backend == "auto":
            try:
                from odh_kubeflow_tpu.train.checkpoint import (  # noqa: F401
                    CheckpointManager,
                )

                self._backend = "orbax"
            except Exception:  # jax/orbax not importable → file fallback
                self._backend = "json"
        return self._backend

    # -- paths / metadata ----------------------------------------------------

    def _dir(self, uid: str) -> str:
        return os.path.join(self.root, uid)

    def _meta_path(self, uid: str) -> str:
        return os.path.join(self._dir(uid), _META)

    def _read_meta(self, uid: str) -> Optional[Obj]:
        try:
            with open(self._meta_path(uid)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _write_meta(self, uid: str, meta: Obj) -> None:
        os.makedirs(self._dir(uid), exist_ok=True)
        tmp = self._meta_path(uid) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, self._meta_path(uid))

    # -- orbax backend -------------------------------------------------------

    def _manager(self, uid: str):
        mngr = self._managers.get(uid)
        if mngr is None:
            from odh_kubeflow_tpu.train.checkpoint import CheckpointManager

            mngr = self._managers[uid] = CheckpointManager(
                os.path.join(self._dir(uid), "orbax"),
                max_to_keep=self.max_to_keep,
                # synchronous: the suspend path needs the snapshot
                # durable before the pods are torn down
                async_save=False,
            )
        return mngr

    # -- API -----------------------------------------------------------------

    def save(self, uid: str, state: Obj) -> Obj:
        """Persist ``state`` for ``uid``; returns the receipt
        ``{"step", "digest", "sizeBytes"}`` the SessionCheckpoint
        status records."""
        payload = _canonical(state)
        digest = hashlib.sha256(payload).hexdigest()
        prev = self._read_meta(uid)
        step = (int(prev["step"]) + 1) if prev else 0
        if self.backend == "orbax":
            import jax.numpy as jnp
            import numpy as np

            arr = jnp.asarray(np.frombuffer(payload, np.uint8))
            mngr = self._manager(uid)
            mngr.save(step, {"session": arr}, force=True)
            mngr.wait_until_finished()
        else:
            os.makedirs(self._dir(uid), exist_ok=True)
            with open(self._step_path(uid, step), "wb") as f:
                f.write(payload)
            for old in self._json_steps(uid)[: -self.max_to_keep]:
                try:
                    os.remove(self._step_path(uid, old))
                except OSError:
                    pass
        meta = {"step": step, "digest": digest, "sizeBytes": len(payload)}
        self._write_meta(uid, meta)
        return dict(meta)

    def load(self, uid: str) -> Optional[tuple[Obj, str]]:
        """The latest state for ``uid`` plus the digest of the bytes
        actually read back (callers compare it against the saved
        receipt — the bit-identity check), or None when nothing is
        stored."""
        meta = self._read_meta(uid)
        if meta is None:
            return None
        step = int(meta["step"])
        if self.backend == "orbax":
            import jax
            import numpy as np

            mngr = self._manager(uid)
            like = {
                "session": jax.ShapeDtypeStruct(
                    (int(meta["sizeBytes"]),),
                    np.uint8,
                    sharding=jax.sharding.SingleDeviceSharding(
                        jax.devices()[0]
                    ),
                )
            }
            restored = mngr.restore(like, step=step)
            payload = bytes(np.asarray(restored["session"]))
        else:
            try:
                with open(self._step_path(uid, step), "rb") as f:
                    payload = f.read()
            except OSError:
                return None
        digest = hashlib.sha256(payload).hexdigest()
        return json.loads(payload.decode()), digest

    def exists(self, uid: str) -> bool:
        return self._read_meta(uid) is not None

    def delete(self, uid: str) -> None:
        mngr = self._managers.pop(uid, None)
        if mngr is not None:
            try:
                mngr.close()
            except Exception:  # graftlint: disable=swallowed-exception best-effort close before rmtree
                pass
        shutil.rmtree(self._dir(uid), ignore_errors=True)

    def close(self) -> None:
        for uid in list(self._managers):
            mngr = self._managers.pop(uid)
            try:
                mngr.close()
            except Exception:  # graftlint: disable=swallowed-exception shutdown must not raise
                pass

    # -- json backend helpers ------------------------------------------------

    def _step_path(self, uid: str, step: int) -> str:
        return os.path.join(self._dir(uid), f"state-{step:08d}.json")

    def _json_steps(self, uid: str) -> list[int]:
        try:
            names = os.listdir(self._dir(uid))
        except OSError:
            return []
        steps = []
        for n in names:
            if n.startswith("state-") and n.endswith(".json"):
                try:
                    steps.append(int(n[len("state-"):-len(".json")]))
                except ValueError:
                    pass
        return sorted(steps)
