"""The durable byte store behind SessionCheckpoints, keyed by notebook
UID.

Session state is an opaque JSON-able tree (kernel variables, execution
counters — whatever the in-pod snapshot hook hands over). It is
canonically serialized once, digested (sha256 — the bit-identity
receipt the resume path and the property tests verify), and written
through ``train.checkpoint.CheckpointManager`` — the same orbax-backed
manager training state uses, so session snapshots inherit its
async-capable IO, ``max_to_keep`` GC, and fsspec path support (PVC
paths and ``gs://`` buckets alike). Where orbax/jax is unavailable the
store degrades to plain JSON files with the same layout and receipts.

**Zone replication** (:class:`ReplicatedCheckpointStore`): a single
backing store is one failure domain — a zone loss takes every
suspended session with it. The replicated store fans each save out to
N zone-scoped backing stores (write-all) and records which zones hold
the bytes in the receipt; the sha256 digest doubles as the cross-zone
bit-identity check, so a load may be served from ANY surviving zone
and verified against the CR receipt. A save that lands in fewer zones
than configured is *degraded*, surfaced on the SessionCheckpoint
status and re-replicated by the SessionManager once the zone heals.

Checkpoint IO is blocking filesystem/network work: it must NEVER run
under store/cache locks (graftlint's blocking-under-lock scope covers
this package; the SessionManager only calls the store from reconcile
bodies, which hold none).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
from typing import Any, Optional

Obj = dict[str, Any]

_META = "session-meta.json"


def _canonical(state: Obj) -> bytes:
    return json.dumps(
        state, sort_keys=True, separators=(",", ":")
    ).encode()


class SessionCheckpointStore:
    """``save(uid, state) → receipt`` / ``load(uid) → (state, digest)``
    / ``delete(uid)``. One subdirectory per notebook UID; re-suspends
    write monotonically increasing steps and old steps are GC'd."""

    def __init__(
        self,
        root: str,
        *,
        backend: str = "auto",
        max_to_keep: int = 2,
    ):
        self.root = root
        self.max_to_keep = max_to_keep
        # "auto" resolves lazily at first IO — constructing the store
        # (e.g. at Platform boot) must not pay the jax/orbax import
        self._backend = backend
        self._managers: dict[str, Any] = {}

    @property
    def backend(self) -> str:
        if self._backend == "auto":
            try:
                from odh_kubeflow_tpu.train.checkpoint import (  # noqa: F401
                    CheckpointManager,
                )

                self._backend = "orbax"
            except Exception:  # jax/orbax not importable → file fallback
                self._backend = "json"
        return self._backend

    # -- paths / metadata ----------------------------------------------------

    def _dir(self, uid: str) -> str:
        return os.path.join(self.root, uid)

    def _meta_path(self, uid: str) -> str:
        return os.path.join(self._dir(uid), _META)

    def _read_meta(self, uid: str) -> Optional[Obj]:
        try:
            with open(self._meta_path(uid)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _write_meta(self, uid: str, meta: Obj) -> None:
        os.makedirs(self._dir(uid), exist_ok=True)
        tmp = self._meta_path(uid) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, self._meta_path(uid))

    # -- orbax backend -------------------------------------------------------

    def _manager(self, uid: str):
        mngr = self._managers.get(uid)
        if mngr is None:
            from odh_kubeflow_tpu.train.checkpoint import CheckpointManager

            mngr = self._managers[uid] = CheckpointManager(
                os.path.join(self._dir(uid), "orbax"),
                max_to_keep=self.max_to_keep,
                # synchronous: the suspend path needs the snapshot
                # durable before the pods are torn down
                async_save=False,
            )
        return mngr

    # -- API -----------------------------------------------------------------

    def save(self, uid: str, state: Obj) -> Obj:
        """Persist ``state`` for ``uid``; returns the receipt
        ``{"step", "digest", "sizeBytes"}`` the SessionCheckpoint
        status records."""
        payload = _canonical(state)
        digest = hashlib.sha256(payload).hexdigest()
        prev = self._read_meta(uid)
        step = (int(prev["step"]) + 1) if prev else 0
        if self.backend == "orbax":
            import jax.numpy as jnp
            import numpy as np

            arr = jnp.asarray(np.frombuffer(payload, np.uint8))
            mngr = self._manager(uid)
            mngr.save(step, {"session": arr}, force=True)
            mngr.wait_until_finished()
        else:
            os.makedirs(self._dir(uid), exist_ok=True)
            with open(self._step_path(uid, step), "wb") as f:
                f.write(payload)
            for old in self._json_steps(uid)[: -self.max_to_keep]:
                try:
                    os.remove(self._step_path(uid, old))
                except OSError:
                    pass
        meta = {"step": step, "digest": digest, "sizeBytes": len(payload)}
        self._write_meta(uid, meta)
        return dict(meta)

    def load(
        self, uid: str, expect_digest: Optional[str] = None
    ) -> Optional[tuple[Obj, str]]:
        """The latest state for ``uid`` plus the digest of the bytes
        actually read back (callers compare it against the saved
        receipt — the bit-identity check), or None when nothing is
        stored. ``expect_digest`` is accepted for signature parity
        with :class:`ReplicatedCheckpointStore` (a single store has no
        alternative zone to fall back to, so it is ignored here)."""
        meta = self._read_meta(uid)
        if meta is None:
            return None
        step = int(meta["step"])
        if self.backend == "orbax":
            import jax
            import numpy as np

            mngr = self._manager(uid)
            like = {
                "session": jax.ShapeDtypeStruct(
                    (int(meta["sizeBytes"]),),
                    np.uint8,
                    sharding=jax.sharding.SingleDeviceSharding(
                        jax.devices()[0]
                    ),
                )
            }
            restored = mngr.restore(like, step=step)
            payload = bytes(np.asarray(restored["session"]))
        else:
            try:
                with open(self._step_path(uid, step), "rb") as f:
                    payload = f.read()
            except OSError:
                return None
        digest = hashlib.sha256(payload).hexdigest()
        return json.loads(payload.decode()), digest

    def exists(self, uid: str) -> bool:
        return self._read_meta(uid) is not None

    def delete(self, uid: str) -> bool:
        """Returns whether the delete is complete (duck parity with
        :class:`ReplicatedCheckpointStore` — a single local store's
        rmtree either finished or the leftovers are observable)."""
        mngr = self._managers.pop(uid, None)
        if mngr is not None:
            try:
                mngr.close()
            except Exception:  # graftlint: disable=swallowed-exception best-effort close before rmtree
                pass
        shutil.rmtree(self._dir(uid), ignore_errors=True)
        return not os.path.exists(self._dir(uid))

    def close(self) -> None:
        for uid in list(self._managers):
            mngr = self._managers.pop(uid)
            try:
                mngr.close()
            except Exception:  # graftlint: disable=swallowed-exception shutdown must not raise
                pass

    # -- json backend helpers ------------------------------------------------

    def _step_path(self, uid: str, step: int) -> str:
        return os.path.join(self._dir(uid), f"state-{step:08d}.json")

    def saved_digest(self, uid: str) -> Optional[str]:
        """The digest of the newest save recorded in this store's own
        metadata (no byte read) — what the replicated store compares
        across zones to find which ones are current."""
        meta = self._read_meta(uid)
        return str(meta["digest"]) if meta and "digest" in meta else None

    def _json_steps(self, uid: str) -> list[int]:
        try:
            names = os.listdir(self._dir(uid))
        except OSError:
            return []
        steps = []
        for n in names:
            if n.startswith("state-") and n.endswith(".json"):
                try:
                    steps.append(int(n[len("state-"):-len(".json")]))
                except ValueError:
                    pass
        return sorted(steps)


# ---------------------------------------------------------------------------
# zone replication


def parse_zone_spec(spec: str, default_root: str) -> dict[str, str]:
    """``SESSION_CHECKPOINT_ZONES`` parser: a comma-separated list of
    ``zone=path`` entries (independent PVCs / buckets, one per zone) or
    bare zone names, which become subdirectories of ``default_root``
    (sim / single-volume dev). Order is preserved — the first zone is
    the preferred read source. Empty spec → no replication."""
    zones: dict[str, str] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            zone, _, path = part.partition("=")
            zones[zone.strip()] = path.strip()
        else:
            zones[part] = os.path.join(default_root, part)
    return zones


class ReplicatedCheckpointStore:
    """Zone-replicated façade over N :class:`SessionCheckpointStore`
    backing stores, one per failure domain (``topology.kubernetes.io/
    zone``). Same duck type as the single store — the SessionManager
    swaps it in unchanged — plus the replication surface:

    - ``save`` is **write-all**: the canonical bytes go to every zone;
      the receipt records ``zones`` (where the write actually became
      durable) and ``degraded`` (fewer zones than configured). At
      least one zone must land or the save raises — a checkpoint with
      zero durability receipts must never release the slice.
    - ``load`` reads from **any surviving zone**, newest first; when
      the caller passes the CR receipt digest, zones whose bytes read
      back different (stale step, bit rot, torn volume) are skipped in
      favor of a zone that verifies — the sha256 receipt is the
      cross-zone bit-identity rail.
    - ``heal`` re-replicates the newest verified state into zones that
      missed it (the zone-heal half of the degraded contract).

    ``fail_zone``/``heal_zone`` are the deterministic outage hooks the
    drills (and operators' break-glass tooling) use; real IO errors on
    a zone degrade the same way."""

    def __init__(
        self,
        zones: dict[str, str],
        *,
        backend: str = "auto",
        max_to_keep: int = 2,
    ):
        if not zones:
            raise ValueError("ReplicatedCheckpointStore needs >=1 zone")
        self.stores: dict[str, SessionCheckpointStore] = {
            zone: SessionCheckpointStore(
                path, backend=backend, max_to_keep=max_to_keep
            )
            for zone, path in zones.items()
        }
        self._failed: set[str] = set()

    @property
    def zones(self) -> list[str]:
        return list(self.stores)

    # -- outage hooks --------------------------------------------------------

    def fail_zone(self, zone: str) -> None:
        """Take ``zone`` offline: reads and writes against it behave
        exactly like a dead volume (skipped / degraded)."""
        if zone in self.stores:
            self._failed.add(zone)

    def heal_zone(self, zone: str) -> None:
        self._failed.discard(zone)

    def failed_zones(self) -> list[str]:
        return sorted(self._failed)

    # -- the SessionCheckpointStore duck -------------------------------------

    def save(self, uid: str, state: Obj) -> Obj:
        """Write-all with per-zone durability receipts. The returned
        receipt extends the single-store shape with ``zones`` (the
        list that actually landed) and ``degraded``."""
        receipt: Optional[Obj] = None
        landed: list[str] = []
        for zone, store in self.stores.items():
            if zone in self._failed:
                continue
            try:
                zone_receipt = store.save(uid, state)
            except OSError:
                continue  # this zone is down; the receipt records it
            landed.append(zone)
            if receipt is None:
                receipt = zone_receipt
        if receipt is None or not landed:
            raise OSError(
                f"checkpoint for {uid} landed in no zone "
                f"(configured: {', '.join(self.stores)})"
            )
        receipt["zones"] = landed
        receipt["degraded"] = len(landed) < len(self.stores)
        return receipt

    def load(
        self, uid: str, expect_digest: Optional[str] = None
    ) -> Optional[tuple[Obj, str]]:
        """The newest stored state from any surviving zone. With
        ``expect_digest`` (the CR receipt) the first zone whose bytes
        verify wins; a zone holding stale or corrupt bytes is skipped
        while ANY zone still verifies. Without it (or when no zone
        verifies) the newest-step zone is served and the caller's own
        digest check decides.

        Candidate selection reads only each zone's metadata; the
        checkpoint BYTES (the expensive read on gs:// backends) are
        fetched from chosen zones only."""
        candidates: list[tuple[int, str]] = []  # (step, zone), meta-only
        for zone, store in self.stores.items():
            if zone in self._failed:
                continue
            meta = store._read_meta(uid)
            if meta is None:
                continue
            if expect_digest and meta.get("digest") == expect_digest:
                try:
                    loaded = store.load(uid)
                except OSError:
                    continue
                # verify the BYTES too — a meta that matches over torn
                # bytes must not end the scan early
                if loaded is not None and loaded[1] == expect_digest:
                    return loaded
                continue
            candidates.append((int(meta.get("step", 0)), zone))
        for _step, zone in sorted(candidates, reverse=True):
            try:
                loaded = self.stores[zone].load(uid)
            except OSError:
                continue
            if loaded is not None:
                return loaded
        return None

    def exists(self, uid: str) -> bool:
        return any(
            store.exists(uid)
            for zone, store in self.stores.items()
            if zone not in self._failed
        )

    def delete(self, uid: str) -> bool:
        """Delete ``uid``'s bytes from every reachable zone. Returns
        whether the delete is COMPLETE — False while any zone (failed,
        or erroring) may still hold bytes, so the caller keeps the CR
        (the only uid→bytes record) and retries after the zone heals
        instead of orphaning a checkpoint on the dark volume forever."""
        complete = True
        for zone, store in self.stores.items():
            if zone in self._failed:
                complete = False
                continue
            try:
                store.delete(uid)
            except OSError:
                complete = False
                continue
            if store.exists(uid):
                complete = False
        return complete

    def close(self) -> None:
        for store in self.stores.values():
            store.close()

    # -- replication status & heal -------------------------------------------

    def replication_status(self, uid: str, digest: str) -> Obj:
        """Which zones hold bytes verifying against ``digest`` (the CR
        receipt): ``{"zones": [...], "missing": [...], "degraded"}``.
        Zones currently failed count as missing — their bytes are
        unreachable whether or not they exist."""
        holding: list[str] = []
        missing: list[str] = []
        for zone, store in self.stores.items():
            if zone not in self._failed and store.saved_digest(uid) == digest:
                holding.append(zone)
            else:
                missing.append(zone)
        return {
            "zones": holding,
            "missing": missing,
            "degraded": bool(missing) or not holding,
        }

    def heal(self, uid: str, digest: str) -> Obj:
        """Re-replicate after a zone heals: copy the newest VERIFIED
        state (any zone whose read-back matches ``digest``) into every
        reachable zone that lacks it, and return the refreshed
        :meth:`replication_status`. A no-op (current status returned)
        while no verifying source zone is reachable."""
        source: Optional[Obj] = None
        for zone, store in self.stores.items():
            if zone in self._failed:
                continue
            try:
                loaded = store.load(uid)
            except OSError:
                continue
            if loaded is not None and loaded[1] == digest:
                source = loaded[0]
                break
        if source is not None:
            for zone, store in self.stores.items():
                if zone in self._failed or store.saved_digest(uid) == digest:
                    continue
                try:
                    store.save(uid, source)
                except OSError:
                    continue  # still down; next heal pass retries
        return self.replication_status(uid, digest)
