"""Suspend-to-checkpoint sessions: release the slice, keep the kernel.

The reference platform's culler stops idle notebooks outright — warm
state is lost and the TPU slice stays pinned until the cull fires.
NotebookOS ("A Replicated Notebook Platform for Interactive Training
with On-Demand GPUs", arXiv 2503.20591) shows the better design:
snapshot the kernel, release the accelerator, restore on demand in
seconds. This package is that subsystem for TPU slices:

- ``SessionCheckpoint`` — a platform object (normal store/watch path)
  recording one notebook's durable kernel snapshot: where it lives,
  its digest/size, and the suspend/resume state machine
  (``Suspending → Suspended → Resuming → Restored``);
- ``checkpoint``      — the ``CheckpointManager``-backed byte store
  keyed by notebook UID (orbax when available, JSON files otherwise);
- ``manager``         — the ``SessionManager`` controller: snapshots on
  cull/preempt *before* the gang scales down, restores into the fresh
  pod on resume *before* the notebook reports ready, and implements the
  scheduler's checkpoint-then-preempt suspender hooks.

The contract with the rest of the platform:

- the culler (``suspend_on_cull``) and the slice scheduler
  (checkpoint-then-preempt) request a suspend by stamping
  ``SUSPENDED_AT_ANNOTATION`` alongside ``kubeflow-resource-stopped``;
- the notebook controller holds the scale-down while
  ``suspend_pending`` is true, so the snapshot happens against live
  pods; once the checkpoint is durable the StatefulSet goes to zero and
  the gang Workload is deleted — the slice reservation is freed;
- quota pools (``scheduling/queue.py``) gain an oversubscription
  factor: suspended sessions hold no chips, so admitted-but-suspendable
  sessions can exceed physical inventory up to ``hard × factor``;
- JWA distinguishes "stopped" from "suspended, resumable" and offers a
  resume API that re-enqueues the Workload.
"""

from __future__ import annotations

from typing import Any, Optional

from odh_kubeflow_tpu.apis import SUSPENDED_AT_ANNOTATION
from odh_kubeflow_tpu.machinery import objects as obj_util
from odh_kubeflow_tpu.machinery.store import NotFound

Obj = dict[str, Any]

GROUP = "sessions.kubeflow.org"
SESSION_API_VERSION = f"{GROUP}/v1alpha1"

NOTEBOOK_UID_LABEL = f"{GROUP}/notebook-uid"

# SessionCheckpoint status.phase state machine
PHASE_SUSPENDING = "Suspending"
PHASE_SUSPENDED = "Suspended"
PHASE_RESUMING = "Resuming"
PHASE_RESTORED = "Restored"


def register_sessions(api: Any) -> None:
    """Register the SessionCheckpoint kind on an APIServer-shaped api
    (embedded store or RemoteAPIServer)."""
    api.register_kind(
        SESSION_API_VERSION, "SessionCheckpoint", "sessioncheckpoints", True
    )


def checkpoint_of(api: Any, notebook: Obj) -> Optional[Obj]:
    """The notebook's SessionCheckpoint (named after it), or None when
    it has none — or the sessions kind isn't registered at all.

    The checkpoint rides the notebook NAME, but a deleted-and-recreated
    notebook reuses the name with a fresh uid — the ``notebook-uid``
    label stamped at checkpoint creation fences a leftover checkpoint
    out of the new notebook's resume path."""
    try:
        ckpt = api.get(
            "SessionCheckpoint",
            obj_util.name_of(notebook),
            obj_util.namespace_of(notebook),
        )
    except NotFound:
        return None
    want = obj_util.meta(notebook).get("uid", "")
    have = obj_util.labels_of(ckpt).get(NOTEBOOK_UID_LABEL, "")
    if want and have and want != have:
        return None
    return ckpt


def checkpoint_durable(ckpt: Optional[Obj], suspended_at: str) -> bool:
    """Whether ``ckpt`` holds the snapshot for THIS suspend epoch
    (``suspended_at`` is the annotation value — a re-suspend stamps a
    new timestamp and needs a fresh snapshot)."""
    if ckpt is None:
        return False
    status = ckpt.get("status") or {}
    return (
        status.get("phase") == PHASE_SUSPENDED
        and status.get("suspendedAt") == suspended_at
    )


def suspend_pending(
    api: Any,
    notebook: Obj,
    grace_seconds: float = 600.0,
    now: Optional[float] = None,
) -> bool:
    """True while a requested suspend still needs its snapshot taken —
    the notebook controller holds the scale-down (pods stay up, the
    Workload keeps its reservation) until this turns false.

    ``grace_seconds`` is the wedge-breaker: if no session manager
    completes the checkpoint within the grace window (missing deploy,
    snapshot endpoint dead), the suspend degrades to a plain stop —
    losing state is better than leaking a TPU slice forever."""
    suspended_at = obj_util.annotations_of(notebook).get(
        SUSPENDED_AT_ANNOTATION
    )
    if not suspended_at:
        return False
    if checkpoint_durable(checkpoint_of(api, notebook), suspended_at):
        return False
    if grace_seconds is not None:
        import time

        now = time.time() if now is None else now
        if now - obj_util.parse_rfc3339(suspended_at) > grace_seconds:
            return False
    return True


def committed_checkpoints(api: Any, namespace: Optional[str] = None) -> list[Obj]:
    """THE committed-session ledger: SessionCheckpoints whose chips are
    committed to the pool but not occupying inventory — phase
    ``Suspended`` or ``Resuming``, EXCLUDING any whose Workload is
    currently Admitted (those chips live in the active charge; counting
    the checkpoint too would double-book them). Shared by admission
    (``scheduling/queue.py``), the JWA quota block, and the dashboard
    occupancy panel so the three surfaces cannot drift."""
    try:
        rows = api.list("SessionCheckpoint", namespace=namespace)  # uncached-ok: committed-ledger snapshot over a small kind
    except NotFound:  # sessions subsystem not installed
        return []
    out = []
    for ck in rows:
        if obj_util.get_path(ck, "status", "phase", default="") not in (
            PHASE_SUSPENDED,
            PHASE_RESUMING,
        ):
            continue
        try:
            wl = api.get(
                "Workload", obj_util.name_of(ck), obj_util.namespace_of(ck)
            )
            if (
                obj_util.get_path(wl, "status", "state", default="")
                == "Admitted"
            ):
                continue
        except NotFound:
            pass
        out.append(ck)
    return out


def checkpoint_chips(ckpt: Obj) -> int:
    return int(obj_util.get_path(ckpt, "spec", "chips", default=0) or 0)


def new_checkpoint(notebook: Obj, chips: int, accel: str, topo: str) -> Obj:
    """A fresh SessionCheckpoint shell for ``notebook`` (the manager
    fills status as the state machine advances). Not owner-referenced:
    the manager GCs it explicitly so it can also delete the stored
    bytes (cascade would drop the object before the UID is read)."""
    return {
        "apiVersion": SESSION_API_VERSION,
        "kind": "SessionCheckpoint",
        "metadata": {
            "name": obj_util.name_of(notebook),
            "namespace": obj_util.namespace_of(notebook),
            "labels": {
                NOTEBOOK_UID_LABEL: obj_util.meta(notebook).get("uid", "")
            },
        },
        "spec": {
            "notebook": obj_util.name_of(notebook),
            "notebookUID": obj_util.meta(notebook).get("uid", ""),
            "chips": int(chips),
            "acceleratorType": accel,
            "topology": topo,
        },
    }
