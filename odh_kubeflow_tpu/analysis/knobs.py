"""Knob-registry drift lint: every env knob, accounted for.

The platform is configured by ~90 ``os.environ`` reads spread across
the runner, client, replica, profile and platform layers. Each knob is
documented in a GUIDE.md table and (for the deployed components) set in
a manifest env stanza — three surfaces that historically drifted
independently: manifests shipped envs nothing read (``ADMIN``,
``QUOTA_TPU_KEY``, ``CULL_CHECK_TPU_DUTY_CYCLE`` before this lint),
and new knobs landed in code without a docs row.

This module makes the registry (``analysis/knobs.json``) the single
machine-readable source of truth and cross-checks it against all three
surfaces, tier-1 gated (``tests/test_knobs.py``) and CLI-runnable::

    python -m odh_kubeflow_tpu.analysis.knobs

Checks:

- **undocumented**: a knob read in package code but absent from the
  registry — add a registry entry (name, scope, default, description).
- **phantom**: a registry knob no code reads — delete the entry or the
  dead config it describes. Entries marked ``"dynamic": true`` are
  exempt (read via generated code or a computed name the AST scan
  cannot see, e.g. the in-pod profiler autostart flag).
- **guide**: every registry knob must appear backticked in
  ``docs/GUIDE.md`` (the knob tables / appendix).
- **manifest**: every ``env:`` name in ``manifests/**.yaml`` that looks
  like a platform knob must be a registry knob (or listed in the
  registry's ``manifest_external`` allowlist — envs owned by kube or
  third-party images, e.g. the pod-injected TPU topology contract).

The scanner is AST-based and understands the package's idioms:
``os.environ.get/[]/setdefault``, ``os.getenv``, ``env = os.environ``
aliases, module-level name constants (``CHAOS_ENV = "GRAFT_CHAOS"``),
and per-file env-reader helpers (``_env_int("SNAPSHOT_BYTES", …)``,
nested ``flag("USE_ISTIO")`` closures) — a helper is any function with
a parameter that flows into an environ read's key position.
"""

from __future__ import annotations

import ast
import json
import os
import re
import sys
from typing import Iterator, Optional

from odh_kubeflow_tpu.analysis.graftlint import iter_sources, package_root

_attr = None  # no callgraph dependency: the scan is self-contained


def registry_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "knobs.json")


def repo_root() -> str:
    return os.path.dirname(package_root())


def guide_path() -> str:
    return os.path.join(repo_root(), "docs", "GUIDE.md")


def manifests_root() -> str:
    return os.path.join(repo_root(), "manifests")


# knob names are SCREAMING_SNAKE; anything else in an env stanza (e.g.
# lowercase pod metadata) is ignored by the manifest cross-check
_KNOB_NAME_RE = re.compile(r"^[A-Z][A-Z0-9_]{2,}$")


# ---------------------------------------------------------------------------
# scanner


def _environ_aliases(tree: ast.AST) -> set[str]:
    """Names bound to ``os.environ`` — ``env = os.environ`` aliases and
    ``from os import environ`` imports. A bare name called ``environ``
    is NOT assumed (WSGI handlers take a request dict by that name)."""
    aliases: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "os":
            for a in node.names:
                if a.name == "environ":
                    aliases.add(a.asname or a.name)
        if not isinstance(node, ast.Assign):
            continue
        v = node.value
        if (
            isinstance(v, ast.Attribute)
            and v.attr == "environ"
            and isinstance(v.value, ast.Name)
            and v.value.id == "os"
        ):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    aliases.add(t.id)
    return aliases


def _name_constants(tree: ast.AST) -> dict[str, str]:
    """Module-level ``NAME = "LITERAL"`` string constants (resolves
    ``os.environ.get(CHAOS_ENV)``)."""
    out: dict[str, str] = {}
    for node in tree.body if isinstance(tree, ast.Module) else []:
        if (
            isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, str)
        ):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = node.value.value
    return out


def _is_environ_base(node: ast.AST, aliases: set[str]) -> bool:
    """``os.environ`` / a recorded alias of it."""
    if isinstance(node, ast.Attribute) and node.attr == "environ":
        return isinstance(node.value, ast.Name) and node.value.id == "os"
    return isinstance(node, ast.Name) and node.id in aliases


def _env_key_exprs(tree: ast.AST, aliases: set[str]) -> Iterator[ast.AST]:
    """Every expression used as an environ KEY in this tree:
    ``<environ>.get/setdefault(K, …)``, ``<environ>[K]``,
    ``os.getenv(K, …)``."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            f = node.func
            if (
                isinstance(f, ast.Attribute)
                and f.attr in ("get", "setdefault", "pop")
                and _is_environ_base(f.value, aliases)
                and node.args
            ):
                yield node.args[0]
            elif (
                isinstance(f, ast.Attribute)
                and f.attr == "getenv"
                and isinstance(f.value, ast.Name)
                and f.value.id == "os"
                and node.args
            ):
                yield node.args[0]
        elif isinstance(node, ast.Subscript) and _is_environ_base(
            node.value, aliases
        ):
            yield node.slice


def _helper_params(tree: ast.AST, aliases: set[str]) -> dict[str, int]:
    """Functions (at any nesting) where a PARAMETER flows into an
    environ read's key position → {helper name: param index}. Catches
    ``_env_int(name, default)`` and the nested ``flag(name)`` idiom."""
    helpers: dict[str, int] = {}
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        params = [a.arg for a in node.args.args]
        for key in _env_key_exprs(node, aliases):
            if isinstance(key, ast.Name) and key.id in params:
                helpers[node.name] = params.index(key.id)
    return helpers


def scan_tree(tree: ast.AST) -> set[str]:
    """Knob names read by one parsed module."""
    aliases = _environ_aliases(tree)
    consts = _name_constants(tree)
    names: set[str] = set()

    def key_name(expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return expr.value
        if isinstance(expr, ast.Name):
            return consts.get(expr.id)
        return None

    for key in _env_key_exprs(tree, aliases):
        name = key_name(key)
        if name:
            names.add(name)
    helpers = _helper_params(tree, aliases)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fname = None
        if isinstance(node.func, ast.Name):
            fname = node.func.id
        elif isinstance(node.func, ast.Attribute):
            fname = node.func.attr
        idx = helpers.get(fname or "")
        if idx is None:
            continue
        # self/cls offset does not apply: helpers here are module-level
        # or nested functions, never methods, and the scan is per-file
        if idx < len(node.args):
            name = key_name(node.args[idx])
            if name:
                names.add(name)
    return names


def scan_source(text: str) -> set[str]:
    """Fixture entry point: knob names read by a source string."""
    return scan_tree(ast.parse(text))


def scan_package(root: Optional[str] = None) -> dict[str, list[str]]:
    """Knob → sorted list of package-relative files reading it."""
    out: dict[str, set[str]] = {}
    for src in iter_sources(root):
        for name in scan_tree(src.tree):
            out.setdefault(name, set()).add(src.rel)
    return {k: sorted(v) for k, v in sorted(out.items())}


# ---------------------------------------------------------------------------
# registry + cross-checks


def load_registry(path: Optional[str] = None) -> dict:
    with open(path or registry_path(), encoding="utf-8") as fh:
        return json.load(fh)


def guide_text(path: Optional[str] = None) -> str:
    with open(path or guide_path(), encoding="utf-8") as fh:
        return fh.read()


def guide_knob_mentions(text: str) -> set[str]:
    """Backticked SCREAMING_SNAKE names anywhere in the guide text."""
    return {
        m.group(1)
        for m in re.finditer(r"`([A-Z][A-Z0-9_]{2,})(?:=[^`]*)?`", text)
    }


# scopes in appendix order (free-form strings; unknown scopes sort last)
_SCOPE_ORDER = (
    "platform", "runner", "client", "replica", "controller", "scheduler",
    "sessions", "warmup", "profile", "web", "webhooks", "pod", "test",
)


def appendix_row(entry: dict) -> str:
    """The canonical GUIDE.md appendix table row for one registry
    entry — the lint demands this EXACT line in the guide, so the
    appendix is generated-by-enforcement: edit knobs.json, re-render,
    and a stale default/description row fails tier-1."""
    default = entry.get("default") or "—"
    return f"| `{entry['name']}` | {default} | {entry['description']} |"


def render_appendix(registry: Optional[dict] = None) -> str:
    """The full '## Appendix: knob reference' body (scope-grouped
    tables) rendered from the registry — paste-ready for GUIDE.md."""
    reg = registry if registry is not None else load_registry()
    by_scope: dict[str, list[dict]] = {}
    for e in reg.get("knobs", []):
        by_scope.setdefault(e.get("scope", "?"), []).append(e)
    order = [s for s in _SCOPE_ORDER if s in by_scope] + sorted(
        s for s in by_scope if s not in _SCOPE_ORDER
    )
    lines: list[str] = []
    for scope in order:
        lines += [f"### {scope}", "", "| knob | default | description |",
                  "|---|---|---|"]
        lines += [
            appendix_row(e)
            for e in sorted(by_scope[scope], key=lambda x: x["name"])
        ]
        lines.append("")
    return "\n".join(lines).rstrip() + "\n"


def manifest_env_names(root: Optional[str] = None) -> dict[str, list[str]]:
    """Env names set in manifest stanzas: ``- name: KNOB`` entries and
    kustomize ``KNOB=value`` literals, knob-shaped names only."""
    root = root or manifests_root()
    out: dict[str, set[str]] = {}
    name_re = re.compile(r"^\s*-?\s*name:\s*([A-Z][A-Z0-9_]{2,})\s*$")
    literal_re = re.compile(r"^\s*-\s*([A-Z][A-Z0-9_]{2,})=")
    for dirpath, _dirs, files in os.walk(root):
        for fname in sorted(files):
            if not fname.endswith((".yaml", ".yml")):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, root)
            with open(path, encoding="utf-8") as fh:
                for line in fh:
                    m = name_re.match(line) or literal_re.match(line)
                    if m and _KNOB_NAME_RE.match(m.group(1)):
                        out.setdefault(m.group(1), set()).add(rel)
    return {k: sorted(v) for k, v in sorted(out.items())}


def knob_violations(
    root: Optional[str] = None,
    registry: Optional[dict] = None,
    guide: Optional[str] = None,
    manifests: Optional[dict[str, list[str]]] = None,
) -> list[str]:
    """Every drift between code, registry, GUIDE.md and manifests —
    empty on a healthy tree (the tier-1 gate). ``guide`` is the guide
    TEXT (defaults to docs/GUIDE.md): each registry knob must appear
    backticked AND its exact :func:`appendix_row` must be present, so
    the appendix cannot drift from the registry's defaults or
    descriptions."""
    reg = registry if registry is not None else load_registry()
    knobs = {e["name"]: e for e in reg.get("knobs", [])}
    scanned = scan_package(root)
    text = guide if guide is not None else guide_text()
    guide_names = guide_knob_mentions(text)
    manifest = (
        manifests if manifests is not None else manifest_env_names()
    )
    external = set(reg.get("manifest_external", []))
    out: list[str] = []
    for name, files in scanned.items():
        if name not in knobs:
            out.append(
                f"undocumented knob {name!r} (read in {', '.join(files)}): "
                "add it to analysis/knobs.json with scope/default/"
                "description and a GUIDE.md row"
            )
    for name, entry in knobs.items():
        if entry.get("dynamic"):
            continue
        if name not in scanned:
            out.append(
                f"phantom knob {name!r}: registered in analysis/knobs.json "
                "but no package code reads it — delete the entry or mark "
                'it "dynamic" with a pointer to the generated read site'
            )
    for name, entry in knobs.items():
        if name not in guide_names:
            out.append(
                f"knob {name!r} is not documented in docs/GUIDE.md — add "
                "it to a knob table (the Knob reference appendix at "
                "minimum)"
            )
        elif appendix_row(entry) not in text:
            out.append(
                f"knob {name!r}'s appendix row is stale or missing in "
                "docs/GUIDE.md (default/description diverged from "
                "analysis/knobs.json) — regenerate with `python -m "
                "odh_kubeflow_tpu.analysis.knobs --render-appendix`"
            )
    for name, files in manifest.items():
        if name in knobs or name in external:
            continue
        out.append(
            f"manifest env {name!r} ({', '.join(files)}) is not a "
            "registered knob: nothing in the package reads it — remove "
            "the stanza, wire the knob, or allowlist it under "
            '"manifest_external" in analysis/knobs.json'
        )
    return sorted(out)


def main(argv: Optional[list[str]] = None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    if "--render-appendix" in args:
        # paste-ready appendix body for docs/GUIDE.md, straight from
        # the registry (the lint holds the guide to these exact rows)
        print(render_appendix(), end="")
        return 0
    violations = knob_violations()
    for v in violations:
        print(v)
    n = len(load_registry().get("knobs", []))
    if violations:
        print(
            f"knob-registry: {len(violations)} violation(s) across "
            f"{n} registered knob(s)",
            file=sys.stderr,
        )
        return 1
    print(f"knob-registry: clean ({n} knobs)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
