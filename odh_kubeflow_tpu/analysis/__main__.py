"""``python -m odh_kubeflow_tpu.analysis`` — run graftlint over the
package (or given paths) and exit non-zero on findings. The CI lint
step and ``make lint`` gate on this."""

import sys

from odh_kubeflow_tpu.analysis.graftlint import main

sys.exit(main())
