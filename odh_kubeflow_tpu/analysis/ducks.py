"""Duck-conformance inference for the ``APIServer`` protocol surface.

The platform's storage stack is a tower of duck-typed wrappers around
``machinery/store.py``'s ``APIServer`` — the chaos injector, the
informer-cache façade, the HTTP client, the read-split and fanout
shims, the partition router. Nothing makes them conform: a wrapper can
silently miss a verb (``__getattr__`` hides the hole until a caller
needs fault injection on it), drop a keyword the reference grew
(PR-10's ``limit=``), or swallow kwargs through a blind ``*args,
**kwargs`` pass-through that turns a typo'd keyword into silent
mis-routing instead of a loud ``TypeError``.

This module is the ratchet:

- the reference protocol (verb set + per-verb signatures + the
  ``applied_rv``/``kind_version``/``state_digest`` auxiliary surface)
  is EXTRACTED from ``machinery/store.py`` on every run — the rule
  tracks the reference as it evolves, no hand-maintained copy to rot
  (``DEFAULT_REFERENCE`` below is only the fixture-mode fallback, and
  a tier-1 test pins it byte-for-byte to the live extraction);
- every implementation is DECLARED in the ``DUCKS`` inventory with its
  delegation policy (which verbs must be explicit methods, which may
  ride ``__getattr__``), its allowed signature deviations (a remote
  client has no in-process ``inline=`` pump), and its declared extra
  error surface (the chaos injector raises ``Conflict`` on create by
  design) — declared-and-verified, the ``POLICY_ANCHORS`` pattern;
- an auto-discovery sweep over ``machinery/`` catches the NEXT wrapper
  someone writes without declaring it (PR-13's ``replica.py`` silently
  shadowed out of a lint scope is exactly this failure);
- the error-translation loop is closed end to end: ``httpapi``'s
  APIError→HTTP-status table and ``client.py``'s status→APIError
  tables must compose to the identity for every wire-protocol error
  class, so a status the server can emit never comes back as the
  wrong exception type (or as a bare ``APIError``) on the client;
- each explicit verb's inferred raise set (the PR-15
  ``analysis/exceptions.py`` machinery) must stay inside the declared
  verb model ``VERB_RAISES`` plus the duck's declared extras.

Real findings get FIXED, not baselined — the committed baseline ships
empty, and the tier-1 gate in ``tests/test_ducks.py`` keeps it that
way.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterator, Mapping, Optional

from odh_kubeflow_tpu.analysis.callgraph import FuncInfo, _attr_chain
from odh_kubeflow_tpu.analysis.exceptions import (
    VERB_RAISES,
    mine_hierarchy,
    render_chain,
)
from odh_kubeflow_tpu.analysis.graftlint import (
    Finding,
    ProgramRule,
    register,
)

# ---------------------------------------------------------------------------
# the protocol surface

# the verb set every APIServer duck must serve (explicitly or through a
# declared delegation path)
CORE_VERBS: tuple[str, ...] = (
    "create",
    "get",
    "list",
    "list_chunk",
    "update",
    "update_status",
    "patch",
    "delete",
    "watch",
    "create_or_get",
    "emit_event",
)

# the type-registry / admission surface (broadcast on routers, no-op on
# remote clients — kube parity: you deploy a webhook, you don't
# register Go code into kube-apiserver)
REGISTRY_VERBS: tuple[str, ...] = (
    "register_kind",
    "register_admission_hook",
    "type_info",
    "kind_for_plural",
)

# the replication / bytes-cache / digest-drill surface. Ducks that
# declare it must define it EXPLICITLY: ``__getattr__`` delegation
# makes ``hasattr`` probes always-true, silently bypasses wrapper
# semantics (a chaos wrapper's fault points, a router's fleet
# composition), and leaves nothing for this rule to verify.
AUX_SURFACE: tuple[str, ...] = ("applied_rv", "kind_version", "state_digest")

REFERENCE_FILE = "machinery/store.py"
REFERENCE_CLASS = "APIServer"


@dataclasses.dataclass(frozen=True)
class Param:
    name: str
    has_default: bool


@dataclasses.dataclass(frozen=True)
class Sig:
    """A method signature, normalized: positional-or-keyword params
    after ``self`` (with defaultness), keyword-only params, and the
    catch-all flags."""

    params: tuple[Param, ...]
    kwonly: tuple[Param, ...] = ()
    vararg: bool = False
    kwarg: bool = False

    def render(self) -> str:
        parts = [
            f"{p.name}=…" if p.has_default else p.name for p in self.params
        ]
        if self.vararg:
            parts.append("*args")
        if self.kwonly and not self.vararg:
            parts.append("*")
        parts.extend(f"{p.name}=…" for p in self.kwonly)
        if self.kwarg:
            parts.append("**kwargs")
        return "(" + ", ".join(parts) + ")"


def _sig_of(node: ast.FunctionDef) -> Sig:
    a = node.args
    pos = a.posonlyargs + a.args
    if pos and pos[0].arg in ("self", "cls"):
        pos = pos[1:]
    n_default = len(a.defaults)
    params = tuple(
        Param(p.arg, i >= len(pos) - n_default) for i, p in enumerate(pos)
    )
    kwonly = tuple(
        Param(p.arg, a.kw_defaults[i] is not None)
        for i, p in enumerate(a.kwonlyargs)
    )
    return Sig(params, kwonly, a.vararg is not None, a.kwarg is not None)


def _p(*names: str) -> tuple[Param, ...]:
    """Shorthand: ``name`` is required, ``name=`` is optional."""
    return tuple(
        Param(n[:-1], True) if n.endswith("=") else Param(n, False)
        for n in names
    )


# the reference protocol as of machinery/store.py — the fixture-mode
# fallback. Package runs re-extract it from source; the tier-1 test
# pins this copy to the live extraction so it cannot drift.
DEFAULT_REFERENCE: dict[str, Sig] = {
    "create": Sig(_p("obj", "dry_run=")),
    "get": Sig(_p("kind", "name", "namespace=")),
    "list": Sig(
        _p("kind", "namespace=", "label_selector=", "field_matches=", "limit=")
    ),
    "list_chunk": Sig(
        _p(
            "kind",
            "namespace=",
            "label_selector=",
            "field_matches=",
            "limit=",
            "continue_token=",
        )
    ),
    "update": Sig(_p("obj")),
    "update_status": Sig(_p("obj")),
    "patch": Sig(_p("kind", "name", "patch", "namespace=")),
    "delete": Sig(_p("kind", "name", "namespace=")),
    "watch": Sig(
        _p(
            "kind",
            "namespace=",
            "send_initial=",
            "resource_version=",
            "inline=",
        )
    ),
    "create_or_get": Sig(_p("obj")),
    "emit_event": Sig(
        _p("involved", "reason", "message", "event_type=", "component=")
    ),
    "register_kind": Sig(_p("api_version", "kind", "plural", "namespaced=")),
    "register_admission_hook": Sig(_p("kinds", "fn", "mutating=", "name=")),
    "type_info": Sig(_p("kind")),
    "kind_for_plural": Sig(_p("plural")),
    "applied_rv": Sig(()),
    "kind_version": Sig(_p("kind")),
    "state_digest": Sig(()),
}


def reference_protocol(program) -> dict[str, Sig]:
    """The reference verb signatures, extracted from the analyzed
    ``machinery/store.py`` when present (package runs) and falling back
    to :data:`DEFAULT_REFERENCE` per-verb otherwise (fixtures)."""
    out = dict(DEFAULT_REFERENCE)
    src = program.sources.get(REFERENCE_FILE)
    if src is None:
        return out
    for node in src.tree.body:
        if isinstance(node, ast.ClassDef) and node.name == REFERENCE_CLASS:
            for item in node.body:
                if (
                    isinstance(item, ast.FunctionDef)
                    and item.name in DEFAULT_REFERENCE
                ):
                    out[item.name] = _sig_of(item)
    return out


# ---------------------------------------------------------------------------
# the implementation inventory


@dataclasses.dataclass(frozen=True)
class DuckSpec:
    """One declared APIServer implementation and its conformance
    policy. ``explicit`` members must resolve to real method
    definitions (own body or a base class in the analyzed set);
    everything else may ride ``__getattr__`` when ``delegated_ok``.
    ``aux`` names the auxiliary-surface members this duck serves —
    those must ALWAYS be explicit. ``allow_missing`` grants per-verb
    reference parameters this duck deliberately does not accept;
    ``extra_raises`` declares per-verb platform errors beyond the
    ``VERB_RAISES`` model this duck raises by design."""

    file: str
    cls: str
    role: str
    explicit: frozenset[str] = frozenset()
    aux: frozenset[str] = frozenset(AUX_SURFACE)
    delegated_ok: bool = False
    allow_missing: Mapping[str, frozenset[str]] = dataclasses.field(
        default_factory=dict
    )
    extra_raises: Mapping[str, frozenset[str]] = dataclasses.field(
        default_factory=dict
    )
    notes: str = ""


_ALL_VERBS = frozenset(CORE_VERBS) | frozenset(REGISTRY_VERBS)

# a remote wire client has no in-process event pump to inline
_NO_INLINE = {"watch": frozenset({"inline"})}

DUCKS: tuple[DuckSpec, ...] = (
    DuckSpec(
        file="machinery/replica.py",
        cls="ReplicaStore",
        role="follower replica",
        # an APIServer subclass: the whole surface is inherited; the
        # mutation overrides raise NotLeader instead of forwarding
        explicit=_ALL_VERBS | frozenset(AUX_SURFACE),
        notes="inherits APIServer; mutations 307 to the leader",
    ),
    DuckSpec(
        file="machinery/faults.py",
        cls="FaultInjector",
        role="chaos wrapper",
        explicit=frozenset(CORE_VERBS) | frozenset(AUX_SURFACE),
        delegated_ok=True,
        # the injected fault schedule raises beyond the verb model by
        # design: 409 storms on any mutation, generic 5xx on anything
        extra_raises={
            verb: frozenset({"Conflict"})
            for verb in ("create", "delete", "create_or_get", "emit_event")
        },
        notes="every verb must pass a fault point; registry delegates",
    ),
    DuckSpec(
        file="machinery/cache.py",
        cls="CachedClient",
        role="informer read façade",
        explicit=frozenset({"get", "list"}),
        aux=frozenset(),
        delegated_ok=True,
        notes="cache-served reads; writes/watches/registry delegate",
    ),
    DuckSpec(
        file="machinery/client.py",
        cls="RemoteAPIServer",
        role="HTTP client",
        explicit=_ALL_VERBS,
        aux=frozenset({"applied_rv"}),
        allow_missing=_NO_INLINE,
        notes="no __getattr__: the wire surface is the whole surface; "
        "kind_version/state_digest have no wire endpoint",
    ),
    DuckSpec(
        file="machinery/client.py",
        cls="ReplicaFanout",
        role="read fanout over replica endpoints",
        explicit=frozenset(
            {"get", "list", "list_chunk", "watch"}
        )
        | frozenset(REGISTRY_VERBS),
        aux=frozenset({"applied_rv"}),
        delegated_ok=True,
        allow_missing=_NO_INLINE,
        notes="reads fan out with endpoint pinning; writes delegate to "
        "the first endpoint (the runner pairs this with ReadSplitAPI)",
    ),
    DuckSpec(
        file="machinery/replica.py",
        cls="ReadSplitAPI",
        role="read/write splitter",
        explicit=frozenset(
            {"get", "list", "list_chunk", "watch", "register_kind"}
        )
        | frozenset(AUX_SURFACE),
        delegated_ok=True,
        notes="reads replica-served, so the freshness/digest surface "
        "must report the READ arm; writes delegate to the leader",
    ),
    DuckSpec(
        file="machinery/partition.py",
        cls="PartitionRouter",
        role="namespace-sharded router",
        explicit=_ALL_VERBS | frozenset(AUX_SURFACE),
        delegated_ok=True,
        notes="routes by namespace owner; fleet aux surfaces compose "
        "per-partition values",
    ),
)


# ---------------------------------------------------------------------------
# method resolution (MRO-lite over the analyzed file set)


def _resolve_method(
    program, rel: str, cls: str, name: str, _depth: int = 0
) -> Optional[FuncInfo]:
    """The defining :class:`FuncInfo` for ``cls.name``, walking base
    classes through same-file definitions and ``from x import y``
    links. Bases outside the analyzed set simply don't resolve."""
    if _depth > 8:
        return None
    fn = program.functions.get(f"{rel}::{cls}.{name}")
    if fn is not None:
        return fn
    for base in program._bases.get(rel, {}).get(cls, ()):
        if base in program._bases.get(rel, {}):
            found = _resolve_method(program, rel, base, name, _depth + 1)
        else:
            imported = program._from_imports.get(rel, {}).get(base)
            if imported is None:
                continue
            found = _resolve_method(
                program, imported[0], imported[1], name, _depth + 1
            )
        if found is not None:
            return found
    return None


def _derives_from_reference(program, rel: str, cls: str, _depth: int = 0) -> bool:
    if _depth > 8:
        return False
    for base in program._bases.get(rel, {}).get(cls, ()):
        if base == REFERENCE_CLASS:
            return True
        if base in program._bases.get(rel, {}):
            if _derives_from_reference(program, rel, base, _depth + 1):
                return True
        else:
            imported = program._from_imports.get(rel, {}).get(base)
            if imported is not None and _derives_from_reference(
                program, imported[0], imported[1], _depth + 1
            ):
                return True
    return False


# ---------------------------------------------------------------------------
# signature conformance


def _compat_problems(
    ref: Sig, impl: Sig, allow_missing: frozenset[str]
) -> list[str]:
    """Why ``impl`` cannot serve every call shape the reference
    accepts (empty when conformant). A full ``*args, **kwargs``
    catch-all is call-compatible by construction — the blind
    pass-through check reports the forwarding hazard separately."""
    problems: list[str] = []
    impl_by_name = {p.name: p for p in impl.params + impl.kwonly}
    ref_names = [p.name for p in ref.params]
    for p in ref.params:
        got = impl_by_name.get(p.name)
        if got is None:
            if p.name in allow_missing or impl.kwarg:
                continue
            problems.append(f"drops reference parameter `{p.name}`")
        elif p.has_default and not got.has_default:
            problems.append(
                f"makes optional reference parameter `{p.name}` required"
            )
    order = [p.name for p in impl.params if p.name in set(ref_names)]
    expected = [n for n in ref_names if n in set(order)]
    if order != expected:
        problems.append(
            "reorders reference parameters "
            f"({', '.join(order)} vs {', '.join(expected)})"
        )
    for p in impl.params + impl.kwonly:
        if p.name not in set(ref_names) and not p.has_default:
            problems.append(f"adds required parameter `{p.name}`")
    return problems


def _blind_forward(fn: FuncInfo) -> Optional[ast.Call]:
    """The call forwarding this method's own ``*args``/``**kwargs``
    catch-all, when there is one. A catch-all that is merely absorbed
    (a replica's NotLeader-raising mutation stub) is not blind — it
    drops nothing silently; it refuses loudly."""
    a = fn.node.args
    vararg = a.vararg.arg if a.vararg is not None else None
    kwarg = a.kwarg.arg if a.kwarg is not None else None
    if vararg is None and kwarg is None:
        return None
    for node in ast.walk(fn.node):
        if not isinstance(node, ast.Call):
            continue
        star = any(
            isinstance(x, ast.Starred)
            and isinstance(x.value, ast.Name)
            and x.value.id == vararg
            for x in node.args
        )
        dstar = any(
            k.arg is None
            and isinstance(k.value, ast.Name)
            and k.value.id == kwarg
            for k in node.keywords
        )
        if star or dstar:
            return node
    return None


# ---------------------------------------------------------------------------
# error-mapping round trip (httpapi → wire → client)

HTTPAPI_FILE = "machinery/httpapi.py"
CLIENT_FILE = "machinery/client.py"


def _find_dict_assign(tree: ast.AST, name: str) -> Optional[ast.Assign]:
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Dict)
            and any(
                isinstance(t, ast.Name) and t.id == name
                for t in node.targets
            )
        ):
            return node
    return None


def _status_table(tree: ast.AST) -> Optional[list[tuple[str, int]]]:
    """``httpapi._STATUS`` as an ORDERED (class name, code) list —
    ``_err_status`` walks it with ``isinstance`` in dict order, so
    order is semantics."""
    node = _find_dict_assign(tree, "_STATUS")
    if node is None:
        return None
    out: list[tuple[str, int]] = []
    for k, v in zip(node.value.keys, node.value.values):
        chain = _attr_chain(k)
        if chain and isinstance(v, ast.Constant) and isinstance(v.value, int):
            out.append((chain[-1], v.value))
    return out


def _reason_table(tree: ast.AST) -> Optional[dict[str, str]]:
    node = _find_dict_assign(tree, "_REASON_TO_ERR")
    if node is None:
        return None
    out: dict[str, str] = {}
    for k, v in zip(node.value.keys, node.value.values):
        chain = _attr_chain(v)
        if isinstance(k, ast.Constant) and isinstance(k.value, str) and chain:
            out[k.value] = chain[-1]
    return out


def _code_table(tree: ast.AST) -> Optional[dict[int, str]]:
    node = _find_dict_assign(tree, "_ERR_BY_CODE")
    if node is None:
        return None
    out: dict[int, str] = {}
    for k, v in zip(node.value.keys, node.value.values):
        chain = _attr_chain(v)
        if isinstance(k, ast.Constant) and isinstance(k.value, int) and chain:
            out[k.value] = chain[-1]
    return out


# ---------------------------------------------------------------------------
# the rule


@register
class DuckConformanceRule(ProgramRule):
    """Every ``APIServer`` implementation conforms to the reference
    protocol: verb set, per-verb signatures, explicit auxiliary
    surface, no blind kwargs forwarding, declared error surface, and
    an httpapi↔client error mapping that composes to the identity."""

    id = "duck-conformance"
    description = (
        "APIServer duck drifting from the reference protocol "
        "(missing verb, incompatible signature, blind pass-through, "
        "aux gap, error-translation hole)"
    )

    def check_program(self, program) -> Iterator[Finding]:
        ref = reference_protocol(program)
        declared = {(s.file, s.cls) for s in DUCKS}
        for spec in DUCKS:
            yield from self._check_duck(program, spec, ref)
        yield from self._check_discovery(program, declared)
        yield from self._check_round_trip(program)
        yield from self._check_error_surface(program)

    # -- per-duck conformance ------------------------------------------------

    def _check_duck(self, program, spec: DuckSpec, ref) -> Iterator[Finding]:
        src = program.sources.get(spec.file)
        if src is None:
            return  # fixture/scoped run: this duck isn't in the set
        cls_node = next(
            (
                n
                for n in src.tree.body
                if isinstance(n, ast.ClassDef) and n.name == spec.cls
            ),
            None,
        )
        if cls_node is None:
            yield self.finding(
                src,
                src.tree,
                f"DUCKS declares {spec.cls} in {spec.file} but no such "
                "class exists — update the analysis.ducks inventory",
            )
            return
        has_getattr = (
            _resolve_method(program, spec.file, spec.cls, "__getattr__")
            is not None
        )
        for verb in CORE_VERBS + REGISTRY_VERBS + AUX_SURFACE:
            required_explicit = verb in spec.explicit or verb in spec.aux
            fn = _resolve_method(program, spec.file, spec.cls, verb)
            if fn is None:
                if required_explicit:
                    kind = (
                        "auxiliary surface" if verb in AUX_SURFACE else "verb"
                    )
                    yield self.finding(
                        src,
                        cls_node,
                        f"{spec.cls} ({spec.role}) has no explicit "
                        f"`{verb}` — the {kind} is part of its declared "
                        "duck contract and __getattr__ delegation does "
                        "not count (nothing to verify, wrapper "
                        "semantics silently bypassed)",
                    )
                elif not (spec.delegated_ok and has_getattr):
                    if verb in AUX_SURFACE and verb not in spec.aux:
                        continue  # deliberately absent (no wire surface)
                    yield self.finding(
                        src,
                        cls_node,
                        f"{spec.cls} ({spec.role}) serves no `{verb}` — "
                        "no explicit method, no inherited definition, "
                        "no __getattr__ delegation path",
                    )
                continue
            if fn.src.rel != spec.file:
                continue  # inherited from the reference: conformant
            if verb in AUX_SURFACE and verb not in spec.aux:
                continue
            sig = _sig_of(fn.node)
            allow = frozenset(spec.allow_missing.get(verb, frozenset()))
            for problem in _compat_problems(ref[verb], sig, allow):
                yield self.finding(
                    fn.src,
                    fn.node,
                    f"{spec.cls}.{verb}{sig.render()} {problem} — "
                    f"reference is {REFERENCE_CLASS}.{verb}"
                    f"{ref[verb].render()}",
                )
            fwd = _blind_forward(fn)
            if fwd is not None and verb not in AUX_SURFACE:
                yield self.finding(
                    fn.src,
                    fn.node,
                    f"{spec.cls}.{verb} forwards a blind *args/**kwargs "
                    "catch-all — a typo'd keyword silently mis-routes "
                    "instead of raising TypeError; spell out the "
                    f"reference signature {REFERENCE_CLASS}.{verb}"
                    f"{ref[verb].render()}",
                )

    # -- undeclared implementations ------------------------------------------

    def _check_discovery(self, program, declared) -> Iterator[Finding]:
        for src in program.sources.values():
            if src.section != "machinery":
                continue
            for node in src.tree.body:
                if not isinstance(node, ast.ClassDef):
                    continue
                if (
                    src.rel == REFERENCE_FILE
                    and node.name == REFERENCE_CLASS
                ):
                    continue
                if (src.rel, node.name) in declared:
                    continue
                own_verbs = {
                    n.name
                    for n in node.body
                    if isinstance(n, ast.FunctionDef)
                    and n.name in CORE_VERBS
                }
                if len(own_verbs) >= 3 or _derives_from_reference(
                    program, src.rel, node.name
                ):
                    yield self.finding(
                        src,
                        node,
                        f"{node.name} implements "
                        f"{len(own_verbs)} APIServer verbs "
                        f"({', '.join(sorted(own_verbs))}) but is not "
                        "declared in the analysis.ducks DUCKS "
                        "inventory — declare it with its delegation "
                        "policy so conformance is checked",
                    )

    # -- httpapi ↔ client round trip -----------------------------------------

    def _check_round_trip(self, program) -> Iterator[Finding]:
        httpapi = program.sources.get(HTTPAPI_FILE)
        client = program.sources.get(CLIENT_FILE)
        if httpapi is None or client is None:
            return
        status = _status_table(httpapi.tree)
        reason = _reason_table(client.tree)
        by_code = _code_table(client.tree)
        if status is None or reason is None or by_code is None:
            return
        reason_node = _find_dict_assign(client.tree, "_REASON_TO_ERR")
        hierarchy = mine_hierarchy(program)

        def ancestors(err: str) -> set[str]:
            out = {err}
            cur: Optional[str] = err
            while cur is not None:
                cur = hierarchy.get(cur)
                if cur is not None:
                    out.add(cur)
            return out

        for key, klass in reason.items():
            if key != klass:
                yield self.finding(
                    client,
                    reason_node,
                    f"_REASON_TO_ERR maps reason {key!r} to {klass} — "
                    "the server sets Status.reason to the error class "
                    "name, so key and class must agree",
                )
        wire_classes = {
            n.name
            for n in (program.sources.get(REFERENCE_FILE).tree.body
                      if REFERENCE_FILE in program.sources else ())
            if isinstance(n, ast.ClassDef) and n.name in hierarchy
        }
        for err in sorted(hierarchy):
            if err == "APIError":
                continue
            anc = ancestors(err)
            code = next((c for k, c in status if k in anc), 500)
            mapped = reason.get(err) or by_code.get(code) or "APIError"
            if mapped == err:
                continue
            if err in wire_classes or not wire_classes:
                yield self.finding(
                    client,
                    reason_node,
                    f"round trip is not the identity for {err}: the "
                    f"server emits HTTP {code} with reason {err!r}, "
                    f"the client maps it back to {mapped} — add the "
                    "reason entry (or fix the code table) so the "
                    "caller gets the exception the server raised",
                )
            elif mapped == "APIError" or mapped not in anc:
                yield self.finding(
                    client,
                    reason_node,
                    f"{err} degrades to {mapped} over the wire (HTTP "
                    f"{code}, reason {err!r} unknown to the client) — "
                    "an ad-hoc error class may widen to an ancestor, "
                    "but never sideways or to bare APIError; add a "
                    "reason entry or derive it from the class the "
                    "client should see",
                )

    # -- declared error surface ----------------------------------------------

    def _check_error_surface(self, program) -> Iterator[Finding]:
        if REFERENCE_FILE not in program.sources:
            return
        from odh_kubeflow_tpu.analysis.exceptions import ExceptionAnalysis

        ea = ExceptionAnalysis.of(program)
        hierarchy = ea.hierarchy
        specs = DUCKS + (
            DuckSpec(
                file=REFERENCE_FILE,
                cls=REFERENCE_CLASS,
                role="reference",
                explicit=_ALL_VERBS,
            ),
        )
        for spec in specs:
            if spec.file not in program.sources:
                continue
            for verb in sorted(spec.explicit & set(VERB_RAISES)):
                fn = program.functions.get(f"{spec.file}::{spec.cls}.{verb}")
                if fn is None:
                    continue
                allowed = (
                    VERB_RAISES[verb]
                    | frozenset(spec.extra_raises.get(verb, frozenset()))
                    | {"APIError"}
                )
                res = ea.result_for(fn.qual)
                seen: set[str] = set()
                for err, site, _can, esc in res.sites:
                    if not esc or err not in hierarchy or err in seen:
                        continue
                    seen.add(err)
                    if ea.catches(allowed, err):
                        continue
                    yield self.finding(
                        fn.src,
                        fn.node,
                        f"{spec.cls}.{verb} can raise {err} "
                        f"({render_chain(site.chain)}) which is outside "
                        f"the declared verb model VERB_RAISES[{verb!r}] "
                        "and this duck's declared extras — extend the "
                        "model or the DUCKS declaration so exception-"
                        "flow reasoning stays sound",
                    )
