"""The platform's graftlint rules.

Each rule encodes one invariant the control plane relies on but the
language cannot enforce. Rules are deliberately conservative: a rule
that cries wolf gets suppressed wholesale and protects nothing, so
every heuristic here is tuned to flag the shapes that are bugs in
THIS codebase (see each rule's docstring for the exact contract).

Add a rule by subclassing :class:`graftlint.Rule`, decorating with
``@register``, and giving it a fixture-proven true positive in
``tests/test_analysis.py`` — the whole-package gate keeps the tree
clean against it from then on.
"""

from __future__ import annotations

import ast
from typing import Any, Iterator, Optional

from odh_kubeflow_tpu.analysis import callgraph
from odh_kubeflow_tpu.analysis.graftlint import (
    Finding,
    ProgramRule,
    Rule,
    SourceFile,
    register,
)
from odh_kubeflow_tpu.utils.prometheus import metric_name_violations

# kinds whose unselective cluster-wide list is always a smell on a hot
# path (they all have namespace buckets and/or platform indexers)
INDEXABLE_KINDS = frozenset(
    {
        "Pod",
        "StatefulSet",
        "Deployment",
        "Service",
        "Event",
        "Node",
        "Notebook",
        "PersistentVolumeClaim",
        "ResourceQuota",
        "Secret",
    }
)

# dict/list mutators that modify in place (FrozenDict/FrozenList raise
# on every one of these)
_MUTATORS = frozenset(
    {
        "append",
        "extend",
        "insert",
        "pop",
        "popitem",
        "clear",
        "update",
        "remove",
        "sort",
        "reverse",
        "setdefault",
    }
)


# one attribute-chain walker for the per-file and whole-program
# analyses (``self.api.get`` → ["self", "api", "get"])
_attr_chain = callgraph._attr_chain


def _root_name(node: ast.AST) -> Optional[str]:
    """The base Name of a subscript/attribute access path
    (``obj["a"]["b"]`` → "obj"), or None."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


# ---------------------------------------------------------------------------
# uncached-list


@register
class UncachedListRule(Rule):
    """AST-accurate replacement for the old grep scan in
    ``tests/test_cache.py``: a cluster-wide ``.list("<Kind>")`` of an
    indexable kind — no namespace, no selector, no field match — on a
    hot path scans and freezes/copies the whole cluster per call. Use
    the namespaced/selector/indexed read forms, or mark a genuinely
    global cold/snapshot pass with ``# uncached-ok: <reason>``."""

    id = "uncached-list"
    description = (
        "bare cluster-wide list() of an indexable kind on a hot path"
    )
    dirs = ("controllers", "web", "scheduling", "webhooks", "sessions",
            "warmup")
    # the partition router's merge and move paths issue list() calls
    # themselves; an unselective cluster-wide scan of an indexable
    # kind there multiplies by the partition count
    files = ("machinery/partition.py",)

    _SELECTIVE_KWARGS = ("namespace", "label_selector", "field_matches")

    def check(self, src: SourceFile) -> Iterator[Finding]:
        for node in ast.walk(src.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "list"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and node.args[0].value in INDEXABLE_KINDS
            ):
                continue
            kind = node.args[0].value
            selective = len(node.args) > 1 or any(
                kw.arg in self._SELECTIVE_KWARGS
                and not (
                    isinstance(kw.value, ast.Constant)
                    and kw.value.value is None
                )
                for kw in node.keywords
            )
            if selective:
                continue
            # legacy marker continuity: `# uncached-ok: <reason>` on
            # any line of the call keeps working
            span = range(node.lineno, (node.end_lineno or node.lineno) + 1)
            if any("uncached-ok" in src.line(n) for n in span):
                continue
            yield self.finding(
                src,
                node,
                f"cluster-wide list of indexable kind {kind!r}; use a "
                "namespaced/selector/indexed read or annotate with "
                "`# uncached-ok: <reason>`",
            )


# ---------------------------------------------------------------------------
# unbounded-list


@register
class UnboundedListRule(Rule):
    """A list call on a serving path — web/HTTP handlers and the
    informer's prime/resync — that names a kind but carries no
    ``limit`` builds one response-sized payload for the WHOLE
    collection: at fleet size (25k+ notebooks) that is a multi-MB
    serialize-and-ship per request. Such calls must paginate
    (``limit=`` / ``list_chunk`` walks) or be explicitly marked
    ``# unbounded-ok: <reason>`` (the standing reason in web/ is a
    cache-served zero-copy read — the informer mirror hands out shared
    references, no payload is built). Scope: ``web/`` plus the
    informer cache's own prime path."""

    id = "unbounded-list"
    description = (
        "list of a kind without a limit on a serving/prime path "
        "(fleet-sized payload)"
    )
    dirs = ("web",)
    # beyond web/: the informer prime path, the read-replica serving
    # tier, and the partition router's scatter-gather merge — a
    # fleet-sized unpaginated list there defeats the whole point of
    # scaling the read path out. (Base Rule.applies unions files +
    # dirs.)
    files = (
        "machinery/cache.py",
        "machinery/replica.py",
        "machinery/partition.py",
    )

    _LISTERS = frozenset({"api", "client", "server", "store", "backend"})

    def check(self, src: SourceFile) -> Iterator[Finding]:
        for node in ast.walk(src.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "list"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                continue
            chain = _attr_chain(node.func)
            if not any(part in self._LISTERS for part in chain[:-1]):
                continue
            if any(
                kw.arg == "limit"
                and not (
                    isinstance(kw.value, ast.Constant)
                    and kw.value.value is None
                )
                for kw in node.keywords
            ):
                continue
            span = range(node.lineno, (node.end_lineno or node.lineno) + 1)
            if any("unbounded-ok" in src.line(n) for n in span):
                continue
            yield self.finding(
                src,
                node,
                f"list of {node.args[0].value!r} without a limit on a "
                "serving/prime path; paginate (limit= / list_chunk) or "
                "annotate with `# unbounded-ok: <reason>`",
            )


# ---------------------------------------------------------------------------
# swallowed-exception


@register
class SwallowedExceptionRule(Rule):
    """A bare ``except:`` or ``except Exception:`` whose body neither
    re-raises nor leaves any trace (log, Event, metric, or real
    handling) turns every failure in controllers/webhooks/scheduling/
    machinery into silence — reconcile loops quietly stop converging.
    Handlers that do anything observable (a call, a raise, a
    conditional) pass; only trivially-swallowing bodies (``pass``,
    ``continue``, ``return <constant>``) are flagged."""

    id = "swallowed-exception"
    # (sessions/ included: a swallowed snapshot failure silently loses
    # a user's kernel)
    description = "broad except handler that silently discards the error"
    dirs = ("controllers", "webhooks", "scheduling", "machinery", "sessions")

    _BROAD = ("Exception", "BaseException")

    def _is_broad(self, handler: ast.ExceptHandler) -> bool:
        t = handler.type
        if t is None:
            return True
        if isinstance(t, ast.Name):
            return t.id in self._BROAD
        if isinstance(t, ast.Tuple):
            return any(
                isinstance(e, ast.Name) and e.id in self._BROAD for e in t.elts
            )
        return False

    def _trivial(self, body: list[ast.stmt]) -> bool:
        for stmt in body:
            if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
                continue
            if isinstance(stmt, ast.Expr) and isinstance(
                stmt.value, ast.Constant
            ):
                continue  # docstring / ellipsis
            if isinstance(stmt, ast.Return):
                v = stmt.value
                if v is None or isinstance(v, (ast.Constant, ast.Name)):
                    continue
                if isinstance(v, (ast.List, ast.Dict, ast.Tuple, ast.Set)) and not getattr(
                    v, "elts", getattr(v, "keys", ())
                ):
                    continue  # return [] / {} / ()
            return False
        return True

    def check(self, src: SourceFile) -> Iterator[Finding]:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if self._is_broad(node) and self._trivial(node.body):
                yield self.finding(
                    src,
                    node,
                    "broad except swallows the error with no log/Event/"
                    "metric; handle it, narrow the exception type, or "
                    "annotate with a reason",
                )


# ---------------------------------------------------------------------------
# blocking-under-lock (static half; analysis/sanitizer.py is the
# runtime half)


@register
class BlockingUnderLockRule(Rule):
    """``time.sleep``, HTTP client calls, and blocking queue/watch
    ``get(timeout=…)`` inside a ``with <lock>:`` block stall every
    other thread contending for that lock — the exact shape of the
    PR 1 ``_RateLimiter`` bug. ``Condition.wait`` is exempt (it
    releases the lock while blocked)."""

    id = "blocking-under-lock"
    description = "blocking call (sleep/HTTP/watch-get) while holding a lock"
    files = (
        "machinery/store.py",
        "machinery/cache.py",
        "machinery/client.py",
        "controllers/runtime.py",
        "scheduling/scheduler.py",
        "scheduling/queue.py",
        # checkpoint IO (snapshot HTTP hooks, orbax writes) must never
        # run under store/cache locks — suspend would stall every reader
        "sessions/manager.py",
        "sessions/checkpoint.py",
        # grew concurrent in PRs 7/10: the WAL's group-commit io_lock
        # and the event-loop serving tier
        "machinery/wal.py",
        "machinery/eventloop.py",
        # singleflight: the compile-cache inflight lock must only guard
        # the table — compiles and artifact IO happen outside it
        "warmup/compilecache.py",
        "warmup/pool.py",
        # the replication pull loop blocks on sockets by design — but
        # NEVER under the replica store's lock (rv-pinned reads park on
        # a Condition there, which is the one exempt form)
        "machinery/replica.py",
        # the partition router's merged-watch pump lock serializes leg
        # drains on the WRITE path — a blocking get under it would
        # stall every mutator of every partition at once
        "machinery/partition.py",
    )

    # one lock vocabulary for the per-file and whole-program analyses
    # (callgraph.is_lockish uses the same tuple)
    _LOCKISH = callgraph.LOCKISH_MARKERS
    _WAITS = frozenset({"wait", "wait_for"})

    def _is_lockish(self, expr: ast.AST) -> bool:
        chain = _attr_chain(expr)
        if not chain:
            return False
        terminal = chain[-1].lower()
        return any(marker in terminal for marker in self._LOCKISH)

    def _blocking_call(self, call: ast.Call) -> Optional[str]:
        # ONE blocking-leaf vocabulary for the per-file and
        # through-calls analyses (callgraph.blocking_leaf): sleep,
        # fsync, socket/HTTP IO, blocking get(timeout=…)
        desc = callgraph.blocking_leaf(call)
        if desc == "os.fsync":
            chain = _attr_chain(call.func)
            if chain and chain[0] == "self":
                # self.io.fsync(f) — a method indirection (the WAL's
                # FileIO), which the interprocedural rule chases
                return None
        return desc

    def _iter_immediate(self, node: ast.AST) -> Iterator[ast.AST]:
        """Descendants that execute inside the critical section —
        nested defs/lambdas run later, outside the lock, and are
        pruned (``ast.walk`` would descend into them)."""
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            yield child
            yield from self._iter_immediate(child)

    def _scan_body(
        self, src: SourceFile, body: list[ast.stmt]
    ) -> Iterator[Finding]:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # defined under the lock, executed later
            for node in [stmt, *self._iter_immediate(stmt)]:
                if not isinstance(node, ast.Call):
                    continue
                chain = _attr_chain(node.func)
                if chain and chain[-1] in self._WAITS:
                    continue
                what = self._blocking_call(node)
                if what:
                    yield self.finding(
                        src,
                        node,
                        f"{what} while holding a lock; move the blocking "
                        "call outside the critical section",
                    )

    def check(self, src: SourceFile) -> Iterator[Finding]:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.With):
                continue
            if any(
                self._is_lockish(item.context_expr) for item in node.items
            ):
                yield from self._scan_body(src, node.body)


# ---------------------------------------------------------------------------
# retry-without-backoff


@register
class RetryWithoutBackoffRule(Rule):
    """A hand-rolled retry loop — ``while True`` or fixed-count
    ``for … in range(n)`` around an API call, swallowing the error to
    go around again with a constant sleep (or none) — synchronises
    every failing client into a thundering herd against the recovering
    server. Route retries through ``machinery.backoff`` (jittered
    exponential delays, capped attempts, Retry-After honoured). A loop
    that references the backoff helper, sleeps a *computed* delay, or
    whose except handler exits the loop (return/raise/break) is not a
    retry loop and passes."""

    id = "retry-without-backoff"
    description = (
        "bare retry loop around API calls without the shared backoff "
        "helper"
    )
    dirs = ("machinery", "controllers")

    _API_TERMINALS = frozenset(
        {
            "create",
            "update",
            "update_status",
            "patch",
            "delete",
            "list",
            "watch",
            "urlopen",
            "_request",
            "_do_request",
            "_call",
            "_query",
            "emit_event",
            "create_or_get",
        }
    )
    _BACKOFFISH_CALLS = frozenset({"retry", "next_delay", "delays"})

    def _is_retry_loop_header(self, node: ast.AST) -> bool:
        if isinstance(node, ast.While):
            return isinstance(node.test, ast.Constant) and bool(
                node.test.value
            )
        if isinstance(node, ast.For):
            it = node.iter
            return (
                isinstance(it, ast.Call)
                and isinstance(it.func, ast.Name)
                and it.func.id == "range"
            )
        return False

    def _iter_live(self, node: ast.AST) -> Iterator[ast.AST]:
        """Descendants executing inside the loop iteration — nested
        defs/lambdas run later and are pruned."""
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            yield child
            yield from self._iter_live(child)

    def _uses_backoff(self, loop: ast.AST) -> bool:
        for node in [loop, *self._iter_live(loop)]:
            if isinstance(node, ast.Call):
                chain = _attr_chain(node.func)
                if chain and (
                    chain[-1] in self._BACKOFFISH_CALLS
                    or any("backoff" in c.lower() for c in chain)
                ):
                    return True
            if isinstance(node, ast.Name) and "backoff" in node.id.lower():
                return True
            if isinstance(node, ast.Attribute) and (
                "backoff" in node.attr.lower()
            ):
                return True
        return False

    def _handler_retries(self, handler: ast.ExceptHandler) -> bool:
        """Whether the handler lets the loop go around again: a body
        ending in return/raise/break exits instead of retrying."""
        if not handler.body:
            return True
        last = handler.body[-1]
        return not isinstance(last, (ast.Return, ast.Raise, ast.Break))

    def _api_retry_try(self, loop: ast.AST) -> Optional[ast.Try]:
        for node in self._iter_live(loop):
            if not isinstance(node, ast.Try):
                continue
            in_try = [
                n
                for stmt in node.body
                for n in [stmt, *self._iter_live(stmt)]
            ]
            calls_api = any(
                isinstance(n, ast.Call)
                and (chain := _attr_chain(n.func))
                and chain[-1] in self._API_TERMINALS
                and len(chain) > 1
                for n in in_try
            )
            if calls_api and any(
                self._handler_retries(h) for h in node.handlers
            ):
                return node
        return None

    def _sleeps_constant_or_nothing(self, loop: ast.AST) -> bool:
        for node in self._iter_live(loop):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            if not chain or chain[-1] != "sleep":
                continue
            if not all(isinstance(a, ast.Constant) for a in node.args):
                return False  # computed delay: some pacing policy exists
        return True  # constant sleeps and no sleep at all both flag

    def check(self, src: SourceFile) -> Iterator[Finding]:
        for node in ast.walk(src.tree):
            if not self._is_retry_loop_header(node):
                continue
            if self._uses_backoff(node):
                continue
            if self._api_retry_try(node) is None:
                continue
            if not self._sleeps_constant_or_nothing(node):
                continue
            yield self.finding(
                src,
                node,
                "retry loop around an API call with constant (or no) "
                "sleep; use machinery.backoff.retry()/next_delay() for "
                "jittered, capped retries",
            )


# ---------------------------------------------------------------------------
# unbudgeted-retry


@register
class UnbudgetedRetryRule(Rule):
    """A retry site on the API path that ignores the process-wide
    retry budget (``machinery.overload.shared_budget``) amplifies load
    exactly when the fleet can least afford it: every stacked layer
    multiplies attempts-per-logical-request during a brownout — the
    retry-storm half of a metastable failure. Two shapes flag in
    ``machinery/`` and ``web/``: a ``backoff.retry(...)`` call that
    does not thread a ``budget=``, and a hand-rolled reconnect loop
    pacing itself with ``backoff.next_delay`` that consults neither a
    retry budget nor a circuit breaker anywhere in its body. The
    escape hatch is ``# budget-ok: <reason>`` on a line of the flagged
    call, for retries that genuinely must not be budget-bound: loops
    that MUST go forever (the replication stream), purely local
    optimistic-concurrency merges, and third-party-API etag races."""

    id = "unbudgeted-retry"
    description = (
        "API-path retry without the shared overload retry budget "
        "(thread budget= or justify with # budget-ok)"
    )
    dirs = ("machinery", "web")

    _GUARD_TOKENS = ("budget", "breaker")

    def _escaped(self, src: SourceFile, node: ast.AST) -> bool:
        last = getattr(node, "end_lineno", None) or node.lineno
        return any(
            "budget-ok" in src.line(i)
            for i in range(node.lineno, last + 1)
        )

    def _backoff_call(self, node: ast.AST, name: str) -> bool:
        if not isinstance(node, ast.Call):
            return False
        chain = _attr_chain(node.func)
        return bool(
            chain
            and chain[-1] == name
            and any("backoff" in c.lower() for c in chain[:-1])
        )

    def _iter_live(self, node: ast.AST, stop_at_loops: bool = False):
        """Descendants executing in ``node``'s own iteration: nested
        defs/lambdas run later and are pruned; with ``stop_at_loops``
        nested loops are pruned too (innermost-loop attribution)."""
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            if stop_at_loops and isinstance(child, (ast.While, ast.For)):
                continue
            yield child
            yield from self._iter_live(child, stop_at_loops)

    def _loop_guarded(self, loop: ast.AST) -> bool:
        """Whether the loop's live body consults a budget or breaker —
        any name/attribute carrying either token (``self._breaker``,
        ``budget.try_spend()``…)."""
        for node in self._iter_live(loop):
            if isinstance(node, ast.Name) and any(
                t in node.id.lower() for t in self._GUARD_TOKENS
            ):
                return True
            if isinstance(node, ast.Attribute) and any(
                t in node.attr.lower() for t in self._GUARD_TOKENS
            ):
                return True
            if isinstance(node, ast.keyword) and node.arg and any(
                t in node.arg.lower() for t in self._GUARD_TOKENS
            ):
                return True
        return False

    def check(self, src: SourceFile) -> Iterator[Finding]:
        for node in ast.walk(src.tree):
            if self._backoff_call(node, "retry"):
                has_budget = any(
                    kw.arg == "budget" for kw in node.keywords
                )
                if not has_budget and not self._escaped(src, node):
                    yield self.finding(
                        src,
                        node,
                        "backoff.retry without a retry budget: thread "
                        "budget=overload.shared_budget() (or a shared "
                        "RetryBudget) so stacked retry layers share one "
                        "amplification bound, or justify with "
                        "# budget-ok: <reason>",
                    )
            if isinstance(node, (ast.While, ast.For)):
                calls = [
                    n
                    for n in self._iter_live(node, stop_at_loops=True)
                    if self._backoff_call(n, "next_delay")
                ]
                if not calls or self._loop_guarded(node):
                    continue
                for call in calls:
                    if not self._escaped(src, call):
                        yield self.finding(
                            src,
                            call,
                            "reconnect loop paced by backoff.next_delay "
                            "consults neither a retry budget nor a "
                            "circuit breaker; gate it (see client.py's "
                            "watch pump) or justify with "
                            "# budget-ok: <reason>",
                        )


# ---------------------------------------------------------------------------
# unfenced-write


@register
class UnfencedWriteRule(Rule):
    """A module that participates in leader election or shard
    membership (imports ``machinery.leader`` / constructs a
    ``LeaderElector``/``ShardMembership``) is a controller-path
    writer, and every store write it issues must carry its lease
    epoch: either lexically inside ``with <elector>.fence():`` /
    ``with fenced(...):``, or through a receiver whose name marks it
    fenced (``fenced_api.update(...)``). A raw write in such a module
    is exactly the leader-election TOCTOU the store's fencing-token
    check closes — a deposed holder completing an in-flight write
    after losing the lease. Components that get their fence from the
    Manager (``fence_fn``) never import leader machinery and are out
    of scope, same scope discipline as ``retry-without-backoff``.
    Genuinely epoch-free writes (boot-time registration, test
    scaffolding) are annotated ``# unfenced-ok: <reason>``."""

    id = "unfenced-write"
    description = (
        "store write in a leader-electing module outside a fencing "
        "context"
    )
    dirs = ("controllers", "machinery", "scheduling", "sessions", "web",
            "warmup")

    # the fencing helpers themselves (and the runner, which only wires
    # electors into the Manager) are the mechanism, not consumers
    _EXEMPT_FILES = frozenset({"machinery/leader.py"})

    _WRITE_TERMINALS = frozenset(
        {
            "create",
            "update",
            "update_status",
            "patch",
            "delete",
            "emit_event",
            "create_or_get",
        }
    )
    _WRITERISH = frozenset({"api", "client", "store"})
    _LEADER_NAMES = ("LeaderElector", "ShardMembership")

    def _module_uses_leader(self, tree: ast.AST) -> bool:
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod.endswith("machinery.leader") or mod == "leader":
                    return True
            elif isinstance(node, ast.Import):
                if any(
                    a.name.endswith("machinery.leader") for a in node.names
                ):
                    return True
            elif isinstance(node, ast.Name) and node.id in self._LEADER_NAMES:
                return True
        return False

    def _is_fence_ctx(self, expr: ast.AST) -> bool:
        """``<elector>.fence()`` / ``fenced(...)`` / ``leader.fenced(…)``
        — any call whose terminal name is fence-ish."""
        if not isinstance(expr, ast.Call):
            return False
        chain = _attr_chain(expr.func)
        return bool(chain) and chain[-1] in ("fence", "fenced")

    def _is_raw_write(self, call: ast.Call) -> bool:
        chain = _attr_chain(call.func)
        if len(chain) < 2 or chain[-1] not in self._WRITE_TERMINALS:
            return False
        receiver = chain[:-1]
        if any("fenced" in part.lower() for part in receiver):
            return False  # a fence-carrying handle
        return any(part in self._WRITERISH for part in receiver)

    def _visit(
        self, src: SourceFile, node: ast.AST, fenced: bool
    ) -> Iterator[Finding]:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a function body starts unfenced: the fence is a dynamic
            # contextvar, and a def's call site is unknown lexically
            for child in node.body:
                yield from self._visit(src, child, False)
            return
        if isinstance(node, ast.Lambda):
            # conservatively skipped: `retry(lambda: api.update(x))`
            # inside a fence block runs while the fence is installed
            return
        if isinstance(node, ast.With):
            inner = fenced or any(
                self._is_fence_ctx(item.context_expr) for item in node.items
            )
            for child in node.body:
                yield from self._visit(src, child, inner)
            return
        if (
            not fenced
            and isinstance(node, ast.Call)
            and self._is_raw_write(node)
        ):
            span = range(node.lineno, (node.end_lineno or node.lineno) + 1)
            if not any("unfenced-ok" in src.line(i) for i in span):
                yield self.finding(
                    src,
                    node,
                    "store write in a leader-electing module without "
                    "a fencing context; wrap in `with elector.fence():`"
                    " (or route through a fenced handle), or annotate "
                    "with `# unfenced-ok: <reason>`",
                )
        for child in ast.iter_child_nodes(node):
            yield from self._visit(src, child, fenced)

    def check(self, src: SourceFile) -> Iterator[Finding]:
        if src.rel in self._EXEMPT_FILES:
            return
        if not self._module_uses_leader(src.tree):
            return
        for child in src.tree.body:
            yield from self._visit(src, child, False)


# ---------------------------------------------------------------------------
# hot-path-json-dumps


@register
class HotPathJsonDumpsRule(Rule):
    """Every JSON response the web/API tier emits must flow through
    ``machinery.serialize.dumps`` (C-speed, byte-identical to
    ``json.dumps``) or the serialized-bytes cache — a direct
    ``json.dumps`` on a serving path silently reverts that response to
    an interpreter tree walk per hit, exactly the cost the native
    serializer removed. Scope is the serving tiers (``web/``,
    ``machinery/``); ``machinery/serialize.py`` itself is exempt (it
    IS the fallback). Genuinely cold or outbound sites (client request
    bodies, cloud-API payloads, bench baselines) are marked
    ``# dumps-ok: <reason>`` on any line of the call."""

    id = "hot-path-json-dumps"
    description = (
        "direct json.dumps on a web/machinery serving path (bypasses "
        "the native serializer)"
    )
    dirs = ("web", "machinery")

    _EXEMPT_FILES = frozenset({"machinery/serialize.py"})

    @staticmethod
    def _json_module_names(tree: ast.AST) -> frozenset[str]:
        """Local names bound to the ``json`` module (``import json``,
        ``import json as _json``) — so a same-named ``dumps`` method on
        some other object is never mistaken for the stdlib encoder."""
        names = {"json"}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "json":
                        names.add(a.asname or a.name)
        return frozenset(names)

    @staticmethod
    def _bare_dumps_names(tree: ast.AST) -> frozenset[str]:
        """Local names bound to ``json.dumps`` via
        ``from json import dumps [as …]``."""
        names = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module == "json":
                for a in node.names:
                    if a.name == "dumps":
                        names.add(a.asname or a.name)
        return frozenset(names)

    def check(self, src: SourceFile) -> Iterator[Finding]:
        if src.rel in self._EXEMPT_FILES:
            return
        json_names = self._json_module_names(src.tree)
        bare_names = self._bare_dumps_names(src.tree)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            hit = (
                isinstance(func, ast.Attribute)
                and func.attr == "dumps"
                and isinstance(func.value, ast.Name)
                and func.value.id in json_names
            ) or (
                isinstance(func, ast.Name) and func.id in bare_names
            )
            if not hit:
                continue
            span = range(node.lineno, (node.end_lineno or node.lineno) + 1)
            if any("dumps-ok" in src.line(n) for n in span):
                continue
            yield self.finding(
                src,
                node,
                "direct json.dumps on a serving path; route through "
                "machinery.serialize.dumps (or the serialized-bytes "
                "cache), or annotate with `# dumps-ok: <reason>`",
            )


# ---------------------------------------------------------------------------
# span-in-hot-loop


@register
class SpanInHotLoopRule(Rule):
    """Span recording is cheap per span but NOT free-per-million:
    every ``tracing.span(...)`` allocates ids and lands a record in
    the collector ring. Creating one inside a per-watch-event or
    per-page loop in ``machinery/`` (the event pumps, list walkers,
    and serving paths everything else rides on) turns a single
    request into an unbounded span fan-out and flushes the ring of
    the traces an operator actually wants. Span the operation, not
    the iteration — or mark a deliberately-traced loop body with
    ``# span-ok: <reason>``. Nested function bodies inside the loop
    are skipped (they execute on their own schedule, not
    per-iteration)."""

    id = "span-in-hot-loop"
    description = (
        "tracing.span() created inside a per-event/per-page loop in "
        "machinery/"
    )
    dirs = ("machinery",)

    _SPAN_ATTRS = frozenset({"span", "child_span"})

    @staticmethod
    def _loop_body_nodes(loop: ast.AST) -> Iterator[ast.AST]:
        """Walk a loop's body, pruning nested function/lambda scopes
        (their bodies don't run per-iteration)."""
        stack = list(loop.body) + list(getattr(loop, "orelse", []))
        while stack:
            node = stack.pop()
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def _is_span_call(self, node: ast.AST, bare_names: frozenset[str]) -> bool:
        if not isinstance(node, ast.Call):
            return False
        func = node.func
        if (
            isinstance(func, ast.Attribute)
            and func.attr in self._SPAN_ATTRS
            and isinstance(func.value, ast.Name)
            and func.value.id == "tracing"
        ):
            return True
        return isinstance(func, ast.Name) and func.id in bare_names

    @staticmethod
    def _bare_span_names(tree: ast.AST) -> frozenset[str]:
        """Names bound via ``from …tracing import span [as …]``."""
        names = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and (
                node.module or ""
            ).endswith("tracing"):
                for a in node.names:
                    if a.name in ("span", "child_span"):
                        names.add(a.asname or a.name)
        return frozenset(names)

    def check(self, src: SourceFile) -> Iterator[Finding]:
        bare = self._bare_span_names(src.tree)
        for loop in ast.walk(src.tree):
            if not isinstance(loop, (ast.For, ast.While, ast.AsyncFor)):
                continue
            for node in self._loop_body_nodes(loop):
                if not self._is_span_call(node, bare):
                    continue
                span_lines = range(
                    node.lineno, (node.end_lineno or node.lineno) + 1
                )
                if any("span-ok" in src.line(n) for n in span_lines):
                    continue
                yield self.finding(
                    src,
                    node,
                    "span created inside a loop on a machinery hot "
                    "path; span the operation outside the loop or "
                    "annotate with `# span-ok: <reason>`",
                )


# ---------------------------------------------------------------------------
# metric-naming


def metric_definition_sites(
    root: Optional[str] = None,
) -> list[tuple[str, str, str, int]]:
    """Every statically visible metric definition in the package:
    ``(rel_path, type, name, lineno)`` for ``registry.counter/gauge/
    histogram("literal", …)`` calls and direct ``Counter/Gauge/
    Histogram("literal", …)`` constructions. Exposed so tests can
    assert the scan still sees the platform's metric surface (an empty
    scan means the detector broke, not that the tree is clean)."""
    from odh_kubeflow_tpu.analysis.graftlint import iter_sources

    out = []
    for src in iter_sources(root):
        for typ, name, node in _iter_metric_defs(src.tree):
            out.append((src.rel, typ, name, node.lineno))
    return out


_FACTORY_METHODS = {  # registry.counter("name", …) — the common form
    "counter": "counter",
    "gauge": "gauge",
    "histogram": "histogram",
}
_CONSTRUCTORS = {  # Counter("name", …) — only when provably prometheus's
    "Counter": "counter",
    "Gauge": "gauge",
    "Histogram": "histogram",
}


def _prometheus_constructor_aliases(tree: ast.AST) -> dict[str, str]:
    """Local names bound to utils.prometheus's Counter/Gauge/Histogram
    via ``from … prometheus import`` — so ``collections.Counter("x")``
    and other same-named classes are never mistaken for metrics."""
    aliases: dict[str, str] = {}
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.ImportFrom)
            and node.module
            and node.module.split(".")[-1] == "prometheus"
        ):
            for a in node.names:
                if a.name in _CONSTRUCTORS:
                    aliases[a.asname or a.name] = _CONSTRUCTORS[a.name]
    return aliases


def _iter_metric_defs(tree: ast.AST) -> Iterator[tuple[str, str, ast.Call]]:
    ctor_aliases = _prometheus_constructor_aliases(tree)
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and node.args):
            continue
        func = node.func
        typ = None
        if isinstance(func, ast.Attribute):
            typ = _FACTORY_METHODS.get(func.attr)
            if typ is None and func.attr in _CONSTRUCTORS:
                # prometheus.Counter(…) / <…>.prometheus.Counter(…)
                chain = _attr_chain(func)
                if "prometheus" in chain[:-1]:
                    typ = _CONSTRUCTORS[func.attr]
        elif isinstance(func, ast.Name):
            typ = ctor_aliases.get(func.id)
        if typ is None:
            continue
        first = node.args[0]
        if not (isinstance(first, ast.Constant) and isinstance(first.value, str)):
            continue
        yield typ, first.value, node


@register
class MetricNamingRule(Rule):
    """The registry conventions (``utils.prometheus.
    metric_name_violations``) checked statically at every definition
    site, so a misnamed metric fails lint before any process registers
    it. Literal ``labelnames`` tuples are checked too."""

    id = "metric-naming"
    description = "metric definition violating Prometheus naming conventions"

    def check(self, src: SourceFile) -> Iterator[Finding]:
        for typ, name, node in _iter_metric_defs(src.tree):
            labelnames: list[str] = []
            for kw in node.keywords:
                if kw.arg == "labelnames" and isinstance(
                    kw.value, (ast.Tuple, ast.List)
                ):
                    labelnames = [
                        e.value
                        for e in kw.value.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)
                    ]
            for violation in metric_name_violations(name, typ, labelnames):
                yield self.finding(src, node, violation)


# ---------------------------------------------------------------------------
# frozen-mutation


_READ_METHODS = frozenset({"get", "list", "by_index", "index_buckets"})
_CLIENTISH = frozenset({"api", "client", "cache", "informer", "store"})


def _is_cache_read(call: ast.Call) -> bool:
    """A call that returns shared frozen objects when the platform
    runs cache-fronted: ``<…>.api/client/cache.get/list/by_index/
    index_buckets(…)`` or the ``list_by_index`` helper."""
    if isinstance(call.func, ast.Name):
        return call.func.id == "list_by_index"
    chain = _attr_chain(call.func)
    if len(chain) < 2 or chain[-1] not in _READ_METHODS:
        return False
    return any(part in _CLIENTISH for part in chain[:-1])


@register
class FrozenMutationRule(Rule):
    """Objects read through ``CachedClient``/the informer cache are
    SHARED and deep-frozen; in-place mutation raises
    ``FrozenObjectError`` at runtime (or, worse, corrupts every other
    reader if the freeze is ever bypassed). Any subscript assignment
    or mutating method call on a variable sourced from a cache-shaped
    read must take a private copy first: ``obj = mutable(obj)``.
    Scope-limited to the cache-fronted layers (controllers/web/
    scheduling); the raw store hands out private copies."""

    id = "frozen-mutation"
    description = (
        "in-place mutation of a cache-sourced object without mutable()"
    )
    dirs = ("controllers", "web", "scheduling", "sessions", "warmup")

    def check(self, src: SourceFile) -> Iterator[Finding]:
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_function(src, node)

    # -- per-function sequential taint walk ---------------------------------

    def _check_function(
        self, src: SourceFile, fn: ast.FunctionDef
    ) -> Iterator[Finding]:
        tainted: set[str] = set()
        yield from self._walk(src, fn.body, tainted)

    def _walk(
        self, src: SourceFile, body: list[ast.stmt], tainted: set[str]
    ) -> Iterator[Finding]:
        for stmt in body:
            yield from self._handle_stmt(src, stmt, tainted)

    def _handle_stmt(
        self, src: SourceFile, stmt: ast.stmt, tainted: set[str]
    ) -> Iterator[Finding]:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested function: fresh scope
            yield from self._check_function(src, stmt)
            return
        yield from self._mutations_in(src, stmt, tainted)
        if isinstance(stmt, ast.Assign):
            for target in stmt.targets:
                self._assign(target, stmt.value, tainted)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._assign(stmt.target, stmt.value, tainted)
        elif isinstance(stmt, ast.For):
            if isinstance(stmt.target, ast.Name):
                if isinstance(
                    stmt.iter, ast.Call
                ) and _is_cache_read(stmt.iter):
                    tainted.add(stmt.target.id)
                elif (
                    isinstance(stmt.iter, ast.Name)
                    and stmt.iter.id in tainted
                ):
                    # iterating a tainted list: elements share the taint
                    tainted.add(stmt.target.id)
                else:
                    tainted.discard(stmt.target.id)
            yield from self._walk(src, stmt.body, tainted)
            yield from self._walk(src, stmt.orelse, tainted)
            return
        # recurse into compound statements with the same scope
        for attr in ("body", "orelse", "finalbody", "handlers"):
            sub = getattr(stmt, attr, None)
            if not sub:
                continue
            if attr == "handlers":
                for h in sub:
                    yield from self._walk(src, h.body, tainted)
            else:
                yield from self._walk(src, sub, tainted)

    def _assign(
        self, target: ast.AST, value: ast.AST, tainted: set[str]
    ) -> None:
        if not isinstance(target, ast.Name):
            return
        if isinstance(value, ast.Call):
            if _is_cache_read(value):
                tainted.add(target.id)
                return
            chain = _attr_chain(value.func)
            if chain and chain[-1] in ("mutable", "deepcopy"):
                tainted.discard(target.id)
                return
            tainted.discard(target.id)
            return
        if isinstance(value, ast.Name) and value.id in tainted:
            tainted.add(target.id)  # alias keeps the taint
            return
        tainted.discard(target.id)

    def _mutations_in(
        self, src: SourceFile, stmt: ast.stmt, tainted: set[str]
    ) -> Iterator[Finding]:
        if not tainted:
            return
        targets: list[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = [
                t
                for t in stmt.targets
                if isinstance(t, (ast.Subscript, ast.Attribute))
            ]
        elif isinstance(stmt, ast.AugAssign) and isinstance(
            stmt.target, (ast.Subscript, ast.Attribute, ast.Name)
        ):
            targets = [stmt.target]
        elif isinstance(stmt, ast.Delete):
            targets = [
                t for t in stmt.targets if isinstance(t, ast.Subscript)
            ]
        for t in targets:
            root = _root_name(t)
            if root in tainted:
                yield self.finding(
                    src,
                    stmt,
                    f"in-place write to cache-sourced object {root!r} "
                    "(shared, frozen); take a private copy first: "
                    f"{root} = mutable({root})",
                )
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            call = stmt.value
            if isinstance(call.func, ast.Attribute) and (
                call.func.attr in _MUTATORS
            ):
                root = _root_name(call.func.value)
                if root in tainted:
                    yield self.finding(
                        src,
                        stmt,
                        f".{call.func.attr}() on cache-sourced object "
                        f"{root!r} (shared, frozen); take a private copy "
                        f"first: {root} = mutable({root})",
                    )


# ---------------------------------------------------------------------------
# interprocedural rules (whole-program: analysis/callgraph.py)

# the concurrency-bearing files whose critical sections the
# interprocedural blocking analysis guards — the intra-procedural
# blocking-under-lock scope, by construction
_CONCURRENCY_FILES = BlockingUnderLockRule.files


@register
class BlockingReachableUnderLockRule(ProgramRule):
    """The through-calls half of ``blocking-under-lock``: a ``with
    lock:`` body that CALLS something which (transitively) sleeps,
    fsyncs, or does socket IO stalls every contender exactly like an
    inline sleep — this is the class of bug PR 10's off-lock snapshots
    fixed by hand (the snapshot dump used to serialize under the store
    lock, three calls deep). Findings carry the witness call chain.
    Deliberate designs (the WAL's io_lock exists to serialize fsync
    batches) annotate the call site with a reason."""

    id = "blocking-reachable-under-lock"
    description = (
        "call chain from a with-lock body to sleep/socket IO/fsync in "
        "a callee"
    )

    def check_program(self, program) -> Iterator[Finding]:
        for fn in program.functions.values():
            if fn.src.rel not in _CONCURRENCY_FILES:
                continue
            for region in fn.regions:
                seen: set[str] = set()
                for cs in region.calls:
                    for target in cs.targets:
                        if target == fn.qual:
                            continue
                        for desc, chain in sorted(
                            program.reach_blocking(target).items()
                        ):
                            if desc in seen:
                                continue
                            seen.add(desc)
                            head = callgraph.Step(
                                fn.short, fn.src.rel, cs.node.lineno, cs.label
                            )
                            yield self.finding(
                                fn.src,
                                cs.node,
                                f"{desc} reachable while holding "
                                f"{region.lock!r}: "
                                + callgraph.render_chain((head,) + chain)
                                + "; move the blocking work off the "
                                "critical section or annotate with a "
                                "reason",
                            )


@register
class LockOrderCycleRule(ProgramRule):
    """Static lockdep: every ``with A:`` body that (directly or
    through any resolved call chain) acquires B records the edge A→B;
    a cycle in that graph is a deadlock waiting for the interleaving
    the runtime sanitizer only catches when a test happens to execute
    it. Both witness call paths are reported. Lock ranks come from the
    sanitizer factory names, so the static graph and the
    GRAFT_SANITIZE order graph speak the same language."""

    id = "lock-order-cycle"
    description = (
        "cycle in the static acquires-while-holding graph (potential "
        "deadlock), with witness call paths"
    )

    # the concurrency-bearing sections: lock edges are collected from
    # every function defined here (callees may live anywhere)
    _SECTIONS = ("machinery", "controllers", "scheduling")

    def check_program(self, program) -> Iterator[Finding]:
        # edge (held → wanted) → (witness text, src, anchor node)
        edges: dict[tuple[str, str], tuple[str, Any, Any]] = {}
        for fn in program.functions.values():
            if fn.src.section not in self._SECTIONS:
                continue
            for region in fn.regions:
                for site in region.nested:
                    if site.lock == region.lock:
                        continue
                    edges.setdefault(
                        (region.lock, site.lock),
                        (
                            f"{fn.short} "
                            f"({fn.src.rel}:{site.node.lineno}) acquires "
                            f"{site.lock!r} while holding {region.lock!r}",
                            fn.src,
                            region.node,
                        ),
                    )
                for cs in region.calls:
                    for target in cs.targets:
                        if target == fn.qual:
                            continue
                        for lock, chain in sorted(
                            program.reach_acquires(target).items()
                        ):
                            if lock == region.lock:
                                continue
                            head = callgraph.Step(
                                fn.short,
                                fn.src.rel,
                                cs.node.lineno,
                                cs.label,
                            )
                            edges.setdefault(
                                (region.lock, lock),
                                (
                                    f"holding {region.lock!r}: "
                                    + callgraph.render_chain(
                                        (head,) + chain
                                    ),
                                    fn.src,
                                    region.node,
                                ),
                            )
        adj: dict[str, set[str]] = {}
        for a, b in edges:
            adj.setdefault(a, set()).add(b)

        def reaches(src_lock: str, dst: str) -> Optional[list[str]]:
            # DFS path src→…→dst over the edge graph (deterministic
            # order for reproducible witness selection)
            stack = [(src_lock, [src_lock])]
            seen = {src_lock}
            while stack:
                node, path = stack.pop()
                for nxt in sorted(adj.get(node, ())):
                    if nxt == dst:
                        return path + [nxt]
                    if nxt not in seen:
                        seen.add(nxt)
                        stack.append((nxt, path + [nxt]))
            return None

        reported: set[frozenset[str]] = set()
        for (a, b), (witness, src, anchor) in sorted(edges.items()):
            back = reaches(b, a)
            if back is None:
                continue
            cycle = frozenset(back)
            if cycle in reported:
                continue
            reported.add(cycle)
            back_witnesses = [
                edges[(back[i], back[i + 1])][0]
                for i in range(len(back) - 1)
            ]
            yield self.finding(
                src,
                anchor,
                f"lock-order cycle {a!r} → {b!r} → … → {a!r}: "
                f"[forward] {witness}; [back] "
                + "; ".join(back_witnesses),
            )


@register
class AwaitHoldingLockRule(ProgramRule):
    """Coroutines running inline on the event-loop thread multiplex
    EVERY connection: one blocking call — or one acquisition of a lock
    a slow writer might hold — parks the whole serving tier, not one
    request. Nothing blocking and no lock may be reachable from an
    ``async def`` in the event-loop tier; hand such work to the worker
    pool (``run_in_executor``) instead. ``await``-ed calls and
    ``asyncio.sleep`` yield the loop and are exempt."""

    id = "await-holding-lock"
    description = (
        "blocking call or lock acquisition reachable from an event-"
        "loop coroutine"
    )

    _FILES = ("machinery/eventloop.py",)

    def check_program(self, program) -> Iterator[Finding]:
        for fn in program.functions.values():
            if fn.src.rel not in self._FILES or not fn.is_async:
                continue
            for desc, node in fn.blocking:
                yield self.finding(
                    fn.src,
                    node,
                    f"coroutine {fn.short} runs {desc} on the loop "
                    "thread; every connection stalls behind it — use "
                    "run_in_executor or an awaitable",
                )
            for site in fn.acquires:
                yield self.finding(
                    fn.src,
                    site.node,
                    f"coroutine {fn.short} acquires lock {site.lock!r} "
                    "on the loop thread; a slow holder parks every "
                    "connection — dispatch to the worker pool",
                )
            seen: set[tuple[str, str]] = set()
            for cs in fn.calls:
                for target in cs.targets:
                    if target == fn.qual:
                        continue  # self-recursion: the direct scan above owns it
                    head = callgraph.Step(
                        fn.short, fn.src.rel, cs.node.lineno, cs.label
                    )
                    for desc, chain in sorted(
                        program.reach_blocking(target).items()
                    ):
                        if ("b", desc) in seen:
                            continue
                        seen.add(("b", desc))
                        yield self.finding(
                            fn.src,
                            cs.node,
                            f"{desc} reachable from loop coroutine "
                            f"{fn.short}: "
                            + callgraph.render_chain((head,) + chain),
                        )
                    for lock, chain in sorted(
                        program.reach_acquires(target).items()
                    ):
                        if ("l", lock) in seen:
                            continue
                        seen.add(("l", lock))
                        yield self.finding(
                            fn.src,
                            cs.node,
                            f"lock {lock!r} acquisition reachable from "
                            f"loop coroutine {fn.short}: "
                            + callgraph.render_chain((head,) + chain),
                        )


# ---------------------------------------------------------------------------
# exception-flow rules (whole-program: analysis/exceptions.py)

# error-contract / handler-masks-fencing / dead-except self-register on
# import — raise-set inference over the same call graph, see the
# module docstring for the contract table and suppression syntax
from odh_kubeflow_tpu.analysis import exceptions as _exceptions  # noqa: E402,F401

# protocol-surface rules (whole-program): duck-conformance verifies
# every APIServer implementation against the reference protocol (and
# the httpapi↔client error-mapping round trip); protocol-drift keeps
# the kube-metadata contract registry honest against the tree
from odh_kubeflow_tpu.analysis import ducks as _ducks  # noqa: E402,F401
from odh_kubeflow_tpu.analysis import protocol as _protocol  # noqa: E402,F401
