"""Static analysis + runtime concurrency tooling for the platform.

Three halves, one package:

- **graftlint** (``analysis/graftlint.py`` + ``analysis/rules.py``):
  AST-based invariant rules — per-file (frozen-mutation,
  uncached-list, swallowed-exception, blocking-under-lock,
  metric-naming, …) and whole-program over the package call graph
  (``analysis/callgraph.py``): ``lock-order-cycle``,
  ``blocking-reachable-under-lock``, ``await-holding-lock``, plus the
  exception-flow rules (``analysis/exceptions.py`` — interprocedural
  raise-set inference): ``error-contract``,
  ``handler-masks-fencing``, ``dead-except``, each reporting witness
  call chains. Run with ``python -m odh_kubeflow_tpu.analysis``
  (exit-code gated, wired into ``make lint`` and CI);
  ``--format=json`` for machines, and a committed
  ``analysis/baseline.json`` ratchet so the gate fails only on NEW
  findings. The knob-registry drift lint (``analysis/knobs.py`` +
  ``knobs.json``) cross-checks every ``os.environ`` knob against the
  registry, GUIDE.md, and manifest env stanzas
  (``python -m odh_kubeflow_tpu.analysis.knobs``).
- **sanitizer** (``analysis/sanitizer.py``): the ``GRAFT_SANITIZE=1``
  lock-wrapping layer that turns the randomized property tests into
  race probes (lock-order inversions, non-reentrant re-entry,
  blocking calls under store/cache locks).
- **schedule** (``analysis/schedule.py``): the deterministic schedule
  explorer — serializes scenario threads one-runnable-at-a-time via
  the sanitizer lock factories plus explicit ``sched_point`` markers,
  explores seeded random + bounded systematic interleavings, and
  replays any failing schedule from its seed (``make explore``,
  GRAFT_SCHED posture).

This module is also the platform's single lint entry point:
``lint_registry`` re-exports the live-registry metric naming lint so
callers need exactly one import for every lint surface.
"""

from odh_kubeflow_tpu.analysis import sanitizer  # noqa: F401
from odh_kubeflow_tpu.analysis import schedule  # noqa: F401
from odh_kubeflow_tpu.analysis.graftlint import (  # noqa: F401
    RULES,
    Finding,
    ProgramRule,
    Rule,
    SourceFile,
    active_rules,
    apply_baseline,
    default_baseline_path,
    lint_source,
    load_baseline,
    main,
    register,
    run_package,
    run_paths,
    run_source,
)
from odh_kubeflow_tpu.analysis.rules import (  # noqa: F401
    metric_definition_sites,
)
from odh_kubeflow_tpu.utils.prometheus import (  # noqa: F401
    lint_metric_names as lint_registry,
)
