"""Static analysis + runtime concurrency sanitizer for the platform.

Two halves, one entry point:

- **graftlint** (``analysis/graftlint.py`` + ``analysis/rules.py``):
  AST-based invariant rules — frozen-mutation, uncached-list,
  swallowed-exception, blocking-under-lock, metric-naming — with
  per-line suppression and file/rule allowlists. Run with
  ``python -m odh_kubeflow_tpu.analysis`` (exit-code gated, wired
  into ``make lint`` and CI).
- **sanitizer** (``analysis/sanitizer.py``): the ``GRAFT_SANITIZE=1``
  lock-wrapping layer that turns the randomized property tests into
  race probes (lock-order inversions, non-reentrant re-entry,
  blocking calls under store/cache locks).

This module is also the platform's single lint entry point:
``lint_registry`` re-exports the live-registry metric naming lint so
callers need exactly one import for every lint surface.
"""

from odh_kubeflow_tpu.analysis import sanitizer  # noqa: F401
from odh_kubeflow_tpu.analysis.graftlint import (  # noqa: F401
    RULES,
    Finding,
    Rule,
    SourceFile,
    active_rules,
    lint_source,
    main,
    register,
    run_package,
    run_paths,
    run_source,
)
from odh_kubeflow_tpu.analysis.rules import (  # noqa: F401
    metric_definition_sites,
)
from odh_kubeflow_tpu.utils.prometheus import (  # noqa: F401
    lint_metric_names as lint_registry,
)
