"""The kube-metadata contract registry and its drift lint.

Components on this platform coordinate through object METADATA at
least as much as through the API verbs: the suspend contract is three
annotations, admission gating is an annotation plus a label, warm-pool
claims are a handshake of five, the usage ledger and the tracer stamp
their own. Each key is a protocol — somebody writes it, somebody else
reads it, and nothing ever checked that both ends exist. Review
history shows exactly that failing: PR-14 and PR-17 reviews both found
annotation writers whose readers never fired.

``analysis/protocol.json`` is the registry (the ``knobs.json`` mold):
every domain-prefixed annotation/label key and every owned status
field, with the kind it rides on, its writer and reader modules, and a
one-line description. This module is the enforcement:

- an AST scanner mines every metadata read/write across the package —
  string-literal keys (``cloud.google.com/gke-nodepool``), module
  constants named ``*_ANNOTATION``/``*_LABEL`` (including bare-name
  values like ``kubeflow-resource-stopped``), resolvable f-strings
  (``f"{GROUP}/workload"``), and prefix constants
  (``…/poddefault-``) — classifying each site as a write (subscript
  store, ``setdefault``, ``pop``, ``del``, metadata dict literal) or a
  read (``get``, load subscript, ``in``, selector dicts,
  ``startswith``, label-index registration);
- :func:`protocol_violations` is the four-way tier-1-gated cross-check:
  code⊆registry (no undocumented keys), registry⊆code (no phantom
  keys), writer-without-reader / reader-without-writer orphan
  detection (externally-owned keys carry ``# protocol-ok: <reason>``
  in code AND an ``external`` note in the registry), and a GUIDE.md
  appendix that must match the rendered registry byte-exact;
- the ``protocol-drift`` :class:`ProgramRule` surfaces the code-side
  violations through ``python -m odh_kubeflow_tpu.analysis`` with
  site-anchored witnesses, sharing ``--format=json`` / ``--baseline``
  semantics with every other graftlint rule.

Status/condition fields are registry-DECLARED, not exhaustively mined:
the scanner verifies each declared field has live writers and readers
(``obj["status"][f]``, ``get_path(obj, "status", f)``, status dict
literals), but does not claim to find every status touch — annotation
and label keys are where the cross-component protocol lives.

Resource names (``google.com/tpu``) are registered with type
``resource`` and exempt from orphan analysis: the writer is the pod
spec (kube semantics), not a platform module.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import sys
from typing import Iterator, Optional

from odh_kubeflow_tpu.analysis.graftlint import (
    Finding,
    ProgramRule,
    SourceFile,
    iter_sources,
    package_root,
    register,
)

# ---------------------------------------------------------------------------
# key recognition

# a domain-prefixed kube metadata key: <dns-domain>/<name>, exactly one
# slash, the domain carrying at least one dot (so media types and path
# fragments don't match)
_DOMAIN_KEY_RE = re.compile(
    r"^[a-z0-9](?:[a-z0-9.-]*[a-z0-9])?\.[a-z]{2,}/"
    r"[A-Za-z0-9][A-Za-z0-9._-]*$"
)
# apiVersion strings share the shape (`rbac.authorization.k8s.io/v1`);
# a version segment after the slash disqualifies the string as a key
_VERSION_SEGMENT_RE = re.compile(r"^v\d+(?:(?:alpha|beta)\d+)?$")

# module constants with these name suffixes register their value as a
# key even when it is bare (no domain prefix): `OWNER_ANNOTATION =
# "owner"`, `TPU_RUNTIME_LABEL = "tpu-runtime"`
_CONST_SUFFIXES = ("_ANNOTATION", "_LABEL", "_ANNOTATION_PREFIX", "_LABEL_PREFIX")

REGISTRY_BASENAME = "protocol.json"
GUIDE_RELPATH = os.path.join("docs", "GUIDE.md")
APPENDIX_HEADING = "## Appendix: metadata protocol reference"
# presence of this file marks a package-scale run (fixture one-file
# programs only get the code⊆registry check)
ANCHOR_FILE = "apis/__init__.py"
MARKER = "protocol-ok:"


def registry_path() -> str:
    return os.path.join(
        os.path.dirname(os.path.abspath(__file__)), REGISTRY_BASENAME
    )


def repo_root() -> str:
    return os.path.dirname(package_root())


def guide_path() -> str:
    return os.path.join(repo_root(), GUIDE_RELPATH)


def guide_text() -> str:
    with open(guide_path(), encoding="utf-8") as fh:
        return fh.read()


def load_registry(path: Optional[str] = None) -> dict:
    with open(path or registry_path(), encoding="utf-8") as fh:
        return json.load(fh)


def is_protocol_key(value: str) -> bool:
    """Whether a string literal is a domain-prefixed metadata key (and
    not an apiVersion)."""
    if not _DOMAIN_KEY_RE.match(value):
        return False
    name = value.split("/", 1)[1]
    return not _VERSION_SEGMENT_RE.match(name)


# ---------------------------------------------------------------------------
# the scanner


@dataclasses.dataclass(frozen=True)
class Site:
    """One metadata-key touch: where, which way, and whether the
    statement carries a ``# protocol-ok:`` marker."""

    rel: str
    line: int
    access: str  # "write" | "read"
    marked: bool


@dataclasses.dataclass
class Scan:
    """The mined protocol surface of one file set."""

    # key (or prefix key, trailing "-") → sites
    keys: dict[str, list[Site]] = dataclasses.field(default_factory=dict)
    # keys whose constant is *_PREFIX-named or dash-terminated
    prefixes: set[str] = dataclasses.field(default_factory=set)
    # declared-status-field name → sites
    status: dict[str, list[Site]] = dataclasses.field(default_factory=dict)

    def add(self, key: str, site: Site, prefix: bool = False) -> None:
        self.keys.setdefault(key, []).append(site)
        if prefix:
            self.prefixes.add(key)

    def writers(self, key: str) -> list[str]:
        return sorted(
            {s.rel for s in self.keys.get(key, []) if s.access == "write"}
        )

    def readers(self, key: str) -> list[str]:
        return sorted(
            {s.rel for s in self.keys.get(key, []) if s.access == "read"}
        )


def _module_constants(sources: list[SourceFile]) -> dict[str, dict[str, str]]:
    """rel → {constant name → string value}, resolving same-module
    f-strings (``WORKLOAD_LABEL = f"{GROUP}/workload"``) and then
    cross-module ``from x import NAME`` links."""
    plain: dict[str, dict[str, str]] = {}
    pending: dict[str, list[tuple[str, ast.JoinedStr]]] = {}
    for src in sources:
        consts: dict[str, str] = {}
        fstrings: list[tuple[str, ast.JoinedStr]] = []
        for node in src.tree.body:
            targets: list[ast.expr] = []
            value: Optional[ast.expr] = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            names = [t.id for t in targets if isinstance(t, ast.Name)]
            if not names or value is None:
                continue
            if isinstance(value, ast.Constant) and isinstance(value.value, str):
                for n in names:
                    consts[n] = value.value
            elif isinstance(value, ast.JoinedStr):
                for n in names:
                    fstrings.append((n, value))
        plain[src.rel] = consts
        pending[src.rel] = fstrings
    by_rel = {s.rel: s for s in sources}
    for rel, fstrings in pending.items():
        for name, node in fstrings:
            resolved = _resolve_fstring(node, rel, plain, by_rel)
            if resolved is not None:
                plain[rel][name] = resolved
    return plain


def _import_map(
    src: SourceFile,
) -> tuple[dict[str, list[tuple[str, str]]], dict[str, list[str]]]:
    """Two resolution maps for ``from x import y [as z]`` statements
    inside the package: imported NAME → candidate (origin rel, origin
    name) pairs, and imported MODULE alias → candidate origin rels
    (``from pkg.utils import tracing`` binds a module — its constants
    are reached through attribute access, ``tracing.TRACE_ANNOTATION``)."""
    names: dict[str, list[tuple[str, str]]] = {}
    modules: dict[str, list[str]] = {}
    pkg = os.path.basename(package_root())
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.ImportFrom):
            continue
        if node.module:
            parts = node.module.split(".")
            if node.level == 0 and parts[0] != pkg:
                continue
            if node.level == 0:
                parts = parts[1:]
        elif node.level:
            # `from . import x` / `from .. import x` — resolve against
            # this file's own package path
            parts = src.rel.split("/")[: -node.level]
            if node.module:
                parts += node.module.split(".")
        else:
            continue
        base = "/".join(parts)
        name_origins = (
            [f"{base}.py", f"{base}/__init__.py"] if base else ["__init__.py"]
        )
        for a in node.names:
            bound = a.asname or a.name
            for origin in name_origins:
                names.setdefault(bound, []).append((origin, a.name))
            mod_base = f"{base}/{a.name}" if base else a.name
            modules.setdefault(bound, []).extend(
                [f"{mod_base}.py", f"{mod_base}/__init__.py"]
            )
    return names, modules


def _resolve_fstring(
    node: ast.JoinedStr,
    rel: str,
    consts: dict[str, dict[str, str]],
    by_rel: dict[str, SourceFile],
) -> Optional[str]:
    parts: list[str] = []
    for v in node.values:
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            parts.append(v.value)
        elif isinstance(v, ast.FormattedValue) and isinstance(
            v.value, ast.Name
        ):
            name = v.value.id
            val = consts.get(rel, {}).get(name)
            if val is None and rel in by_rel:
                names, _ = _import_map(by_rel[rel])
                for origin, orig_name in names.get(name, []):
                    val = consts.get(origin, {}).get(orig_name)
                    if val is not None:
                        break
            if val is None:
                return None
            parts.append(val)
        else:
            return None
    return "".join(parts)


_SELECTOR_KWARGS = frozenset(
    {"label_selector", "field_matches", "match_labels", "fallback_selector"}
)
# dict-literal keys whose VALUE dict queries metadata rather than
# building it: `{"selector": {KEY: v}}` on a Service, `matchLabels` in
# network policies / PodDefaults, `nodeSelector` on pod specs
_SELECTOR_DICT_KEYS = frozenset({"selector", "matchLabels", "nodeSelector"})
_WRITE_METHODS = frozenset({"setdefault", "pop"})


def _call_writes(meth: str) -> bool:
    """Whether passing a key to ``meth(…)`` mutates metadata:
    ``setdefault``/``pop`` on the dict itself, and the package's
    mutation helpers (``set_annotation``, ``set_label``,
    ``_stamp_editor_sa``, …)."""
    return meth in _WRITE_METHODS or meth.startswith("set_") or "stamp" in meth


class _KeyVisitor:
    """Walks one file, resolving key expressions and classifying each
    by syntactic context. Parent chains are tracked explicitly — the
    classification of a key is a property of what ENCLOSES it."""

    def __init__(
        self,
        src: SourceFile,
        consts: dict[str, str],
        imports: tuple[dict[str, list[tuple[str, str]]], dict[str, list[str]]],
        all_consts: dict[str, dict[str, str]],
        scan: Scan,
        declared_status: frozenset[str],
    ):
        self.src = src
        self.consts = consts
        self.import_names, self.import_modules = imports
        self.all_consts = all_consts
        self.scan = scan
        self.declared_status = declared_status
        # module-level STRING constant definitions are not protocol
        # touches — skip anything enclosed by one (matched by node
        # identity). Module-level dict/list config still counts: a
        # toleration table keyed by a node label USES the label.
        self._const_defs: set[int] = set()
        for node in src.tree.body:
            value = None
            if isinstance(node, ast.Assign):
                value = node.value
            elif isinstance(node, ast.AnnAssign):
                value = node.value
            if isinstance(value, (ast.Constant, ast.JoinedStr)):
                self._const_defs.add(id(node))

    # -- key resolution ------------------------------------------------------

    def _classify_const(self, name: str, val: str) -> Optional[tuple[str, bool]]:
        is_key_name = name.endswith(_CONST_SUFFIXES)
        prefix = name.endswith(("_ANNOTATION_PREFIX", "_LABEL_PREFIX")) or (
            is_key_name and val.endswith("-")
        )
        if is_key_name or is_protocol_key(val):
            return val, prefix
        return None

    def _const_value(self, name: str) -> Optional[tuple[str, bool]]:
        val = self.consts.get(name)
        orig = name
        if val is None:
            for origin, orig_name in self.import_names.get(name, []):
                val = self.all_consts.get(origin, {}).get(orig_name)
                if val is not None:
                    orig = orig_name
                    break
        if val is None:
            return None
        return self._classify_const(orig, val)

    def _attr_value(self, mod_alias: str, attr: str) -> Optional[tuple[str, bool]]:
        for origin in self.import_modules.get(mod_alias, []):
            val = self.all_consts.get(origin, {}).get(attr)
            if val is not None:
                return self._classify_const(attr, val)
        return None

    def key_of(self, node: ast.AST) -> Optional[tuple[str, bool]]:
        """(key, is_prefix) when ``node`` denotes a protocol key."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            if is_protocol_key(node.value):
                return node.value, node.value.endswith("-")
            return None
        if isinstance(node, ast.Name):
            return self._const_value(node.id)
        if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
            return self._attr_value(node.value.id, node.attr)
        if isinstance(node, ast.JoinedStr):
            # f"{PREFIX}{name}" → a use of the prefix key
            for v in node.values:
                if isinstance(v, ast.FormattedValue) and isinstance(
                    v.value, ast.Name
                ):
                    got = self._const_value(v.value.id)
                    if got is not None and got[1]:
                        return got[0], True
            resolved = _resolve_fstring(
                node,
                self.src.rel,
                self.all_consts,
                {self.src.rel: self.src},
            )
            if resolved is not None and is_protocol_key(resolved):
                return resolved, resolved.endswith("-")
        return None

    # -- context classification ----------------------------------------------

    def classify(self, parents: list[ast.AST], node: ast.AST) -> str:
        """"write", "read", or "skip" (constant definitions)."""
        if any(id(p) in self._const_defs for p in parents):
            return "skip"  # the module-level definition site itself
        for i in range(len(parents) - 1, -1, -1):
            p = parents[i]
            outer = parents[i - 1] if i > 0 else None
            if isinstance(p, ast.Subscript) and p.slice is node:
                if isinstance(p.ctx, (ast.Store, ast.Del)):
                    return "write"
                return "read"
            if isinstance(p, ast.Call):
                fn = p.func
                meth = fn.attr if isinstance(fn, ast.Attribute) else (
                    fn.id if isinstance(fn, ast.Name) else ""
                )
                if node in p.args or any(
                    kw.value is node for kw in p.keywords
                ):
                    if _call_writes(meth):
                        return "write"
                    return "read"
                node = p
                continue
            if isinstance(p, ast.Compare):
                return "read"
            if isinstance(p, ast.Dict):
                if node in p.keys:
                    return self._dict_key_access(parents[:i], p)
                return "read"
            if isinstance(p, (ast.Tuple, ast.List, ast.Set, ast.JoinedStr,
                              ast.FormattedValue, ast.BinOp)):
                node = p
                continue
            if outer is None:
                break
            node = p
        return "read"

    def _dict_key_access(
        self, parents: list[ast.AST], d: ast.Dict
    ) -> str:
        """A dict literal keyed by a protocol key: selector position →
        read (the dict QUERIES the key); anywhere else → write (the
        dict BUILDS metadata)."""
        node: ast.AST = d
        for p in reversed(parents):
            if isinstance(p, ast.Call):
                for kw in p.keywords:
                    if (
                        kw.value is node or kw is node
                    ) and kw.arg in _SELECTOR_KWARGS:
                        return "read"
                fn = p.func
                meth = fn.attr if isinstance(fn, ast.Attribute) else ""
                if node in p.args and meth in (
                    "match_label_selector",
                    "register_label_index",
                ):
                    return "read"
                return "write"
            if isinstance(p, ast.Assign):
                for t in p.targets:
                    if isinstance(t, ast.Name) and "selector" in t.id.lower():
                        return "read"
                return "write"
            if isinstance(p, ast.Dict):
                # the dict we're bubbling up through may itself be the
                # VALUE of a selector key (`"matchLabels": {KEY: v}`)
                for k, v in zip(p.keys, p.values):
                    if (
                        v is node
                        and isinstance(k, ast.Constant)
                        and k.value in _SELECTOR_DICT_KEYS
                    ):
                        return "read"
                node = p
                continue
            if isinstance(p, (ast.Tuple, ast.List, ast.keyword)):
                node = p
                continue
            break
        return "write"

    # -- the walk ------------------------------------------------------------

    def run(self) -> None:
        self._walk(self.src.tree, [], self.src.tree)

    def _walk(self, node: ast.AST, parents: list[ast.AST], stmt: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            child_stmt = child if isinstance(child, ast.stmt) else stmt
            got = self.key_of(child)
            if got is not None:
                key, prefix = got
                access = self.classify(parents + [node], child)
                if access != "skip":
                    self.scan.add(
                        key,
                        Site(
                            self.src.rel,
                            getattr(child, "lineno", 1),
                            access,
                            _has_marker(self.src, child_stmt),
                        ),
                        prefix,
                    )
                if isinstance(child, ast.JoinedStr):
                    continue  # don't descend into a resolved f-string
            self._status_probe(child, parents + [node], child_stmt)
            self._walk(child, parents + [node], child_stmt)

    # -- status fields -------------------------------------------------------

    def _status_probe(
        self, node: ast.AST, parents: list[ast.AST], stmt: ast.AST
    ) -> None:
        """Declared status fields touched through ``x["status"][f]``,
        ``x.get("status", {}).get(f)``, ``get_path(x, "status", f, …)``
        and ``{"status": {f: …}}`` / ``x["status"] = {f: …}`` shapes."""
        if not self.declared_status:
            return

        def is_status(expr: ast.AST) -> bool:
            # unwrap the pervasive `(x.get("status") or {})` guard
            if isinstance(expr, ast.BoolOp):
                return any(is_status(v) for v in expr.values)
            if (
                isinstance(expr, ast.Subscript)
                and isinstance(expr.slice, ast.Constant)
                and expr.slice.value == "status"
            ):
                return True
            if (
                isinstance(expr, ast.Call)
                and isinstance(expr.func, ast.Attribute)
                and expr.func.attr in ("get", "setdefault")
                and expr.args
                and isinstance(expr.args[0], ast.Constant)
                and expr.args[0].value == "status"
            ):
                return True
            # local-variable indirection: `status = ckpt.get("status")
            # or {}` then `status.get("phase")` — a name heuristic, but
            # the idiom is pervasive and fields are declared-only
            if isinstance(expr, ast.Name) and "status" in expr.id.lower():
                return True
            return False

        def emit(field: str, line: int, access: str) -> None:
            if field in self.declared_status:
                self.scan.status.setdefault(field, []).append(
                    Site(self.src.rel, line, access, _has_marker(self.src, stmt))
                )

        if isinstance(node, ast.Subscript) and isinstance(
            node.slice, ast.Constant
        ):
            field = node.slice.value
            if isinstance(field, str) and is_status(node.value):
                access = (
                    "write"
                    if isinstance(node.ctx, (ast.Store, ast.Del))
                    else "read"
                )
                emit(field, node.lineno, access)
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("get", "setdefault")
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
            and is_status(node.func.value)
        ):
            emit(
                node.args[0].value,
                node.lineno,
                "write" if node.func.attr == "setdefault" else "read",
            )
        if (
            isinstance(node, ast.Call)
            and (
                (
                    isinstance(node.func, ast.Name)
                    and node.func.id == "get_path"
                )
                or (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "get_path"
                )
            )
            and len(node.args) >= 3
            and isinstance(node.args[1], ast.Constant)
            and node.args[1].value == "status"
            and isinstance(node.args[2], ast.Constant)
            and isinstance(node.args[2].value, str)
        ):
            emit(node.args[2].value, node.lineno, "read")
        # wl["status"].update({f: …})
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "update"
            and is_status(node.func.value)
            and node.args
            and isinstance(node.args[0], ast.Dict)
        ):
            for k in node.args[0].keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    emit(k.value, k.lineno, "write")
        # obj["status"] = {f: …}  /  status_patch = {f: …}  /
        # {"status": {f: …}}
        fields_dict: Optional[ast.Dict] = None
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and is_status(node.targets[0])
            and isinstance(node.value, ast.Dict)
        ):
            fields_dict = node.value
        elif isinstance(node, ast.Dict):
            for k, v in zip(node.keys, node.values):
                if (
                    isinstance(k, ast.Constant)
                    and k.value == "status"
                    and isinstance(v, ast.Dict)
                ):
                    fields_dict = v
        if fields_dict is not None:
            for k in fields_dict.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    emit(k.value, k.lineno, "write")


def _has_marker(src: SourceFile, stmt: ast.AST) -> bool:
    # the statement span, plus the line directly above it (the natural
    # home of a standalone `# protocol-ok: <reason>` comment). The
    # line above only counts when it IS a comment line — a trailing
    # marker on the previous statement must not leak downward
    start = getattr(stmt, "lineno", 1)
    end = getattr(stmt, "end_lineno", None) or start
    if any(MARKER in src.line(n) for n in range(start, end + 1)):
        return True
    if start <= 1:
        return False
    above = src.line(start - 1).strip()
    return above.startswith("#") and MARKER in above


def scan_sources(
    sources: list[SourceFile], declared_status: frozenset[str] = frozenset()
) -> Scan:
    scan = Scan()
    consts = _module_constants(sources)
    for src in sources:
        _KeyVisitor(
            src,
            consts.get(src.rel, {}),
            _import_map(src),
            consts,
            scan,
            declared_status,
        ).run()
    return scan


def scan_package(
    root: Optional[str] = None,
    declared_status: frozenset[str] = frozenset(),
) -> Scan:
    return scan_sources(list(iter_sources(root)), declared_status)


# ---------------------------------------------------------------------------
# the appendix (knobs mold: generated-by-enforcement)

_TYPE_ORDER = ("annotation", "label", "resource")
_TYPE_HEADING = {
    "annotation": "Annotations",
    "label": "Labels",
    "resource": "Resource names",
}


def _mods(rels: list[str]) -> str:
    return ", ".join(f"`{r}`" for r in rels) if rels else "—"


def appendix_row(entry: dict) -> str:
    """The canonical GUIDE.md appendix table row for one key — the
    lint demands this EXACT line, so the appendix is generated-by-
    enforcement exactly like the knob reference."""
    ext = " (external)" if entry.get("external") else ""
    return (
        f"| `{entry['key']}` | {entry.get('rides_on', '—')} | "
        f"{_mods(entry.get('writers', []))} | "
        f"{_mods(entry.get('readers', []))} | "
        f"{entry['description']}{ext} |"
    )


def status_row(entry: dict) -> str:
    return (
        f"| `{entry['field']}` | {entry.get('rides_on', '—')} | "
        f"{_mods(entry.get('writers', []))} | "
        f"{_mods(entry.get('readers', []))} | "
        f"{entry['description']} |"
    )


def render_appendix(registry: Optional[dict] = None) -> str:
    """The full appendix body (type-grouped tables) rendered from the
    registry — paste-ready for GUIDE.md under the
    '## Appendix: metadata protocol reference' heading."""
    reg = registry if registry is not None else load_registry()
    by_type: dict[str, list[dict]] = {}
    for e in reg.get("keys", []):
        by_type.setdefault(e.get("type", "annotation"), []).append(e)
    lines: list[str] = []
    for t in _TYPE_ORDER:
        if t not in by_type:
            continue
        lines += [
            f"### {_TYPE_HEADING[t]}",
            "",
            "| key | rides on | writers | readers | description |",
            "|---|---|---|---|---|",
        ]
        lines += [
            appendix_row(e)
            for e in sorted(by_type[t], key=lambda x: x["key"])
        ]
        lines.append("")
    status = reg.get("status_fields", [])
    if status:
        lines += [
            "### Status fields",
            "",
            "| field | rides on | writers | readers | description |",
            "|---|---|---|---|---|",
        ]
        lines += [
            status_row(e) for e in sorted(status, key=lambda x: x["field"])
        ]
        lines.append("")
    return "\n".join(lines).rstrip() + "\n"


# ---------------------------------------------------------------------------
# the four-way cross-check


def _match_registry_key(key: str, entries: dict[str, dict]) -> Optional[str]:
    """The registry key covering ``key`` — exact, or a registered
    prefix entry the key extends."""
    if key in entries:
        return key
    for rkey, e in entries.items():
        if e.get("prefix") and key.startswith(rkey):
            return rkey
    return None


def protocol_violations(
    root: Optional[str] = None,
    registry: Optional[dict] = None,
    guide: Optional[str] = None,
    scan: Optional[Scan] = None,
) -> list[str]:
    """Every drift between code, registry and GUIDE.md — empty on a
    healthy tree (the tier-1 gate, ``tests/test_protocol.py``)."""
    reg = registry if registry is not None else load_registry()
    entries = {e["key"]: e for e in reg.get("keys", [])}
    status_entries = {e["field"]: e for e in reg.get("status_fields", [])}
    declared_status = frozenset(status_entries)
    scanned = (
        scan
        if scan is not None
        else scan_package(root, declared_status=declared_status)
    )
    text = guide if guide is not None else guide_text()
    out: list[str] = []

    for key in sorted(scanned.keys):
        rkey = _match_registry_key(key, entries)
        if rkey is None:
            sites = scanned.keys[key]
            where = ", ".join(
                sorted({s.rel for s in sites})
            )
            out.append(
                f"undocumented metadata key {key!r} (touched in {where}): "
                "add it to analysis/protocol.json with type/rides_on/"
                "writers/readers/description"
            )
    seen_by_rkey: dict[str, list[Site]] = {}
    for key, sites in scanned.keys.items():
        rkey = _match_registry_key(key, entries)
        if rkey is not None:
            seen_by_rkey.setdefault(rkey, []).extend(sites)
    for rkey, e in entries.items():
        sites = seen_by_rkey.get(rkey)
        if not sites:
            out.append(
                f"phantom metadata key {rkey!r}: registered in "
                "analysis/protocol.json but never touched by package "
                "code — delete the entry or fix the scanner miss"
            )
            continue
        writers = sorted({s.rel for s in sites if s.access == "write"})
        readers = sorted({s.rel for s in sites if s.access == "read"})
        if writers != e.get("writers", []):
            out.append(
                f"metadata key {rkey!r}: registry writers "
                f"{e.get('writers', [])} != scanned {writers} — resync "
                "with `python -m odh_kubeflow_tpu.analysis.protocol "
                "--sync-registry`"
            )
        if readers != e.get("readers", []):
            out.append(
                f"metadata key {rkey!r}: registry readers "
                f"{e.get('readers', [])} != scanned {readers} — resync "
                "with `python -m odh_kubeflow_tpu.analysis.protocol "
                "--sync-registry`"
            )
        if e.get("type") == "resource":
            continue  # written by pod specs, kube semantics
        marked = any(s.marked for s in sites)
        external = bool(e.get("external"))
        if writers and not readers and not marked:
            out.append(
                f"orphan metadata key {rkey!r}: written in "
                f"{', '.join(writers)} but nothing in the package reads "
                "it — dead protocol, or an external consumer; fix the "
                "dead write or mark a site `# protocol-ok: <reason>` "
                'and set "external" in the registry'
            )
        if readers and not writers and not marked:
            out.append(
                f"orphan metadata key {rkey!r}: read in "
                f"{', '.join(readers)} but nothing in the package writes "
                "it — dead read, or an externally-written key; fix the "
                "dead read or mark a site `# protocol-ok: <reason>` "
                'and set "external" in the registry'
            )
        if external and not marked:
            out.append(
                f"metadata key {rkey!r} is marked external in the "
                "registry but no touch site carries `# protocol-ok: "
                "<reason>` — annotate the code so the exemption is "
                "visible where the key is used"
            )
    for field, e in status_entries.items():
        sites = scanned.status.get(field, [])
        writers = sorted({s.rel for s in sites if s.access == "write"})
        readers = sorted({s.rel for s in sites if s.access == "read"})
        if not writers:
            out.append(
                f"status field {field!r}: registered in "
                "analysis/protocol.json but no package writer found — "
                "delete the entry or fix the scanner miss"
            )
        if not readers:
            out.append(
                f"status field {field!r}: registered in "
                "analysis/protocol.json but no package reader found — "
                "delete the entry or fix the scanner miss"
            )
        if writers and e.get("writers", []) != writers:
            out.append(
                f"status field {field!r}: registry writers "
                f"{e.get('writers', [])} != scanned {writers} — resync "
                "with `python -m odh_kubeflow_tpu.analysis.protocol "
                "--sync-registry`"
            )
        if readers and e.get("readers", []) != readers:
            out.append(
                f"status field {field!r}: registry readers "
                f"{e.get('readers', [])} != scanned {readers} — resync "
                "with `python -m odh_kubeflow_tpu.analysis.protocol "
                "--sync-registry`"
            )
    if APPENDIX_HEADING not in text:
        out.append(
            f"docs/GUIDE.md is missing the '{APPENDIX_HEADING}' section "
            "— render it with `python -m odh_kubeflow_tpu.analysis."
            "protocol --render-appendix`"
        )
    else:
        for e in reg.get("keys", []):
            if appendix_row(e) not in text:
                out.append(
                    f"metadata key {e['key']!r}'s appendix row is stale "
                    "or missing in docs/GUIDE.md — regenerate with "
                    "`python -m odh_kubeflow_tpu.analysis.protocol "
                    "--render-appendix`"
                )
        for e in reg.get("status_fields", []):
            if status_row(e) not in text:
                out.append(
                    f"status field {e['field']!r}'s appendix row is "
                    "stale or missing in docs/GUIDE.md — regenerate with "
                    "`python -m odh_kubeflow_tpu.analysis.protocol "
                    "--render-appendix`"
                )
    return sorted(out)


# ---------------------------------------------------------------------------
# the lint rule


@register
class ProtocolDriftRule(ProgramRule):
    """The code-side half of the protocol cross-check, surfaced with
    site anchors through the shared graftlint CLI: undocumented keys,
    orphaned writers/readers, and registry drift. The GUIDE appendix
    byte-exactness and phantom-key checks live in
    :func:`protocol_violations` (the knobs mold) — they anchor to the
    registry and the guide, not to package code."""

    id = "protocol-drift"
    description = (
        "kube-metadata key drifting from the protocol registry "
        "(undocumented key, orphaned writer/reader, stale writers/"
        "readers lists)"
    )

    def check_program(self, program) -> Iterator[Finding]:
        try:
            reg = load_registry()
        except (OSError, ValueError):
            return
        entries = {e["key"]: e for e in reg.get("keys", [])}
        declared_status = frozenset(
            e["field"] for e in reg.get("status_fields", [])
        )
        sources = list(program.sources.values())
        scan = scan_sources(sources, declared_status)
        by_rel = {s.rel: s for s in sources}
        full = ANCHOR_FILE in program.sources

        def anchor(sites: list[Site]) -> tuple[SourceFile, ast.AST]:
            first = min(sites, key=lambda s: (s.rel, s.line))
            src = by_rel[first.rel]
            node = ast.Module(body=[], type_ignores=[])
            node.lineno = first.line  # type: ignore[attr-defined]
            node.end_lineno = first.line  # type: ignore[attr-defined]
            return src, node

        for key in sorted(scan.keys):
            if _match_registry_key(key, entries) is None:
                src, node = anchor(scan.keys[key])
                yield self.finding(
                    src,
                    node,
                    f"metadata key {key!r} is not in the protocol "
                    "registry — add it to analysis/protocol.json with "
                    "type/rides_on/writers/readers/description (and "
                    "re-render the GUIDE appendix)",
                )
        if not full:
            return
        seen_by_rkey: dict[str, list[Site]] = {}
        for key, sites in scan.keys.items():
            rkey = _match_registry_key(key, entries)
            if rkey is not None:
                seen_by_rkey.setdefault(rkey, []).extend(sites)
        for rkey, e in entries.items():
            sites = seen_by_rkey.get(rkey)
            if not sites or e.get("type") == "resource":
                continue
            writers = sorted({s.rel for s in sites if s.access == "write"})
            readers = sorted({s.rel for s in sites if s.access == "read"})
            marked = any(s.marked for s in sites)
            if writers and not readers and not marked:
                src, node = anchor(
                    [s for s in sites if s.access == "write"]
                )
                yield self.finding(
                    src,
                    node,
                    f"metadata key {rkey!r} is written here but nothing "
                    "in the package reads it — dead protocol or an "
                    "external consumer; fix the write or mark "
                    "`# protocol-ok: <reason>` and set \"external\" in "
                    "analysis/protocol.json",
                )
            if readers and not writers and not marked:
                src, node = anchor(
                    [s for s in sites if s.access == "read"]
                )
                yield self.finding(
                    src,
                    node,
                    f"metadata key {rkey!r} is read here but nothing in "
                    "the package writes it — dead read or an externally-"
                    "written key; fix the read or mark "
                    "`# protocol-ok: <reason>` and set \"external\" in "
                    "analysis/protocol.json",
                )
            if writers != e.get("writers", []) or readers != e.get(
                "readers", []
            ):
                src, node = anchor(sites)
                yield self.finding(
                    src,
                    node,
                    f"metadata key {rkey!r}: the registry's writers/"
                    "readers lists are stale (registry "
                    f"{e.get('writers', [])}/{e.get('readers', [])}, "
                    f"scanned {writers}/{readers}) — resync with "
                    "`python -m odh_kubeflow_tpu.analysis.protocol "
                    "--sync-registry` and re-render the GUIDE appendix",
                )


# ---------------------------------------------------------------------------
# CLI (knobs mold + --sync-registry)


def sync_registry(path: Optional[str] = None) -> dict:
    """Re-mine writers/readers into the registry file, preserving
    hand-written fields (type, rides_on, description, external,
    prefix) — the maintenance half of the ratchet: add the row by
    hand, let the scanner keep the file lists honest."""
    p = path or registry_path()
    reg = load_registry(p)
    declared_status = frozenset(
        e["field"] for e in reg.get("status_fields", [])
    )
    scan = scan_package(declared_status=declared_status)
    entries = {e["key"]: e for e in reg.get("keys", [])}
    seen: dict[str, list[Site]] = {}
    for key, sites in scan.keys.items():
        rkey = _match_registry_key(key, entries)
        if rkey is not None:
            seen.setdefault(rkey, []).extend(sites)
    for e in reg.get("keys", []):
        sites = seen.get(e["key"], [])
        e["writers"] = sorted({s.rel for s in sites if s.access == "write"})
        e["readers"] = sorted({s.rel for s in sites if s.access == "read"})
    for e in reg.get("status_fields", []):
        sites = scan.status.get(e["field"], [])
        e["writers"] = sorted({s.rel for s in sites if s.access == "write"})
        e["readers"] = sorted({s.rel for s in sites if s.access == "read"})
    with open(p, "w", encoding="utf-8") as fh:
        json.dump(reg, fh, indent=2, sort_keys=False)
        fh.write("\n")
    return reg


def main(argv: Optional[list[str]] = None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    if "--render-appendix" in args:
        print(render_appendix(), end="")
        return 0
    if "--sync-registry" in args:
        reg = sync_registry()
        print(
            f"protocol-registry: resynced {len(reg.get('keys', []))} "
            f"key(s) + {len(reg.get('status_fields', []))} status "
            "field(s)",
            file=sys.stderr,
        )
        return 0
    if "--dump-scan" in args:
        reg = load_registry()
        declared_status = frozenset(
            e["field"] for e in reg.get("status_fields", [])
        )
        scan = scan_package(declared_status=declared_status)
        for key in sorted(scan.keys):
            for s in scan.keys[key]:
                print(f"{key}\t{s.access}\t{s.rel}:{s.line}"
                      + ("\tmarked" if s.marked else ""))
        for field in sorted(scan.status):
            for s in scan.status[field]:
                print(f"status.{field}\t{s.access}\t{s.rel}:{s.line}")
        return 0
    violations = protocol_violations()
    for v in violations:
        print(v)
    reg = load_registry()
    n = len(reg.get("keys", []))
    ns = len(reg.get("status_fields", []))
    if violations:
        print(
            f"protocol-registry: {len(violations)} violation(s) across "
            f"{n} key(s) + {ns} status field(s)",
            file=sys.stderr,
        )
        return 1
    print(
        f"protocol-registry: clean ({n} keys, {ns} status fields)",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
