"""Runtime concurrency sanitizer: lock wrapping + blocking-op probes.

The control plane's thread-safety rests on conventions no test
exercises directly: locks in ``store.py``/``cache.py``/``runtime.py``
are always taken in one global order, non-reentrant locks are never
re-entered, and nothing blocking (``time.sleep``, HTTP round-trips,
``Watch.get``) runs while a store/cache lock is held (the PR 1
``_RateLimiter`` bug was exactly that shape). This module is the
``-race``-style probe for those conventions — the Go operators the
reference builds on get this from the runtime; Python needs a harness.

Opt-in via ``GRAFT_SANITIZE=1`` (or ``enable()`` in a test):

- ``new_lock(name)`` / ``new_rlock(name)`` are the factories the
  machinery uses everywhere it used to call ``threading.Lock()`` /
  ``RLock()``. Disabled (the default), they return the raw primitive —
  zero overhead. Enabled, they return a :class:`SanitizedLock` that
  records per-thread acquisition order.
- **Lock-order inversion**: acquiring B while holding A records the
  edge A→B; a later acquisition that closes a cycle (B held, A wanted,
  with A→…→B already witnessed) is reported with both witness sites.
  Single-threaded runs detect inversions too — the order graph is
  global, so the randomized property tests double as race probes
  without needing a lucky interleaving.
- **Same-thread re-entry** on a non-reentrant lock is a guaranteed
  deadlock; it is reported AND raised as :class:`SanitizerError`
  (blocking forever would just hang the test).
- **Blocking under lock**: ``enable()`` patches ``time.sleep``, and
  the machinery's known blocking entry points (``Watch.get`` with a
  timeout, the remote client's HTTP requests) call
  :func:`note_blocking`; either reports when the calling thread holds
  any sanitized lock.

Reports accumulate in-process (``reports()``); the property tests
assert the list is empty at the end of a randomized run, and
``reset()`` clears state between probes.
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from typing import Optional

__all__ = [
    "SanitizedLock",
    "SanitizerError",
    "enable",
    "disable",
    "enabled",
    "new_lock",
    "new_rlock",
    "note_blocking",
    "reports",
    "reset",
    "set_factory_hook",
]


class SanitizerError(RuntimeError):
    """A concurrency violation that cannot safely proceed (re-entering
    a non-reentrant lock would deadlock the thread for real)."""


_enabled = os.environ.get("GRAFT_SANITIZE", "") == "1"
_real_sleep = None

# global sanitizer state, guarded by one raw lock (never a sanitized
# one — the sanitizer must not recurse into itself)
_state_lock = threading.Lock()
_edges: dict[str, set[str]] = {}  # held-name → {acquired-after names}
_witness: dict[tuple[str, str], str] = {}  # edge → "file:line" first seen
_reports: list[str] = []
_reported_pairs: set[tuple[str, str]] = set()
_tls = threading.local()


def _held() -> list["SanitizedLock"]:
    """This thread's held sanitized locks (the instances, pinned so
    ids stay unique), outermost first, each listed once regardless of
    re-entry depth. ``_tls.counts`` tracks per-INSTANCE depth — two
    distinct locks sharing a factory name are different locks (no
    false re-entry), while the order graph ranks by NAME (lockdep
    semantics: every instance of a lock role shares a rank)."""
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
        _tls.counts = {}
    return h


def _held_names() -> list[str]:
    return [lock.name for lock in _held()]


def _call_site() -> str:
    """First stack frame outside this module — the acquisition site."""
    for frame in reversed(traceback.extract_stack()):
        if not frame.filename.endswith("sanitizer.py"):
            return f"{os.path.basename(frame.filename)}:{frame.lineno}"
    return "?"


def _report(message: str) -> None:
    with _state_lock:
        _reports.append(message)


def _reachable(src: str, dst: str) -> bool:
    """Whether the order graph already witnesses src→…→dst (caller
    holds ``_state_lock``)."""
    seen = {src}
    stack = [src]
    while stack:
        for nxt in _edges.get(stack.pop(), ()):
            if nxt == dst:
                return True
            if nxt not in seen:
                seen.add(nxt)
                stack.append(nxt)
    return False


class SanitizedLock:
    """Instrumented Lock/RLock with the ``threading`` lock protocol
    (``acquire``/``release``/context manager), safe to hand to
    ``threading.Condition``. ``allow_blocking`` marks a lock whose
    critical section is DESIGNED to block (a coarse try-acquire-only
    heal mutex): it still participates in order/re-entry tracking but
    is exempt from blocking-under-lock reports."""

    def __init__(self, name: str, reentrant: bool, allow_blocking: bool = False):
        self.name = name
        self.reentrant = reentrant
        self.allow_blocking = allow_blocking
        self._raw = threading.RLock() if reentrant else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        held = _held()
        counts: dict[int, int] = _tls.counts
        me = id(self)
        if blocking and not self.reentrant and counts.get(me, 0) > 0:
            msg = (
                f"same-thread re-entry on non-reentrant lock "
                f"{self.name!r} at {_call_site()} (guaranteed deadlock)"
            )
            _report(msg)
            raise SanitizerError(msg)
        ok = self._raw.acquire(blocking, timeout)
        if not ok:
            return False
        first = counts.get(me, 0) == 0
        counts[me] = counts.get(me, 0) + 1
        if first:
            if held:
                # _call_site walks the stack (expensive); only needed
                # when an ordering edge is actually being recorded
                site = _call_site()
                with _state_lock:
                    for h in _held_names():
                        if h == self.name:
                            continue
                        edge = (h, self.name)
                        _edges.setdefault(h, set()).add(self.name)
                        _witness.setdefault(edge, site)
                        pair = (self.name, h)
                        if pair not in _reported_pairs and _reachable(
                            self.name, h
                        ):
                            _reported_pairs.add(pair)
                            _reported_pairs.add((h, self.name))
                            prior = _witness.get(pair, "?")
                            _reports.append(
                                f"lock-order inversion: {self.name!r} "
                                f"acquired while holding {h!r} at {site}, "
                                f"but {h!r} was previously acquired while "
                                f"holding {self.name!r} at {prior}"
                            )
            held.append(self)
        return True

    def release(self) -> None:
        self._raw.release()
        held = _held()
        counts: dict[int, int] = _tls.counts
        me = id(self)
        n = counts.get(me, 1) - 1
        if n <= 0:
            counts.pop(me, None)
            if self in held:
                held.remove(self)
        else:
            counts[me] = n

    def __enter__(self) -> "SanitizedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        kind = "RLock" if self.reentrant else "Lock"
        return f"<SanitizedLock {kind} {self.name!r}>"


# ---------------------------------------------------------------------------
# factories (the machinery's only lock constructors)

# installed by analysis.schedule while a deterministic scheduler is
# active: (name, reentrant) -> lock, or None to fall through. Lets the
# explorer hand out cooperative locks without the machinery importing
# anything new.
_factory_hook: Optional[callable] = None


def set_factory_hook(fn) -> None:
    global _factory_hook
    _factory_hook = fn


def new_lock(name: str, allow_blocking: bool = False):
    """A non-reentrant lock; cooperative while a schedule explorer is
    active, sanitized when the sanitizer is enabled, a raw
    ``threading.Lock`` (zero overhead) otherwise. ``allow_blocking``
    exempts the lock from blocking-under-lock reports (see
    :class:`SanitizedLock`) — reserve it for coarse try-acquire-only
    mutexes whose body blocks by design."""
    if _factory_hook is not None:
        lock = _factory_hook(name, False, allow_blocking)
        if lock is not None:
            return lock
    if _enabled:
        return SanitizedLock(name, reentrant=False, allow_blocking=allow_blocking)
    return threading.Lock()


def new_rlock(name: str, allow_blocking: bool = False):
    """A reentrant lock; cooperative under an active explorer,
    sanitized when enabled, raw otherwise."""
    if _factory_hook is not None:
        lock = _factory_hook(name, True, allow_blocking)
        if lock is not None:
            return lock
    if _enabled:
        return SanitizedLock(name, reentrant=True, allow_blocking=allow_blocking)
    return threading.RLock()


# ---------------------------------------------------------------------------
# blocking-op probes


def note_blocking(op: str) -> None:
    """Called by known blocking entry points (``Watch.get`` with a
    timeout, remote HTTP requests). Reports when the calling thread
    holds any sanitized lock — the runtime half of the static
    ``blocking-under-lock`` rule."""
    if not _enabled:
        return
    held = [
        lock.name
        for lock in _held()
        if not getattr(lock, "allow_blocking", False)
    ]
    if held:
        _report(
            f"blocking-under-lock: {op} at {_call_site()} while holding "
            + ", ".join(repr(h) for h in held)
        )


def _sleep_probe(secs: float) -> None:
    note_blocking(f"time.sleep({secs!r})")
    _real_sleep(secs)


# ---------------------------------------------------------------------------
# lifecycle


def enabled() -> bool:
    return _enabled


def enable() -> None:
    """Turn the sanitizer on (idempotent): future ``new_lock`` /
    ``new_rlock`` calls return instrumented locks and ``time.sleep``
    gains the held-lock probe. Already-constructed raw locks stay
    raw — enable before building the objects under test."""
    global _enabled, _real_sleep
    if _enabled and _real_sleep is not None:
        return
    _enabled = True
    if _real_sleep is None:
        _real_sleep = time.sleep
        time.sleep = _sleep_probe


def disable() -> None:
    """Turn the sanitizer off and restore ``time.sleep``. Existing
    SanitizedLock instances keep working (they no-op their raw lock
    semantics); only new constructions and probes are affected."""
    global _enabled, _real_sleep
    _enabled = False
    if _real_sleep is not None:
        time.sleep = _real_sleep
        _real_sleep = None


def reset() -> None:
    """Clear accumulated reports and the global order graph (between
    independent probes). Per-thread held state is left alone — live
    locks may legitimately be held elsewhere."""
    with _state_lock:
        _edges.clear()
        _witness.clear()
        _reports.clear()
        _reported_pairs.clear()


def reports() -> list[str]:
    """Accumulated violation reports (empty == clean run)."""
    with _state_lock:
        return list(_reports)


def order_graph() -> dict[str, dict[str, str]]:
    """The live lock-order graph: held-name → {acquired-after-name:
    first witness site}. The ``/debug/locks`` zpage renders this so an
    operator can read the process's actual lock hierarchy (and any
    reported inversions) without reproducing a deadlock first."""
    with _state_lock:
        return {
            src: {
                dst: _witness.get((src, dst), "?")
                for dst in sorted(dsts)
            }
            for src, dsts in sorted(_edges.items())
        }


if _enabled:  # GRAFT_SANITIZE=1 in the environment: arm immediately
    _enabled = False  # force enable() through its patch path
    enable()
