"""Deterministic schedule explorer (loom/shuttle for the control plane).

The sanitizer (``analysis/sanitizer.py``) reports the bad interleaving
a test run HAPPENS to execute; the races PRs 8/10 fixed — group-commit
writers vs the committer vs a snapshot cut, lease-fencing handover,
informer heal-vs-read — were each found by hand-written drills because
no run happened to execute them. This module makes the interleaving a
controlled input:

- **Serialization**: a :class:`Scheduler` runs the scenario's threads
  one-runnable-at-a-time. Participating threads hand control back at
  *schedule points*: every acquire/release of a lock built through the
  sanitizer factories (``new_lock``/``new_rlock`` route to cooperative
  :class:`SchedLock`\\ s while a scheduler is active), the explicit
  :func:`sched_point` markers in the store commit pipeline and the
  informer heal path, patched ``time.sleep``, and the cooperative
  :func:`wait_event`/:func:`queue_get` shims the store's
  ack-after-durable wait and committer drain run through.
- **Exploration**: :func:`explore` runs the scenario under many
  schedules — seeded random walks (each seed fully determines the
  interleaving) and a bounded *systematic* mode that enumerates the
  first divergent choices depth-first. A schedule fails when a thread
  raises, an invariant check fails, the scheduler detects a deadlock
  (no runnable thread while some are blocked), or a blocking op runs
  while a lock is held.
- **Replay**: a failing schedule replays exactly from its seed (or its
  recorded choice trace in systematic mode) — print the seed, hand it
  to :func:`run_schedule`, and step the identical interleaving.

Scenario shape::

    def scenario(sched):
        wal = WriteAheadLog(tmpdir)
        api = APIServer(wal=wal)            # locks are SchedLocks now
        for i in range(3):
            sched.spawn(f"writer-{i}", lambda i=i: api.create(obj(i)))
        def check():
            assert ...                       # post-quiescence invariant
        return check, api.close              # (check, cleanup)

    outcome = schedule.explore(scenario, schedules=100, seed=7)
    assert outcome.found is None, outcome.found

Threads the scenario does not spawn (the store's committer) are
*adopted*: ``thread_started`` registers them as service threads that
participate in scheduling but do not block completion; when the
scenario's threads finish, service threads fall back to their real
blocking behavior so ordinary teardown (``api.close()``) works.

Exploration is activated programmatically (``explore`` /
``run_schedule`` install the factory hook for their duration), so
production processes never pay for it — the module-level shims are a
``None`` check when no exploration is running. ``GRAFT_SCHED=<n>``
(read by the explorer suite, ``make explore``) multiplies the schedule
budgets for deeper sweeps.
"""

from __future__ import annotations

import dataclasses
import queue as _queue_mod
import random
import threading
import time
from typing import Any, Callable, Iterable, Optional

from odh_kubeflow_tpu.analysis import sanitizer as _sanitizer

__all__ = [
    "SchedLock",
    "Scheduler",
    "ScheduleResult",
    "ExploreOutcome",
    "active",
    "explore",
    "queue_get",
    "run_schedule",
    "sched_point",
    "thread_started",
    "wait_event",
]

_active: Optional["Scheduler"] = None
_real_sleep: Optional[Callable[[float], None]] = None

_MISS = object()  # sentinel: cooperative path declined, use the real op


def active() -> Optional["Scheduler"]:
    return _active


# ---------------------------------------------------------------------------
# module-level shims (the product code's entire integration surface)


def sched_point(label: str = "") -> None:
    """A yield marker: under an active scheduler the calling
    participant hands control back and waits to be rescheduled; a
    no-op (one global read) otherwise. Place these where interleaving
    MATTERS — between prepare and apply, between heal steps — not on
    every line; lock acquire/release already yield."""
    s = _active
    if s is not None:
        s._maybe_point(label)


def wait_event(event: threading.Event, timeout: Optional[float] = None) -> bool:
    """``event.wait`` that participates in scheduling: a participant
    blocks cooperatively (other threads keep being scheduled) until
    the event is set; everyone else gets the real wait. A TIMED wait
    stays real even for participants — logical time does not advance
    under serialization, so a cooperative timed wait could never time
    out; keeping it real preserves the production code path."""
    s = _active
    if s is not None and timeout is None:
        got = s._coop_wait_pred(event.is_set, "event.wait")
        if got is not _MISS:
            return event.is_set()
    return event.wait(timeout)


def queue_get(q: "_queue_mod.Queue", timeout: Optional[float] = None):
    """Blocking ``Queue.get`` that participates in scheduling (the
    committer's drain park). Falls back to the real ``get`` for
    non-participants and after the scheduler completes."""
    s = _active
    if s is not None:
        got = s._coop_queue_get(q)
        if got is not _MISS:
            return got
    return q.get(timeout=timeout)


def thread_started(t: Optional[threading.Thread]) -> None:
    """Adopt a thread the product code just started (the WAL
    committer): under an active scheduler, blocks until the thread has
    registered at its first cooperative operation, so the set of
    schedulable threads — and therefore every seeded choice — is
    deterministic. A thread started during the scenario BUILD phase
    (before ``go()``) is recorded and joins the schedule at start,
    before the first choice is made. No-op otherwise."""
    s = _active
    if s is not None and t is not None:
        s._adopt(t)


# ---------------------------------------------------------------------------
# cooperative lock


class SchedLock:
    """Lock handed out by the sanitizer factories while a scheduler is
    active. Participants acquire it cooperatively (yielding at the
    acquire point and blocking without holding the OS thread's turn);
    non-participants fall through to the raw primitive.

    ``threading.Condition`` interop is deliberately partial: the
    ownership probe (``_is_owned``) is answered correctly and a
    non-blocking acquire of a lock the caller already holds returns
    False instead of tripping the re-entry detector — but
    ``Condition.wait`` itself parks on a raw waiter lock the scheduler
    cannot see, so a participant waiting on a Condition freezes its
    schedule (reported as a hang violation with a replayable seed, not
    a silent wrong answer). Scenarios targeting Condition-based
    components (the controller WorkQueue) need a cooperative wait shim
    first; the drilled targets use Events and queues."""

    def __init__(
        self,
        name: str,
        reentrant: bool,
        sched: "Scheduler",
        allow_blocking: bool = False,
    ):
        self.name = name
        self.reentrant = reentrant
        self.allow_blocking = allow_blocking
        self._sched = sched
        self._raw = threading.RLock() if reentrant else threading.Lock()
        # participant ownership, guarded by the scheduler's mutex
        self._owner: Optional[int] = None
        self._depth = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        s = self._sched
        if s is not _active or not s._is_registered():
            return self._raw.acquire(blocking, timeout)
        return s._lock_acquire(self, blocking)

    def release(self) -> None:
        s = self._sched
        me = threading.get_ident()
        if s is _active and self._owner == me:
            s._lock_release(self)
            return
        self._raw.release()

    def _is_owned(self) -> bool:
        """threading.Condition's ownership probe."""
        if self._owner == threading.get_ident():
            return True
        if self._raw.acquire(False):
            self._raw.release()
            return False
        return True  # the stdlib heuristic: unacquirable ≈ owned

    def __enter__(self) -> "SchedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        kind = "RLock" if self.reentrant else "Lock"
        return f"<SchedLock {kind} {self.name!r}>"


# ---------------------------------------------------------------------------
# scheduler


class _Aborted(BaseException):
    """Unwinds participant threads when a schedule is abandoned
    (deadlock, hang, step budget). BaseException so scenario code's
    ``except Exception`` cannot swallow the teardown."""


class _TState:
    __slots__ = (
        "name", "ident", "gate", "ready", "waiting", "finished",
        "service", "where", "thread",
    )

    def __init__(self, name: str, service: bool):
        self.name = name
        self.ident: Optional[int] = None
        self.gate = threading.Event()
        self.ready: Optional[Callable[[], bool]] = None
        self.waiting = False
        self.finished = False
        self.service = service
        self.where = ""
        self.thread: Optional[threading.Thread] = None


class Scheduler:
    """One schedule: a seeded (or trace-forced) serialization of the
    scenario's threads. Create via :func:`run_schedule`/:func:`explore`
    rather than directly — activation patches the sanitizer lock
    factories and ``time.sleep`` for the schedule's duration."""

    def __init__(
        self,
        seed: int = 0,
        force: Optional[Iterable[int]] = None,
        default_first: bool = False,
        step_timeout: float = 20.0,
        max_steps: int = 50_000,
    ):
        self.seed = seed
        self.rng = random.Random(seed)
        self.force = list(force) if force is not None else None
        self.default_first = default_first
        self.step_timeout = step_timeout
        self.max_steps = max_steps
        self._mx = threading.Lock()
        self._cv = threading.Condition(self._mx)
        self._states: dict[int, _TState] = {}
        self._pending: list[tuple[_TState, threading.Thread]] = []
        self._threads: list[threading.Thread] = []
        # machinery threads started during the scenario BUILD phase
        # (before go()); they poll instead of blocking so they can
        # register the moment the schedule starts — go() waits for
        # every one of them before making the first choice
        self._service_expected: list[threading.Thread] = []
        self._ever_started = False
        self._held: dict[int, list[str]] = {}
        self._started = False
        self._aborted = False
        self._done = threading.Event()
        self._steps = 0
        self._choice_i = 0
        # the thread currently holding the turn (None while all are
        # parked); the watchdog uses it to detect a scheduled thread
        # that DIED without yielding (a service thread's loop exiting
        # on a crash) and hand the turn onward
        self._running: Optional[_TState] = None
        # the schedule's identity: (n_runnable, chosen_index, name) per
        # decision — two runs with equal traces ARE the same
        # interleaving
        self.choices: list[tuple[int, int, str]] = []
        self.violations: list[str] = []

    # -- scenario surface ----------------------------------------------------

    def spawn(self, name: str, fn: Callable, *args) -> None:
        """Register a scenario thread. Threads start inside ``go()``
        and run only when scheduled."""
        st = _TState(name, service=False)

        def body():
            me = threading.get_ident()
            st.ident = me
            st.thread = threading.current_thread()
            with self._cv:
                self._states[me] = st
                st.waiting = True
                self._cv.notify_all()
            self._gate_wait(st)
            try:
                fn(*args)
            except _Aborted:
                pass
            except BaseException as e:  # noqa: BLE001 — the violation IS the result
                self._violation(f"thread {name!r} raised {type(e).__name__}: {e}")
            finally:
                self._thread_finished(st)

        t = threading.Thread(target=body, name=f"sched-{name}", daemon=True)
        self._pending.append((st, t))

    def go(self, timeout: float = 60.0) -> None:
        """Run the schedule to quiescence: start the spawned threads,
        then schedule one runnable thread at a time until every
        scenario thread finished (or the schedule fails)."""
        pending, self._pending = self._pending, []
        if not pending:
            return
        self._threads = [t for _, t in pending]
        for _, t in pending:
            t.start()

        def registered() -> bool:
            return all(
                st.ident is not None and st.ident in self._states
                for st, _ in pending
            )

        with self._cv:
            deadline = time.monotonic() + self.step_timeout
            while not registered() and not self._aborted:
                if not self._cv.wait(timeout=0.5) and (
                    time.monotonic() > deadline
                ):
                    self._violation("spawned threads never registered")
                    self._abort_locked()
                    return
            self._started = True
            self._ever_started = True
            # build-phase machinery threads (a committer born before
            # go()) must be IN the schedule before the first choice,
            # or the first batch races the serialized threads
            expected = [
                t
                for t in self._service_expected
                if t.ident is not None and t.is_alive()
            ]
            while (
                not all(t.ident in self._states for t in expected)
                and not self._aborted
            ):
                if not self._cv.wait(timeout=0.5) and (
                    time.monotonic() > deadline + self.step_timeout
                ):
                    self._violation(
                        "build-phase service threads never joined the "
                        "schedule"
                    )
                    self._abort_locked()
                    return
            self._schedule_locked()
        threading.Thread(
            target=self._watchdog, name="sched-watchdog", daemon=True
        ).start()
        if not self._done.wait(timeout):
            self._violation("schedule hung (go() timeout)")
            with self._mx:
                self._abort_locked()
        # let aborted scenario threads finish unwinding (releasing any
        # cooperative locks) before the caller runs cleanup
        for t in self._threads:
            t.join(timeout=self.step_timeout)

    # -- participation -------------------------------------------------------

    def _is_registered(self) -> bool:
        return threading.get_ident() in self._states

    def _ensure_state(self) -> Optional[_TState]:
        """The calling thread's state. A thread the scheduler has
        never seen that reaches a cooperative operation while the
        schedule is driving is machinery-spawned (the WAL committer, a
        pump): it registers as a *service* thread and PARKS here until
        scheduled — ``thread_started`` in the creator waits for
        exactly this registration, so the schedulable set is
        deterministic before the creator takes another step."""
        me = threading.get_ident()
        st = self._states.get(me)
        if st is not None:
            return None if st.finished else st
        name = f"service-{threading.current_thread().name}"
        st = _TState(name, service=True)
        st.ident = me
        st.thread = threading.current_thread()
        with self._cv:
            if self._aborted or not self._started:
                return None
            self._states[me] = st
            st.waiting = True
            self._cv.notify_all()
        self._gate_wait(st)
        return st

    def _adopt(self, t: threading.Thread) -> None:
        ident = t.ident
        if ident is None:
            return
        with self._cv:
            if not self._started and not self._ever_started:
                # build phase: the thread polls (see _coop_queue_get)
                # and registers at go(), before the first choice
                self._service_expected.append(t)
                return
            deadline = time.monotonic() + self.step_timeout
            while (
                ident not in self._states
                and self._started
                and not self._aborted
            ):
                if not self._cv.wait(timeout=0.5) and time.monotonic() > deadline:
                    self._violation(
                        f"adopted thread {t.name!r} never reached a "
                        "cooperative operation"
                    )
                    self._abort_locked()
                    return

    # -- yield machinery -----------------------------------------------------

    def _gate_wait(self, st: _TState) -> None:
        ok = st.gate.wait(timeout=self.step_timeout)
        st.gate.clear()
        if not ok:
            self._violation(f"thread {st.name!r} starved (gate timeout)")
            with self._mx:
                self._abort_locked()
        if self._aborted and not st.service:
            raise _Aborted()

    def _yield(
        self,
        ready: Optional[Callable[[], bool]],
        label: str,
    ) -> bool:
        """Park the calling participant (runnable again when ``ready``
        passes, immediately if None) and schedule the next thread.
        Returns False when the scheduler is no longer driving (caller
        falls back to real blocking behavior)."""
        me = threading.get_ident()
        with self._mx:
            st = self._states.get(me)
            if (
                st is None
                or st.finished
                or not self._started
                or self._aborted
            ):
                return False
            if self._running is st:
                self._running = None
            st.ready = ready
            st.waiting = True
            st.where = label
            self._schedule_locked()
        self._gate_wait(st)
        return True

    def _maybe_point(self, label: str) -> None:
        st = self._ensure_state()
        if st is not None:
            self._yield(None, label or "sched_point")

    def _coop_wait_pred(self, pred: Callable[[], bool], label: str):
        st = self._ensure_state()
        if st is None:
            return _MISS
        self._note_blocking(label)
        while True:
            with self._mx:
                driving = self._started and not self._aborted
            if not driving or _active is not self:
                return _MISS
            if pred():
                return True
            if not self._yield(
                lambda: pred() or not self._started, label
            ):
                return _MISS

    def _coop_queue_get(self, q: "_queue_mod.Queue"):
        while True:
            with self._mx:
                aborted = self._aborted
                started = self._started
                ever = self._ever_started
            if aborted or (ever and not started) or _active is not self:
                # schedule over OR the scheduler was deactivated before
                # ever starting (build() raised): real blocking
                # behavior — never leave a poller spinning
                return _MISS
            if not started:
                # scheduler active but not yet driving (scenario build
                # phase): serve in short real polls so the thread can
                # join the schedule the moment go() starts
                try:
                    return q.get(timeout=0.005)
                except _queue_mod.Empty:
                    continue
            st = self._ensure_state()  # registers + parks adoptees
            if st is None:
                return _MISS
            try:
                return q.get_nowait()
            except _queue_mod.Empty:
                pass
            if not self._yield(
                lambda: not q.empty() or not self._started, "queue.get"
            ):
                return _MISS

    # -- locks ---------------------------------------------------------------

    def _lock_acquire(self, lock: SchedLock, blocking: bool) -> bool:
        me = threading.get_ident()
        st = self._states.get(me)
        if st is None or st.finished:
            return lock._raw.acquire(blocking)
        with self._mx:
            if lock._owner == me:
                if lock.reentrant:
                    lock._depth += 1
                    return True
                if not blocking:
                    # a try-acquire of one's own lock is a probe
                    # (Condition._is_owned), not an imminent deadlock
                    return False
                self._violation(
                    f"same-thread re-entry on non-reentrant lock "
                    f"{lock.name!r} in {st.name!r} (guaranteed deadlock)"
                )
                self._abort_locked()
                raise _Aborted()
        # the acquire attempt is itself a schedule point: whether a
        # contender gets in first is exactly what exploration varies
        self._yield(None, f"acquire:{lock.name}")
        while True:
            with self._mx:
                if lock._owner is None and lock._raw.acquire(blocking=False):
                    lock._owner = me
                    lock._depth = 1
                    if not lock.allow_blocking:
                        # allow_blocking locks are exempt from the
                        # blocking-under-lock check (_held feeds only it)
                        self._held.setdefault(me, []).append(lock.name)
                    return True
                if not blocking:
                    return False
            def _acquirable() -> bool:
                # probe the RAW lock too: a non-participant holder
                # (a free-running pump that never reached a shim)
                # leaves _owner None while _raw is held — waking on
                # _owner alone would busy-spin the scheduler and make
                # the choice trace OS-timing-dependent
                if lock._owner is not None:
                    return False
                if lock._raw.acquire(False):
                    lock._raw.release()
                    return True
                return False

            if not self._yield(
                lambda: _acquirable() or not self._started,
                f"blocked:{lock.name}",
            ):
                return lock._raw.acquire(blocking)

    def _lock_release(self, lock: SchedLock) -> None:
        me = threading.get_ident()
        with self._mx:
            lock._depth -= 1
            if lock._depth > 0:
                return
            lock._owner = None
            held = self._held.get(me)
            if held and lock.name in held:
                held.remove(lock.name)
        lock._raw.release()
        # release is a schedule point too: a waiter may run next
        self._yield(None, f"release:{lock.name}")

    def _note_blocking(self, op: str) -> None:
        """Blocking op about to run on a participant: a violation when
        any cooperative lock is held (the _RateLimiter bug shape)."""
        held = self._held.get(threading.get_ident())
        if held:
            self._violation(
                f"blocking-under-lock: {op} while holding "
                + ", ".join(repr(h) for h in held)
            )

    # -- scheduling core -----------------------------------------------------

    def _violation(self, msg: str) -> None:
        # lock-free on purpose: violations are recorded from inside
        # _mx-holding paths (deadlock detection, adoption timeouts) —
        # list.append is atomic under the GIL
        self.violations.append(msg)

    def _abort_locked(self) -> None:
        if self._aborted:
            return
        self._aborted = True
        self._started = False
        for st in self._states.values():
            st.gate.set()
        self._done.set()

    def _thread_finished(self, st: _TState) -> None:
        with self._mx:
            st.finished = True
            st.waiting = False
            if self._running is st:
                self._running = None
            if all(
                s.finished for s in self._states.values() if not s.service
            ) and not self._pending:
                self._complete_locked()
            else:
                self._schedule_locked()

    def _complete_locked(self) -> None:
        self._started = False
        for st in self._states.values():
            if st.service and st.waiting:
                st.gate.set()  # fall back to real blocking behavior
        self._done.set()

    def _schedule_locked(self) -> None:
        if self._aborted or not self._started:
            return
        self._steps += 1
        if self._steps > self.max_steps:
            self._violation(f"step budget exceeded ({self.max_steps})")
            self._abort_locked()
            return
        waiting = sorted(
            (
                s
                for s in self._states.values()
                if s.waiting and not s.finished
            ),
            key=lambda s: s.name,
        )
        runnable = [s for s in waiting if s.ready is None or s.ready()]
        if not runnable:
            blocked = [s for s in waiting if not s.service]
            if blocked:
                self._violation(
                    "deadlock: no runnable thread; blocked: "
                    + ", ".join(f"{s.name}@{s.where}" for s in blocked)
                )
                self._abort_locked()
            # only idle service threads left: nothing to do until a
            # scenario thread arrives (or completion/teardown wakes them)
            return
        if len(runnable) == 1:
            idx = 0
        elif self.force is not None and self._choice_i < len(self.force):
            idx = self.force[self._choice_i] % len(runnable)
        elif self.default_first:
            idx = 0
        else:
            idx = self.rng.randrange(len(runnable))
        self._choice_i += 1
        chosen = runnable[idx]
        self.choices.append((len(runnable), idx, chosen.name))
        chosen.waiting = False
        chosen.ready = None
        self._running = chosen
        self._held.setdefault(chosen.ident or 0, [])
        chosen.gate.set()

    def _watchdog(self) -> None:
        """Detect a scheduled thread that exited without yielding —
        spawned bodies report via ``_thread_finished``, but an adopted
        service thread whose loop returns (the committer after a
        CrashPoint) just dies. Hand the turn onward so the schedule
        keeps its determinism: at the hand-off every other thread is
        parked, so the runnable set is exactly what the dead thread
        left behind."""
        while not self._done.is_set():
            (_real_sleep or time.sleep)(0.005)
            with self._mx:
                r = self._running
                if (
                    self._started
                    and not self._aborted
                    and r is not None
                    and r.thread is not None
                    and not r.thread.is_alive()
                ):
                    r.finished = True
                    r.waiting = False
                    self._running = None
                    if all(
                        s.finished
                        for s in self._states.values()
                        if not s.service
                    ):
                        self._complete_locked()
                    else:
                        self._schedule_locked()


# ---------------------------------------------------------------------------
# activation (factory + sleep interposition)


def _activate(sched: Scheduler) -> None:
    global _active, _real_sleep
    if _active is not None:
        raise RuntimeError("a scheduler is already active in this process")
    _active = sched
    _sanitizer.set_factory_hook(
        lambda name, reentrant, allow_blocking=False: SchedLock(
            name, reentrant, sched, allow_blocking
        )
    )
    if _real_sleep is None:
        _real_sleep = time.sleep
        time.sleep = _sched_sleep


def _deactivate() -> None:
    global _active, _real_sleep
    _active = None
    _sanitizer.set_factory_hook(None)
    if _real_sleep is not None:
        time.sleep = _real_sleep
        _real_sleep = None


def _sched_sleep(secs: float) -> None:
    s = _active
    if s is not None and s._is_registered():
        # a participant's sleep is a schedule point, not wall time —
        # and sleeping with a lock held is the classic stall bug
        s._note_blocking(f"time.sleep({secs!r})")
        st = s._ensure_state()
        if st is not None:
            s._yield(None, "time.sleep")
        return
    rs = _real_sleep
    (rs or time.sleep)(secs)


# ---------------------------------------------------------------------------
# exploration harness


@dataclasses.dataclass
class ScheduleResult:
    """One executed schedule: its seed (or forced trace), the decision
    trace actually taken, and any violations."""

    seed: int
    violations: list[str]
    choices: list[tuple[int, int, str]]
    steps: int
    forced: Optional[list[int]] = None

    @property
    def failed(self) -> bool:
        return bool(self.violations)

    def render(self) -> str:
        head = (
            f"schedule seed={self.seed}"
            if self.forced is None
            else f"schedule trace={self.forced}"
        )
        if not self.failed:
            return f"{head}: ok ({self.steps} steps)"
        return (
            f"{head}: FAILED ({self.steps} steps)\n  "
            + "\n  ".join(self.violations)
        )


@dataclasses.dataclass
class ExploreOutcome:
    found: Optional[ScheduleResult]  # first failing schedule, or None
    schedules_run: int

    def __str__(self) -> str:
        if self.found is None:
            return f"explored {self.schedules_run} schedules: all green"
        return (
            f"explored {self.schedules_run} schedules, found failure:\n"
            + self.found.render()
        )


def run_schedule(
    build: Callable[[Scheduler], Any],
    seed: int = 0,
    force: Optional[list[int]] = None,
    default_first: bool = False,
    step_timeout: float = 20.0,
    max_steps: int = 50_000,
    go_timeout: float = 60.0,
) -> ScheduleResult:
    """Execute ONE schedule of the scenario. ``build(sched)`` creates
    the objects under test (their sanitizer-factory locks become
    cooperative), spawns threads via ``sched.spawn``, and returns an
    invariant check callable, a ``(check, cleanup)`` pair, or None.
    The check runs after quiescence; cleanup always runs (schedule the
    store's ``close`` there so adopted committer threads exit)."""
    sched = Scheduler(
        seed=seed,
        force=force,
        default_first=default_first,
        step_timeout=step_timeout,
        max_steps=max_steps,
    )
    _activate(sched)
    check = cleanup = None
    try:
        out = build(sched)
        if isinstance(out, tuple):
            check, cleanup = out
        else:
            check = out
        sched.go(timeout=go_timeout)
        if check is not None and not sched.violations:
            try:
                check()
            except AssertionError as e:
                sched.violations.append(f"invariant violated: {e}")
            except _Aborted:
                pass
            except BaseException as e:  # noqa: BLE001 — ANY check failure
                # (CrashPoint from a crash-drill recovery included) is
                # the schedule's result: letting it escape would lose
                # the seed/trace exactly when a real bug was found
                sched.violations.append(
                    f"invariant check raised {type(e).__name__}: {e}"
                )
    finally:
        _deactivate()
        if cleanup is not None:
            try:
                cleanup()
            except Exception:  # noqa: BLE001 — teardown must not mask the schedule result
                pass
    return ScheduleResult(
        seed=seed,
        violations=list(sched.violations),
        choices=list(sched.choices),
        steps=sched._steps,
        forced=list(force) if force is not None else None,
    )


def explore(
    build: Callable[[Scheduler], Any],
    schedules: int = 100,
    seed: int = 0,
    mode: str = "random",
    systematic_depth: int = 12,
    **run_kwargs,
) -> ExploreOutcome:
    """Run up to ``schedules`` interleavings of the scenario and stop
    at the first failure.

    - ``mode="random"``: seeded random walks with seeds ``seed,
      seed+1, …`` — every schedule independently replayable from its
      seed.
    - ``mode="systematic"``: bounded DFS over the first
      ``systematic_depth`` multi-way decisions: run the leftmost
      schedule, then branch each recorded decision point in turn
      (stateless model checking, shuttle's default posture). Failures
      replay from the recorded ``forced`` trace.
    """
    if mode == "random":
        for i in range(schedules):
            res = run_schedule(build, seed=seed + i, **run_kwargs)
            if res.failed:
                return ExploreOutcome(found=res, schedules_run=i + 1)
        return ExploreOutcome(found=None, schedules_run=schedules)
    if mode != "systematic":
        raise ValueError(f"unknown mode {mode!r}")
    stack: list[list[int]] = [[]]
    runs = 0
    seen: set[tuple[int, ...]] = set()
    while stack and runs < schedules:
        prefix = stack.pop()
        res = run_schedule(
            build, seed=seed, force=prefix, default_first=True, **run_kwargs
        )
        runs += 1
        if res.failed:
            return ExploreOutcome(found=res, schedules_run=runs)
        # branch every undecided MULTI-WAY point inside the depth
        # bound — 1-way (forced) steps consume a trace position but
        # not depth, so long single-runnable stretches (a committer
        # draining alone) don't eat the divergence budget
        taken = [idx for (_, idx, _) in res.choices]
        multiway = 0
        for p, (n, idx, _name) in enumerate(res.choices):
            if n <= 1:
                continue
            if multiway >= systematic_depth:
                break
            multiway += 1
            if p < len(prefix):
                continue  # already forced: don't re-branch
            for alt in range(n):
                if alt == idx:
                    continue
                branch = taken[:p] + [alt]
                key = tuple(branch)
                if key not in seen:
                    seen.add(key)
                    stack.append(branch)
    return ExploreOutcome(found=None, schedules_run=runs)
