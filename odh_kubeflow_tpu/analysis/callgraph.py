"""Package-wide call graph + lock-context dataflow for graftlint.

The per-function rules in ``analysis/rules.py`` see one AST at a time;
the concurrency bugs that survived PRs 7–10 never lived in one
function. A ``with lock:`` body that calls a helper that calls a
helper that fsyncs stalls every contender just as surely as an inline
``time.sleep`` — but only a whole-program view can see the chain. This
module builds that view, conservatively:

- **Function table**: every module-level function and class method in
  the analyzed file set, keyed ``"<rel>::<Class.>name"``.
- **Call resolution** (deliberately precise-over-complete — an
  interprocedural lint that guesses wrong gets suppressed wholesale):
  plain names resolve through the module's own functions and its
  ``from pkg.mod import name`` imports; ``self.m()``/``cls.m()``
  resolve within the enclosing class (and package-resolvable bases);
  ``mod.f()`` resolves through module import aliases; any other
  ``obj.m()`` resolves by method name only when the whole program
  defines at most :data:`AMBIG_LIMIT` methods called ``m`` (unique-ish
  class-hierarchy analysis). Everything else is left unresolved.
- **Lock identity**: ``with <lockish>:`` regions are named via the
  sanitizer factory calls (``self._lock = new_rlock("apiserver.store")``
  maps ``self._lock`` in that class to ``"apiserver.store"``), falling
  back to ``Class.attr`` — lockdep semantics, every instance of a lock
  role shares a rank, matching ``analysis/sanitizer.py``.
- **Summaries**: per function, the blocking leaf calls and lock
  acquisitions reachable through resolved calls, each with the full
  witness call chain — the rules render those chains into findings.

Blocking leaves are the platform's known thread-stallers: ``time.sleep``,
``os.fsync``, socket/HTTP IO (``urlopen``/``getresponse``/``recv``/
``sendall``/``connect``/``accept``), and method ``get(timeout=…)``
(queue/Watch drains). ``Condition.wait`` is exempt (it releases the
lock while blocked), and ``asyncio.sleep``/awaited calls are never
blocking (they yield the loop, which is the point).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable, Iterator, Optional

from odh_kubeflow_tpu.analysis.graftlint import SourceFile

PACKAGE = "odh_kubeflow_tpu"

# an attribute call `obj.m()` with an untypable receiver resolves by
# method name alone when at most this many classes define `m`; beyond
# it the call is left unresolved (precision over completeness)
AMBIG_LIMIT = 3

# markers identifying a with-context expression as a lock (shared
# vocabulary with rules.BlockingUnderLockRule / the sanitizer names)
LOCKISH_MARKERS = ("lock", "mutex", "_cv", "cond")

_WAIT_EXEMPT = frozenset({"wait", "wait_for"})

# method names that collide with builtin container/str/file/queue/
# thread protocol methods NEVER resolve by name alone: `reports.append`
# is a list append, not WriteAheadLog.append, no matter how few classes
# define the name. (self.m() and mod.f() resolution is unaffected.)
_BUILTIN_METHODS = frozenset(
    name
    for t in (list, dict, set, frozenset, tuple, str, bytes, bytearray)
    for name in dir(t)
    if not name.startswith("__")
) | frozenset(
    {
        "put", "put_nowait", "get_nowait", "qsize", "empty", "task_done",
        "start", "join", "acquire", "release", "wait", "notify",
        "notify_all", "set", "clear", "is_set", "locked", "close",
        "flush", "fileno", "readline", "seek", "tell", "cancel", "result",
    }
)
_SOCKET_TERMINALS = frozenset(
    {"urlopen", "getresponse", "recv", "sendall", "accept", "connect"}
)
_FACTORY_TERMINALS = frozenset({"new_lock", "new_rlock"})


def _attr_chain(node: ast.AST) -> list[str]:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return []


def blocking_leaf(call: ast.Call, awaited: bool = False) -> Optional[str]:
    """What this call blocks on, or None. ``awaited`` calls yield the
    event loop instead of a thread and are never blocking."""
    if awaited:
        return None
    chain = _attr_chain(call.func)
    if not chain:
        return None
    terminal = chain[-1]
    head = [c.lower() for c in chain[:-1]]
    if terminal == "sleep":
        if "asyncio" in head:
            return None
        return "time.sleep"
    if terminal == "fsync":
        return "os.fsync"
    if terminal in _SOCKET_TERMINALS and terminal != "connect":
        return f"socket/HTTP {terminal}"
    if terminal == "connect" and any("socket" in h or "conn" in h for h in head):
        return "socket connect"
    if terminal == "request" and any("http" in h for h in head):
        return "http client request"
    if (
        terminal == "get"
        and len(chain) > 1
        and any(
            kw.arg == "timeout"
            and not (isinstance(kw.value, ast.Constant) and kw.value.value is None)
            for kw in call.keywords
        )
    ):
        return "blocking get(timeout=…)"
    return None


def is_lockish(expr: ast.AST) -> bool:
    chain = _attr_chain(expr)
    if not chain:
        return False
    terminal = chain[-1].lower()
    return any(m in terminal for m in LOCKISH_MARKERS)


@dataclasses.dataclass
class CallSite:
    node: ast.Call
    targets: tuple[str, ...]  # resolved candidate quals (may be empty)
    label: str  # human-readable callee for chain rendering


@dataclasses.dataclass
class LockSite:
    lock: str
    node: ast.AST  # the with statement


@dataclasses.dataclass
class Region:
    """One ``with <lock>:`` critical section inside a function.
    Direct blocking leaves inside it are the per-file
    ``blocking-under-lock`` rule's job; a region only carries what the
    interprocedural rules consume — calls and nested acquisitions."""

    lock: str
    node: ast.With
    calls: list[CallSite]
    nested: list[LockSite]  # lock acquisitions lexically inside


@dataclasses.dataclass
class FuncInfo:
    qual: str
    src: SourceFile
    node: ast.AST
    cls: Optional[str]
    is_async: bool
    calls: list[CallSite] = dataclasses.field(default_factory=list)
    blocking: list[tuple[str, ast.AST]] = dataclasses.field(default_factory=list)
    acquires: list[LockSite] = dataclasses.field(default_factory=list)
    regions: list[Region] = dataclasses.field(default_factory=list)

    @property
    def short(self) -> str:
        return self.qual.split("::", 1)[1]


@dataclasses.dataclass
class Step:
    """One hop of a witness chain: a function plus the site inside it
    where the next hop (or the leaf op) happens."""

    func: str  # short name of the function this step is IN
    path: str
    line: int
    what: str  # callee label or leaf description


Chain = tuple  # tuple[Step, ...]


def _mod_rel(module: str) -> Optional[str]:
    """``odh_kubeflow_tpu.machinery.store`` → ``machinery/store.py``."""
    if module == PACKAGE:
        return "__init__.py"
    prefix = PACKAGE + "."
    if not module.startswith(prefix):
        return None
    return module[len(prefix):].replace(".", "/") + ".py"


class Program:
    """The analyzed file set plus its call graph and lock dataflow."""

    def __init__(self, sources: Iterable[SourceFile]):
        self.sources: dict[str, SourceFile] = {s.rel: s for s in sources}
        self.functions: dict[str, FuncInfo] = {}
        # method name → quals of every class method with that name
        self._methods: dict[str, list[str]] = {}
        # (rel, name) → qual for module-level functions
        self._module_funcs: dict[tuple[str, str], str] = {}
        # rel → {local alias → module rel} for module imports
        self._mod_aliases: dict[str, dict[str, str]] = {}
        # rel → names bound by NON-package imports (os, time, urllib…):
        # attribute calls rooted at these must never fall through to
        # method-name CHA (os.fsync is not some class's fsync method)
        self._foreign_roots: dict[str, set[str]] = {}
        # rel → {local name → (module rel, original name)} for
        # from-imports of functions
        self._from_imports: dict[str, dict[str, tuple[str, str]]] = {}
        # rel → {class → tuple of base class names}
        self._bases: dict[str, dict[str, tuple[str, ...]]] = {}
        # (class, attr) → sanitizer factory lock name; attr → names
        self._lock_names: dict[tuple[str, str], str] = {}
        self._lock_attr_names: dict[str, set[str]] = {}
        self._reach_blocking: dict[str, dict[str, Chain]] = {}
        self._reach_acquires: dict[str, dict[str, Chain]] = {}
        for src in self.sources.values():
            self._index_file(src)
        for src in self.sources.values():
            self._analyze_file(src)

    # -- indexing ------------------------------------------------------------

    def _index_file(self, src: SourceFile) -> None:
        aliases: dict[str, str] = {}
        froms: dict[str, tuple[str, str]] = {}
        foreign: set[str] = set()
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    rel = _mod_rel(a.name)
                    if rel is not None:
                        aliases[a.asname or a.name.rsplit(".", 1)[-1]] = rel
                    else:
                        foreign.add(a.asname or a.name.split(".", 1)[0])
            elif isinstance(node, ast.ImportFrom) and node.module:
                mod_rel = _mod_rel(node.module)
                for a in node.names:
                    # `from pkg.machinery import backoff` imports a
                    # MODULE; `from pkg.machinery.store import
                    # paged_list_all` imports a function — try both
                    sub_rel = _mod_rel(f"{node.module}.{a.name}")
                    if sub_rel is not None and sub_rel in self.sources:
                        aliases[a.asname or a.name] = sub_rel
                    elif mod_rel is not None:
                        froms[a.asname or a.name] = (mod_rel, a.name)
                    else:
                        foreign.add(a.asname or a.name)
        self._mod_aliases[src.rel] = aliases
        self._from_imports[src.rel] = froms
        self._foreign_roots[src.rel] = foreign

        bases: dict[str, tuple[str, ...]] = {}
        for node in src.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{src.rel}::{node.name}"
                self._add_func(qual, src, node, None)
                self._module_funcs[(src.rel, node.name)] = qual
            elif isinstance(node, ast.ClassDef):
                bases[node.name] = tuple(
                    b.id for b in node.bases if isinstance(b, ast.Name)
                )
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        qual = f"{src.rel}::{node.name}.{item.name}"
                        self._add_func(qual, src, item, node.name)
                        self._methods.setdefault(item.name, []).append(qual)
                self._index_lock_factories(src, node)
        self._bases[src.rel] = bases

    def _add_func(self, qual: str, src: SourceFile, node, cls) -> None:
        self.functions[qual] = FuncInfo(
            qual=qual,
            src=src,
            node=node,
            cls=cls,
            is_async=isinstance(node, ast.AsyncFunctionDef),
        )

    def _index_lock_factories(self, src: SourceFile, cls: ast.ClassDef) -> None:
        """``self.X = new_lock("name")`` / ``new_rlock`` assignments
        anywhere in the class map (class, X) → the sanitizer name —
        the same rank the runtime order graph uses."""
        for node in ast.walk(cls):
            if not (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
            ):
                continue
            chain = _attr_chain(node.value.func)
            if not chain or chain[-1] not in _FACTORY_TERMINALS:
                continue
            if not (
                node.value.args
                and isinstance(node.value.args[0], ast.Constant)
                and isinstance(node.value.args[0].value, str)
            ):
                continue
            name = node.value.args[0].value
            for target in node.targets:
                tchain = _attr_chain(target)
                if len(tchain) == 2 and tchain[0] == "self":
                    self._lock_names[(cls.name, tchain[1])] = name
                    self._lock_attr_names.setdefault(tchain[1], set()).add(name)

    # -- lock identity -------------------------------------------------------

    def lock_id(self, expr: ast.AST, cls: Optional[str]) -> Optional[str]:
        """The rank name for a with-context lock expression, or None
        when the expression is not lockish."""
        if isinstance(expr, ast.Call):
            # `with self._lock_for(k):` etc. — name by the call's
            # terminal when lockish
            chain = _attr_chain(expr.func)
            if chain and any(m in chain[-1].lower() for m in LOCKISH_MARKERS):
                return chain[-1]
            return None
        if not is_lockish(expr):
            return None
        chain = _attr_chain(expr)
        terminal = chain[-1]
        if len(chain) == 2 and chain[0] in ("self", "cls") and cls:
            named = self._lock_names.get((cls, terminal))
            if named is not None:
                return named
            return f"{cls}.{terminal}"
        # longer chains (`self._wal.io_lock`) and bare names: a unique
        # factory name for the attr wins, else the bare terminal
        names = self._lock_attr_names.get(terminal)
        if names is not None and len(names) == 1:
            return next(iter(names))
        return terminal

    # -- call resolution -----------------------------------------------------

    def _method_in_class(self, rel: str, cls: str, name: str) -> Optional[str]:
        qual = f"{rel}::{cls}.{name}"
        if qual in self.functions:
            return qual
        for base in self._bases.get(rel, {}).get(cls, ()):  # same-file bases
            found = self._method_in_class(rel, base, name)
            if found is not None:
                return found
        return None

    def resolve(self, call: ast.Call, fn: FuncInfo) -> tuple[str, ...]:
        f = call.func
        rel = fn.src.rel
        if isinstance(f, ast.Name):
            local = self._module_funcs.get((rel, f.id))
            if local is not None:
                return (local,)
            imported = self._from_imports.get(rel, {}).get(f.id)
            if imported is not None:
                target = self._module_funcs.get(imported)
                if target is not None:
                    return (target,)
            return ()
        if not isinstance(f, ast.Attribute):
            return ()
        chain = _attr_chain(f)
        if not chain:
            return ()
        terminal = chain[-1]
        if len(chain) == 2 and chain[0] in ("self", "cls") and fn.cls:
            found = self._method_in_class(rel, fn.cls, terminal)
            return (found,) if found is not None else ()
        if len(chain) == 2:
            mod = self._mod_aliases.get(rel, {}).get(chain[0])
            if mod is not None:
                target = self._module_funcs.get((mod, terminal))
                return (target,) if target is not None else ()
        if chain[0] in self._foreign_roots.get(rel, ()):
            # rooted at a non-package import (os.fsync, time.*): the
            # callee is stdlib/third-party, never a package method
            return ()
        if terminal in _BUILTIN_METHODS:
            return ()
        candidates = self._methods.get(terminal, [])
        if 1 <= len(candidates) <= AMBIG_LIMIT:
            return tuple(sorted(candidates))
        return ()

    # -- per-function analysis ----------------------------------------------

    def _analyze_file(self, src: SourceFile) -> None:
        for fn in self.functions.values():
            if fn.src is not src:
                continue
            self._analyze_func(fn)

    def _iter_live(self, node: ast.AST) -> Iterator[tuple[ast.AST, bool]]:
        """(descendant, awaited) pairs executing in this function —
        nested defs/lambdas run later and are pruned."""
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            if isinstance(child, ast.Await):
                for sub in ast.iter_child_nodes(child):
                    yield sub, True
                    yield from self._iter_live(sub)
                continue
            yield child, False
            yield from self._iter_live(child)

    def _call_label(self, call: ast.Call) -> str:
        chain = _attr_chain(call.func)
        return ".".join(chain) if chain else "<call>"

    def _analyze_func(self, fn: FuncInfo) -> None:
        for node, awaited in self._iter_live(fn.node):
            if isinstance(node, ast.Call):
                leaf = blocking_leaf(node, awaited)
                chain = _attr_chain(node.func)
                if chain and chain[-1] in _WAIT_EXEMPT:
                    leaf = None
                if leaf is not None:
                    fn.blocking.append((leaf, node))
                fn.calls.append(
                    CallSite(node, self.resolve(node, fn), self._call_label(node))
                )
            elif isinstance(node, ast.With):
                locks = [
                    lock
                    for item in node.items
                    if (lock := self.lock_id(item.context_expr, fn.cls))
                    is not None
                ]
                for idx, lock in enumerate(locks):
                    fn.acquires.append(LockSite(lock, node))
                    region = self._region(fn, lock, node)
                    # `with a, b:` acquires left-to-right: each earlier
                    # item holds while the later ones are taken — the
                    # same ordering edges the nested spelling records
                    for later in locks[idx + 1:]:
                        if later != lock:
                            region.nested.append(LockSite(later, node))
                    fn.regions.append(region)

    def _region(self, fn: FuncInfo, lock: str, w: ast.With) -> Region:
        calls: list[CallSite] = []
        nested: list[LockSite] = []
        for stmt in w.body:
            if isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                # a def/lambda DEFINED under the lock runs later,
                # outside it (_iter_live prunes these one level down;
                # the seed statement itself must be pruned too)
                continue
            for node, _awaited in [(stmt, False), *self._iter_live(stmt)]:
                if isinstance(node, ast.Call):
                    calls.append(
                        CallSite(
                            node, self.resolve(node, fn), self._call_label(node)
                        )
                    )
                elif isinstance(node, ast.With) and node is not w:
                    for item in node.items:
                        inner = self.lock_id(item.context_expr, fn.cls)
                        if inner is not None:
                            nested.append(LockSite(inner, node))
        return Region(lock, w, calls, nested)

    # -- transitive summaries ------------------------------------------------

    def reach_blocking(self, qual: str) -> dict[str, Chain]:
        """Blocking leaves reachable from ``qual`` through resolved
        calls (the function's own leaves included): leaf description →
        witness chain."""
        return self._reach(qual, self._reach_blocking, "blocking")

    def reach_acquires(self, qual: str) -> dict[str, Chain]:
        """Locks acquired by ``qual`` or anything it transitively
        calls: lock rank → witness chain."""
        return self._reach(qual, self._reach_acquires, "acquires")

    def _reach(self, qual: str, memo: dict, what: str) -> dict[str, Chain]:
        out, _pending = self._reach_rec(qual, memo, what, set())
        return out

    def _reach_rec(
        self, qual: str, memo: dict, what: str, stack: set[str]
    ) -> tuple[dict[str, Chain], set[str]]:
        """DFS with SCC-aware memoization: a summary computed while a
        call cycle is still open is INCOMPLETE for the cycle's inner
        members (they never see facts flowing through the back edge),
        so only the DFS root of its cycle — where every branch has
        been merged — is cached; inner members recompute as roots of
        their own later queries. Returns (summary, pending back-edge
        targets still on the stack)."""
        if qual in memo:
            return memo[qual], set()
        if qual in stack:
            return {}, {qual}
        fn = self.functions.get(qual)
        if fn is None:
            memo[qual] = {}
            return memo[qual], set()
        stack.add(qual)
        out: dict[str, Chain] = {}
        pending: set[str] = set()
        if what == "blocking":
            for desc, node in fn.blocking:
                out.setdefault(
                    desc,
                    (Step(fn.short, fn.src.rel, node.lineno, desc),),
                )
        else:
            for site in fn.acquires:
                out.setdefault(
                    site.lock,
                    (
                        Step(
                            fn.short,
                            fn.src.rel,
                            site.node.lineno,
                            f"acquires {site.lock!r}",
                        ),
                    ),
                )
        for cs in fn.calls:
            for target in cs.targets:
                if target == qual:
                    continue
                sub, sub_pending = self._reach_rec(target, memo, what, stack)
                pending |= sub_pending
                for key, chain in sub.items():
                    out.setdefault(
                        key,
                        (Step(fn.short, fn.src.rel, cs.node.lineno, cs.label),)
                        + chain,
                    )
        stack.discard(qual)
        pending.discard(qual)
        if not pending:
            memo[qual] = out
        return out, pending


def render_chain(chain: Chain) -> str:
    """``f (store.py:12) → g (wal.py:290) → os.fsync`` — the witness
    path a finding carries."""
    parts = []
    for step in chain:
        fname = step.path.rsplit("/", 1)[-1]
        parts.append(f"{step.func} ({fname}:{step.line})")
    if chain:
        parts.append(chain[-1].what)
    return " → ".join(parts)


def build_program(sources: Iterable[SourceFile]) -> Program:
    return Program(sources)
