"""graftlint: AST-based checker for platform invariants.

The control plane's correctness rests on conventions the compiler
never sees — frozen cache objects must not be mutated without
``mutable()``, hot paths must not issue bare cluster-wide lists,
metric names must follow controller-runtime conventions, reconcile
loops must not swallow errors, and nothing blocking may run under a
store/cache lock. Each convention is a :class:`Rule` over the Python
``ast`` (stdlib only, no third-party deps); this module is the
framework — the registry, per-line suppression syntax, file/rule
allowlists, and the findings report. The platform's rules live in
``analysis/rules.py`` and self-register on import.

Usage::

    python -m odh_kubeflow_tpu.analysis            # whole package, exit 1 on findings
    python -m odh_kubeflow_tpu.analysis --select uncached-list path/to/file.py

Suppression::

    something_flagged()  # graftlint: disable=<rule>[,<rule2>] <reason>

applies to every finding whose line falls inside the suppressing
statement (so a multi-line call needs the marker on any of its lines).
``disable=all`` silences every rule on that line. A whole file opts
out of one rule with ``# graftlint: disable-file=<rule> <reason>`` on
any line (reserve this for generated or fixture code). The legacy
``# uncached-ok: <reason>`` marker is honoured by the
``uncached-list`` rule for continuity with the old grep-based scan.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import os
import re
import sys
from typing import Iterable, Iterator, Optional

PACKAGE = "odh_kubeflow_tpu"

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=([A-Za-z0-9_,-]+)(?:\s+(?P<reason>.*))?"
)
_SUPPRESS_FILE_RE = re.compile(
    r"#\s*graftlint:\s*disable-file=([A-Za-z0-9_,-]+)(?:\s+(?P<reason>.*))?"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location. ``end_line`` is the
    last line of the offending statement — suppression markers
    anywhere in the span apply (multi-line calls put the comment where
    it reads best)."""

    rule: str
    path: str  # package-relative posix path
    line: int
    message: str
    severity: str = "error"
    end_line: int = 0

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.severity}: [{self.rule}] {self.message}"


class SourceFile:
    """A parsed source file plus the comment-level context rules need
    (suppression markers, section = first directory under the
    package)."""

    def __init__(self, path: str, rel: str, text: str):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        parts = self.rel.split("/")
        self.section = parts[0] if len(parts) > 1 else ""
        self._line_disables: dict[int, set[str]] = {}
        self._file_disables: set[str] = set()
        for lineno, line in enumerate(self.lines, 1):
            m = _SUPPRESS_RE.search(line)
            if m:
                self._line_disables[lineno] = {
                    r.strip() for r in m.group(1).split(",") if r.strip()
                }
            m = _SUPPRESS_FILE_RE.search(line)
            if m:
                self._file_disables.update(
                    r.strip() for r in m.group(1).split(",") if r.strip()
                )

    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def suppressed(self, rule: str, start: int, end: Optional[int] = None) -> bool:
        """Whether ``rule`` is disabled for lines ``start..end`` (a
        statement's span) — by a line marker inside the span or a
        file-level marker."""
        if rule in self._file_disables or "all" in self._file_disables:
            return True
        for lineno in range(start, (end or start) + 1):
            rules = self._line_disables.get(lineno)
            if rules and (rule in rules or "all" in rules):
                return True
        return False

    def span_text(self, start: int, end: Optional[int] = None) -> str:
        return "\n".join(
            self.lines[start - 1 : (end or start)]
        )


class Rule:
    """Base class: subclass, set ``id``/``description``, implement
    ``check``, and register with :func:`register`. ``dirs`` (sections
    under the package) and ``files`` (exact package-relative paths)
    are the file allowlists — the UNION applies when both are set
    (a dir-scoped rule can pull in individual out-of-dir files, e.g.
    ``unbounded-list`` covering ``machinery/replica.py`` next to
    ``web/``); both ``None`` means every file."""

    id: str = ""
    description: str = ""
    severity: str = "error"
    dirs: Optional[tuple[str, ...]] = None
    files: Optional[tuple[str, ...]] = None
    whole_program = False

    def applies(self, src: SourceFile) -> bool:
        if self.files is None and self.dirs is None:
            return True
        return bool(
            (self.files is not None and src.rel in self.files)
            or (self.dirs is not None and src.section in self.dirs)
        )

    def check(self, src: SourceFile) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(
        self, src: SourceFile, node: ast.AST, message: str
    ) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(
            self.id,
            src.rel,
            line,
            message,
            self.severity,
            end_line=getattr(node, "end_lineno", None) or line,
        )


class ProgramRule(Rule):
    """A whole-program rule: instead of one file at a time it sees the
    complete analyzed file set as a :class:`callgraph.Program` (call
    graph + lock-context dataflow) and reports across function and
    file boundaries. ``check_program`` runs once per lint invocation;
    per-line/file suppression applies to its findings exactly as to
    per-file findings (by the finding's path + line span)."""

    whole_program = True

    def check(self, src: SourceFile) -> Iterator[Finding]:
        return iter(())  # program rules never run per-file

    def check_program(self, program) -> Iterator[Finding]:
        raise NotImplementedError


RULES: dict[str, Rule] = {}


def register(cls: type) -> type:
    """Class decorator adding a rule to the global registry."""
    rule = cls()
    if not rule.id:
        raise ValueError(f"{cls.__name__} has no rule id")
    RULES[rule.id] = rule
    return cls


def active_rules(select: Optional[Iterable[str]] = None) -> list[Rule]:
    """The rule allowlist: all registered rules, or the ``select``
    subset (unknown ids raise — a typo must not silently skip)."""
    _ensure_rules_loaded()
    if select is None:
        return list(RULES.values())
    out = []
    for rule_id in select:
        if rule_id not in RULES:
            raise KeyError(
                f"unknown rule {rule_id!r}; known: {', '.join(sorted(RULES))}"
            )
        out.append(RULES[rule_id])
    return out


def _ensure_rules_loaded() -> None:
    from odh_kubeflow_tpu.analysis import rules as _rules  # noqa: F401 — self-registering


# ---------------------------------------------------------------------------
# runners


def run_source(src: SourceFile, rules: Iterable[Rule]) -> list[Finding]:
    """Run per-file ``rules`` over one parsed file, applying
    suppressions."""
    findings: list[Finding] = []
    for rule in rules:
        if rule.whole_program or not rule.applies(src):
            continue
        for f in rule.check(src):
            if not src.suppressed(f.rule, f.line, f.end_line or f.line):
                findings.append(f)
    return findings


def run_program_rules(
    sources: list[SourceFile], rules: Iterable[Rule]
) -> list[Finding]:
    """Run the whole-program rules once over the analyzed file set,
    applying per-line/file suppressions by finding location."""
    prules = [r for r in rules if r.whole_program]
    if not prules:
        return []
    from odh_kubeflow_tpu.analysis.callgraph import build_program

    program = build_program(sources)
    by_rel = {s.rel: s for s in sources}
    findings: list[Finding] = []
    for rule in prules:
        for f in rule.check_program(program):
            src = by_rel.get(f.path)
            if src is not None and src.suppressed(
                f.rule, f.line, f.end_line or f.line
            ):
                continue
            findings.append(f)
    return findings


def lint_source(
    text: str, rel: str, select: Optional[Iterable[str]] = None
) -> list[Finding]:
    """Lint a source string as if it lived at package-relative path
    ``rel`` (the fixture-snippet entry point tests use). Whole-program
    rules see a one-file program, so interprocedural fixtures stay
    self-contained."""
    src = SourceFile(path=rel, rel=rel, text=text)
    rules = active_rules(select)
    findings = run_source(src, rules)
    findings.extend(run_program_rules([src], rules))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def iter_sources(
    root: Optional[str] = None, rel_root: Optional[str] = None
) -> Iterator[SourceFile]:
    """Every ``.py`` file under ``root`` (vendored frontend assets and
    caches are skipped). ``rel_root`` anchors the package-relative
    paths rules scope on — linting a subdirectory of the package must
    keep each file's real section (``controllers/…``), not re-root it."""
    root = root or package_root()
    rel_root = rel_root or root
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [
            d for d in dirnames if d not in ("__pycache__", "frontend")
        ]
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, rel_root)
            with open(path, encoding="utf-8") as f:
                text = f.read()
            yield SourceFile(path, rel, text)


def run_package(
    root: Optional[str] = None, select: Optional[Iterable[str]] = None
) -> list[Finding]:
    """Run the rule set over the whole package; findings come back
    sorted by path/line (the tier-1 gate asserts this is empty modulo
    the committed baseline)."""
    rules = active_rules(select)
    sources = list(iter_sources(root))
    findings: list[Finding] = []
    for src in sources:
        findings.extend(run_source(src, rules))
    findings.extend(run_program_rules(sources, rules))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def run_paths(
    paths: Iterable[str], select: Optional[Iterable[str]] = None
) -> list[Finding]:
    """Run rules over explicit files/directories. Paths inside the
    package keep their package-relative section (so dir-scoped rules
    apply as in a package run); outside paths are treated as
    section-less. Whole-program rules see exactly the given file set —
    call chains leaving it are simply unresolved."""
    rules = active_rules(select)
    root = package_root()
    sources: list[SourceFile] = []
    for path in paths:
        abspath = os.path.abspath(path)
        inside = abspath == root or abspath.startswith(root + os.sep)
        if os.path.isdir(path):
            sources.extend(
                iter_sources(abspath, rel_root=root if inside else abspath)
            )
            continue
        rel = (
            os.path.relpath(abspath, root)
            if inside
            else os.path.basename(path)
        )
        with open(path, encoding="utf-8") as f:
            text = f.read()
        sources.append(SourceFile(path, rel, text))
    findings: list[Finding] = []
    for src in sources:
        findings.extend(run_source(src, rules))
    findings.extend(run_program_rules(sources, rules))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


# ---------------------------------------------------------------------------
# baseline (ratcheting: CI fails only on NEW findings)


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "baseline.json")


_LINE_REF_RE = re.compile(r"(\.py):\d+")


def baseline_key(f: Finding) -> tuple[str, str, str]:
    """A finding's baseline identity: rule + path + message, with NO
    line numbers — not the finding's own, and not the ``file.py:NN``
    references inside interprocedural witness chains (normalized to
    ``file.py``). Unrelated edits shift lines, and a baseline that
    churns on every refactor protects nothing. A finding whose
    normalized message changes (different chain shape, different
    lock) is a new finding."""
    return (f.rule, f.path, _LINE_REF_RE.sub(r"\1", f.message))


def load_baseline(path: str) -> list[tuple[str, str, str]]:
    """The accepted-findings multiset from ``path`` ([] when the file
    does not exist — an absent baseline accepts nothing). Messages are
    normalized exactly like :func:`baseline_key` so hand-edited or
    older baseline files keep matching."""
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    return [
        (e["rule"], e["path"], _LINE_REF_RE.sub(r"\1", e["message"]))
        for e in doc.get("findings", [])
    ]


def write_baseline(path: str, findings: list[Finding]) -> None:
    doc = {
        "comment": (
            "graftlint accepted-findings baseline: the gate fails only "
            "on findings NOT in this list. Regenerate with "
            "`python -m odh_kubeflow_tpu.analysis --write-baseline` "
            "after deliberately accepting a finding; shrink it "
            "whenever one is fixed."
        ),
        "findings": [
            {"rule": f.rule, "path": f.path, "message": f.message}
            for f in findings
        ],
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")


def apply_baseline(
    findings: list[Finding], baseline: list[tuple[str, str, str]]
) -> tuple[list[Finding], int]:
    """Subtract the baseline multiset from ``findings``: each baseline
    entry absorbs at most one finding with the same identity (two NEW
    instances of a baselined shape still surface one). Returns
    (unbaselined findings, how many were absorbed)."""
    budget: dict[tuple[str, str, str], int] = {}
    for key in baseline:
        budget[key] = budget.get(key, 0) + 1
    out: list[Finding] = []
    absorbed = 0
    for f in findings:
        key = baseline_key(f)
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            absorbed += 1
            continue
        out.append(f)
    return out, absorbed


# ---------------------------------------------------------------------------
# CLI


def main(argv: Optional[list[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog=f"python -m {PACKAGE}.analysis",
        description="AST-based platform invariant checker (graftlint)",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help=f"files or directories to lint (default: the {PACKAGE} package)",
    )
    parser.add_argument(
        "--select",
        help="comma-separated rule allowlist (default: all rules)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    parser.add_argument(
        "--format",
        choices=("text", "json"),
        default="text",
        help="findings output format (json: machine-readable array)",
    )
    parser.add_argument(
        "--baseline",
        help=(
            "accepted-findings file to subtract (default: the committed "
            "analysis/baseline.json on whole-package runs)"
        ),
    )
    parser.add_argument(
        "--no-baseline",
        action="store_true",
        help="report every finding, ignoring any baseline",
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="write the current findings to the baseline file and exit 0",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in active_rules():
            scope = (
                ", ".join(rule.files)
                if rule.files
                else (", ".join(rule.dirs) + "/" if rule.dirs else "package-wide")
            )
            print(f"{rule.id:<22} [{scope}] {rule.description}")
        return 0

    select = (
        [r.strip() for r in args.select.split(",") if r.strip()]
        if args.select
        else None
    )
    if args.paths:
        findings = run_paths(args.paths, select)
    else:
        findings = run_package(select=select)

    baseline_path = args.baseline or (
        default_baseline_path() if not args.paths else None
    )
    if args.write_baseline:
        if (args.paths or args.select) and not args.baseline:
            # a scoped run sees a PARTIAL finding set; writing it to
            # the committed package baseline would silently delete
            # every other accepted entry
            print(
                "graftlint: refusing --write-baseline on a path/--select"
                "-scoped run without an explicit --baseline path (it "
                "would clobber the committed package baseline)",
                file=sys.stderr,
            )
            return 2
        path = args.baseline or default_baseline_path()
        write_baseline(path, findings)
        print(
            f"graftlint: wrote {len(findings)} finding(s) to {path}",
            file=sys.stderr,
        )
        return 0
    absorbed = 0
    if baseline_path is not None and not args.no_baseline:
        findings, absorbed = apply_baseline(
            findings, load_baseline(baseline_path)
        )

    if args.format == "json":
        print(
            json.dumps(
                [
                    {
                        "rule": f.rule,
                        "path": f.path,
                        "line": f.line,
                        "end_line": f.end_line or f.line,
                        "severity": f.severity,
                        "message": f.message,
                    }
                    for f in findings
                ],
                indent=2,
            )
        )
    else:
        for f in findings:
            print(f.render())
    n_rules = len(active_rules(select))
    suffix = f" ({absorbed} baselined)" if absorbed else ""
    if findings:
        print(
            f"graftlint: {len(findings)} new finding(s) across "
            f"{n_rules} rule(s){suffix}",
            file=sys.stderr,
        )
        return 1
    print(f"graftlint: clean ({n_rules} rules){suffix}", file=sys.stderr)
    return 0
