"""Interprocedural exception-flow analysis: error contracts, checked.

The platform's failure paths are governed by written contracts that
nothing enforced statically until now:

- the PR-5 **verb × error retry policy** (429 retried for every verb by
  the client; Conflict surfaced to callers that must level-trigger or
  read-merge-write-retry; 410 Expired handled by relist/restart loops);
- the PR-8 **fencing rule** — ``FencedOut``/``NotLeader`` mean *this
  replica lost authority*; they must abort the holder, never be
  swallowed by a broad handler that keeps acting as leader;
- the PR-10/13 **410 restart contracts** (paginated walks and watch
  resumes restart from fresh state on ``Expired``).

Each was proven once by a drill and can rot silently as new callers
land. This module closes the gap with *raise-set inference* over the
whole-program call graph (``analysis/callgraph.py``):

- the ``APIError`` hierarchy is **mined from machinery/store.py** (any
  package file can extend it; fixtures fall back to the known default);
- per-function *can-raise* sets are seeded from ``raise`` sites and
  from a **verb model** of the API surface (``<…api/client/store>.
  update(…)`` can raise Conflict/FencedOut/… — the same receiver
  vocabulary the frozen-mutation and unfenced-write rules use);
- sets propagate through resolved call edges (module functions,
  ``self.``-methods, import aliases, bounded class-hierarchy analysis)
  with full witness chains;
- ``try/except`` narrows **hierarchy-aware** (``except APIError:``
  absorbs ``Conflict``; a handler whose body re-raises bare is a
  pass-through, not an absorber; module-level handler-tuple constants
  like ``_OUTAGE_ERRORS`` are resolved);
- calls routed through ``machinery.backoff.retry`` absorb their
  policy's retryable set for contract purposes (the *can-raise* view
  keeps them — retry re-raises after attempts are exhausted, so a
  ``except Conflict:`` around a retry call is NOT dead);
- declared **retry-policy anchors** (the client's verb × error table in
  ``RemoteAPIServer._request``, the store's guaranteedUpdate-style
  ``patch``) are verified structurally every run — if a refactor drops
  the ``backoff.retry`` wrap, the anchor fails, the absorbed errors
  reappear at every call site, and the contract rule reports both the
  anchor and the newly-escaping paths with witness chains.

Three whole-program rules ride on the inference (registered on import,
baseline-ratcheted like every graftlint rule):

- ``error-contract``: the declarative contract table — reconcile
  bodies, web handlers, the scheduler cycle, the SessionManager, and
  the promotion watchdog's ``step`` must handle-or-retry
  ``{Conflict, Expired, TooManyRequests}`` at the site where they can
  surface. An escaping retryable error is a finding carrying the full
  entry-point → raising-call chain. Sites that *deliberately* rely on
  an outer mechanism (level-triggered requeue, the kube 410 pagination
  contract) annotate ``# contract-ok: <reason>``.
- ``handler-masks-fencing``: an ``except`` that catches ``FencedOut``
  or ``NotLeader`` — directly, or via a broad ``APIError``/
  ``Exception`` clause that a fencing error can actually reach — and
  *continues* instead of aborting/recording the deposition.
  ``# fencing-ok: <reason>`` marks a deliberate handler.
- ``dead-except``: a handler catching a platform error that no
  reachable operation in its try body can raise — the drift left
  behind when a refactor moves the raising call out from under a
  once-correct handler. Only fires when every call in the body is
  fully analyzable (resolved, verb-modeled, or provably foreign), so
  an unresolvable call never produces a false "dead".
"""

from __future__ import annotations

import ast
import builtins
import dataclasses
from typing import Iterator, Optional

from odh_kubeflow_tpu.analysis import callgraph
from odh_kubeflow_tpu.analysis.callgraph import (
    Chain,
    FuncInfo,
    Program,
    Step,
    render_chain,
)
from odh_kubeflow_tpu.analysis.graftlint import (
    Finding,
    ProgramRule,
    SourceFile,
    register,
)

_attr_chain = callgraph._attr_chain

# the APIError hierarchy as of machinery/store.py — the fixture-mode
# fallback; real package runs re-mine it from source so a new error
# class is picked up the moment it lands
DEFAULT_HIERARCHY: dict[str, Optional[str]] = {
    "APIError": None,
    "NotFound": "APIError",
    "AlreadyExists": "APIError",
    "Conflict": "APIError",
    "Invalid": "APIError",
    "BadRequest": "APIError",
    "Denied": "APIError",
    "Unauthorized": "APIError",
    "TooManyRequests": "APIError",
    "DeadlineExceeded": "APIError",
    "Expired": "APIError",
    "FencedOut": "APIError",
    "NotLeader": "APIError",
}

# the PR-5 retryable set every contract entry point must handle-or-retry
RETRYABLE = frozenset({"Conflict", "Expired", "TooManyRequests"})
# authority failures: abort, never swallow (PR-8)
FENCING = frozenset({"FencedOut", "NotLeader"})

# the error surface of each API verb as seen through the platform
# client stack — the error axis of the PR-5 verb × error table.
# Deliberately generous: over-approximating can-raise keeps dead-except
# conservative, and the contract rule only acts on RETRYABLE ∩ set.
# EVERY verb includes NotFound: the store raises it for an
# unregistered kind (the "subsystem not installed" contract callers
# probe with `except NotFound`).
_VERB_COMMON = frozenset(
    # DeadlineExceeded: every verb sheds with 504 once the caller's
    # end-to-end deadline expires (machinery/overload.py) — and it is
    # deliberately NOT in RETRYABLE: the caller already gave up
    {"NotFound", "Denied", "Unauthorized", "TooManyRequests",
     "DeadlineExceeded"}
)
_MUTATION_COMMON = _VERB_COMMON | frozenset(
    {"Invalid", "BadRequest", "FencedOut", "NotLeader"}
)
VERB_RAISES: dict[str, frozenset[str]] = {
    "get": _VERB_COMMON,
    "list": _VERB_COMMON,
    # rv-pinned / continue-token walks can outlive the compacted window
    "list_chunk": _VERB_COMMON | {"Expired", "BadRequest"},
    "watch": _VERB_COMMON | {"Expired"},
    # paged_list_all restarts Expired walks internally (PR 10) — model
    # the helper itself, not its internals
    "paged_list_all": _VERB_COMMON,
    "create": _MUTATION_COMMON | {"AlreadyExists"},
    "create_or_get": _MUTATION_COMMON,
    "update": _MUTATION_COMMON | {"Conflict"},
    "update_status": _MUTATION_COMMON | {"Conflict"},
    "patch": _MUTATION_COMMON | {"Conflict"},
    "delete": _MUTATION_COMMON,
    "emit_event": _MUTATION_COMMON,
}

# receiver vocabulary marking a call as an API-surface verb (shared
# spirit with frozen-mutation's _CLIENTISH / unfenced-write's
# _WRITERISH, plus the read-replica handles)
_CLIENTISH = frozenset(
    {
        "api",
        "client",
        "store",
        "server",
        "backend",
        "cache",
        "informer",
        "replica",
        "leader",
    }
)

# beyond callgraph.AMBIG_LIMIT: raise-set propagation takes the UNION
# over same-named method candidates, which stays sound as a may-raise
# set — so it can afford a wider net than the concurrency rules
EXC_AMBIG_LIMIT = 8

# call terminals that provably cannot raise platform errors (logging,
# metrics, time/format plumbing) — everything else unresolved poisons
# dead-except completeness
_SAFE_TERMINALS = frozenset(
    {
        "debug", "info", "warning", "error", "exception", "critical", "log",
        "getLogger", "inc", "dec", "observe", "labels", "set_gauge",
        "monotonic", "perf_counter", "sleep", "time", "isoformat",
        "strftime", "utcnow", "now", "timestamp", "total_seconds",
        "format", "format_map", "encode", "decode", "hexdigest",
    }
)


@dataclasses.dataclass(frozen=True)
class RetryPolicyAnchor:
    """A function declared to own part of the retry policy: it must
    wrap its API call in ``machinery.backoff.retry`` (verified
    structurally each run). While verified, the errors it absorbs are
    subtracted from the contract view of every matching verb call —
    delete the wrap and they reappear everywhere, with chains."""

    file: str
    func: str  # short name ("Class.method")
    absorbs: frozenset[str]
    verbs: Optional[frozenset[str]]  # None = every verb call
    description: str

    @property
    def qual(self) -> str:
        return f"{self.file}::{self.func}"


POLICY_ANCHORS: tuple[RetryPolicyAnchor, ...] = (
    RetryPolicyAnchor(
        file="machinery/client.py",
        func="RemoteAPIServer._request",
        absorbs=frozenset({"TooManyRequests"}),
        verbs=None,
        description=(
            "PR-5 client retry policy: a 429 was never executed "
            "server-side, so the client retries it for every verb "
            "after the Retry-After wait"
        ),
    ),
    RetryPolicyAnchor(
        file="machinery/store.py",
        func="APIServer.patch",
        absorbs=frozenset({"Conflict"}),
        verbs=frozenset({"patch"}),
        description=(
            "kube guaranteedUpdate shape: patch is a read-merge-write "
            "that retries Conflict server-side"
        ),
    ),
)


@dataclasses.dataclass(frozen=True)
class Site:
    """One witness: the chain from the owning function inward to the
    raise/model leaf, plus the AST node of the head site (the call or
    raise statement in the owning function — where suppression markers
    and finding spans anchor)."""

    chain: Chain
    node: ast.AST


# (error name, witness, escapes in can-raise view, escapes in contract view)
_SiteRow = tuple[str, Site, bool, bool]


@dataclasses.dataclass
class _FnResult:
    sites: list[_SiteRow]
    complete: bool  # no unanalyzable call reachable (incl. callees)


_EMPTY = _FnResult([], True)


@dataclasses.dataclass(frozen=True)
class ContractEntry:
    kind: str
    qual: str
    fn: FuncInfo


class _Handler:
    """One except clause, normalized: caught type names (module-level
    tuple constants resolved) and whether the body re-raises bare."""

    __slots__ = ("names", "passthrough", "node")

    def __init__(self, names: tuple[str, ...], passthrough: bool, node):
        self.names = names
        self.passthrough = passthrough
        self.node = node


def mine_hierarchy(program: Program) -> dict[str, Optional[str]]:
    """The APIError class tree: seeded from ``machinery/store.py`` when
    it is in the analyzed set (package runs), from the known default
    otherwise (fixtures), then extended to fixpoint with any class in
    the file set deriving from a known error."""
    if "machinery/store.py" in program.sources:
        hierarchy: dict[str, Optional[str]] = {"APIError": None}
    else:
        hierarchy = dict(DEFAULT_HIERARCHY)
    changed = True
    while changed:
        changed = False
        for src in program.sources.values():
            for node in src.tree.body:
                if not isinstance(node, ast.ClassDef):
                    continue
                if node.name in hierarchy:
                    continue
                for base in node.bases:
                    chain = _attr_chain(base)
                    if chain and chain[-1] in hierarchy:
                        hierarchy[node.name] = chain[-1]
                        changed = True
                        break
    return hierarchy


class ExceptionAnalysis:
    """Raise-set inference over a :class:`callgraph.Program`. One
    instance per program (cached on the program object — every rule in
    a lint invocation shares the memoized summaries)."""

    @classmethod
    def of(cls, program: Program) -> "ExceptionAnalysis":
        inst = getattr(program, "_exception_analysis", None)
        if inst is None:
            inst = cls(program)
            program._exception_analysis = inst
        return inst

    def __init__(self, program: Program):
        self.program = program
        self.hierarchy = mine_hierarchy(program)
        self._memo: dict[str, _FnResult] = {}
        self._funcs: dict[str, FuncInfo] = dict(program.functions)
        # class name → quals of __init__ methods (constructor calls
        # resolve so `Result()` never poisons dead-except completeness)
        self._class_inits: dict[str, list[str]] = {}
        self._known_classes: set[str] = set()
        # rel → {name → tuple of caught-type terminals} for module-level
        # `_ERRS = (APIError, OSError)`-style handler constants
        self._handler_tuples: dict[str, dict[str, tuple[str, ...]]] = {}
        for src in program.sources.values():
            self._index_source(src)
        self.route_handlers: list[FuncInfo] = []
        self._index_route_handlers()
        # anchor → "verified" | "missing" | "absent"
        self.anchor_status: dict[RetryPolicyAnchor, str] = {}
        self._verify_anchors()

    # -- indexing ------------------------------------------------------------

    def _index_source(self, src: SourceFile) -> None:
        consts: dict[str, tuple[str, ...]] = {}
        for node in src.tree.body:
            if isinstance(node, ast.ClassDef):
                self._known_classes.add(node.name)
                init = f"{src.rel}::{node.name}.__init__"
                if init in self._funcs:
                    self._class_inits.setdefault(node.name, []).append(init)
            elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Tuple
            ):
                names = tuple(
                    chain[-1]
                    for e in node.value.elts
                    if (chain := _attr_chain(e))
                )
                if names and len(names) == len(node.value.elts):
                    for target in node.targets:
                        if isinstance(target, ast.Name):
                            consts[target.id] = names
        self._handler_tuples[src.rel] = consts

    def _index_route_handlers(self) -> None:
        """Web handlers are nested defs under ``@app.route(...)`` inside
        app factories — not in the module-level function table. Index
        them as entry points, with ``cls`` set to the enclosing class so
        ``self.helper()`` calls resolve."""
        for src in self.program.sources.values():
            if src.section != "web":
                continue
            self._walk_for_routes(src, src.tree, cls=None, prefix="")

    def _walk_for_routes(
        self, src: SourceFile, node: ast.AST, cls: Optional[str], prefix: str
    ) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                self._walk_for_routes(src, child, child.name, prefix)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                routed = any(
                    isinstance(dec, ast.Call)
                    and (chain := _attr_chain(dec.func))
                    and chain[-1] == "route"
                    for dec in child.decorator_list
                )
                if routed:
                    qual = f"{src.rel}::{prefix}{child.name}@{child.lineno}"
                    fn = FuncInfo(
                        qual=qual,
                        src=src,
                        node=child,
                        cls=cls,
                        is_async=isinstance(child, ast.AsyncFunctionDef),
                    )
                    self._funcs[qual] = fn
                    self.route_handlers.append(fn)
                self._walk_for_routes(
                    src, child, cls, prefix=f"{prefix}{child.name}."
                )

    # -- hierarchy -----------------------------------------------------------

    def _ancestors(self, err: str) -> set[str]:
        out = {err}
        cur: Optional[str] = err
        while cur is not None:
            cur = self.hierarchy.get(cur)
            if cur is not None:
                out.add(cur)
        return out

    def catches(self, caught_names, err: str) -> bool:
        """Hierarchy-aware: does a clause catching ``caught_names``
        catch platform error ``err``?"""
        anc = self._ancestors(err)
        return any(
            n in ("Exception", "BaseException") or n in anc
            for n in caught_names
        )

    def handler_spec(self, src: SourceFile, handler: ast.ExceptHandler) -> _Handler:
        t = handler.type
        if t is None:
            names: tuple[str, ...] = ("BaseException",)
        elif isinstance(t, ast.Tuple):
            parts: list[str] = []
            for e in t.elts:
                chain = _attr_chain(e)
                if chain:
                    parts.append(chain[-1])
            names = tuple(parts)
        else:
            chain = _attr_chain(t)
            names = (chain[-1],) if chain else ()
            # `except _OUTAGE_ERRORS:` — a module-level tuple constant
            if names and isinstance(t, ast.Name):
                expanded = self._handler_tuples.get(src.rel, {}).get(t.id)
                if expanded is not None:
                    names = expanded
        # a handler re-raises via bare `raise` OR `raise e` of its own
        # bound name — both are pass-throughs, not absorbers
        passthrough = any(
            isinstance(n, ast.Raise)
            and (
                n.exc is None
                or (
                    isinstance(n.exc, ast.Name)
                    and handler.name is not None
                    and n.exc.id == handler.name
                )
            )
            for n in _live_walk(handler.body)
        )
        return _Handler(names, passthrough, handler)

    # -- anchors -------------------------------------------------------------

    def _verify_anchors(self) -> None:
        for anchor in POLICY_ANCHORS:
            if anchor.file not in self.program.sources:
                # fixtures / scoped runs: the policy lives outside the
                # analyzed set — treat it as in force
                self.anchor_status[anchor] = "absent"
                continue
            fn = self.program.functions.get(anchor.qual)
            ok = fn is not None and any(
                isinstance(n, ast.Call) and self._is_retry_call(n, fn)
                for n in _live_walk(
                    fn.node.body if hasattr(fn.node, "body") else []
                )
            )
            self.anchor_status[anchor] = "verified" if ok else "missing"

    def _anchor_absorbed(self, verb: str, err: str) -> bool:
        for anchor in POLICY_ANCHORS:
            if self.anchor_status.get(anchor) == "missing":
                continue
            if anchor.verbs is not None and verb not in anchor.verbs:
                continue
            if any(self.catches((a,), err) for a in anchor.absorbs):
                return True
        return False

    # -- call classification -------------------------------------------------

    def _is_retry_call(self, call: ast.Call, fn: FuncInfo) -> bool:
        chain = _attr_chain(call.func)
        if not chain or chain[-1] != "retry":
            return False
        if len(chain) > 1:
            return any("backoff" in part.lower() for part in chain[:-1])
        # bare `retry(...)`: accept when imported from machinery.backoff
        imported = self.program._from_imports.get(fn.src.rel, {}).get("retry")
        return imported is not None and imported[0].endswith("backoff.py")

    def _retry_absorbed_names(self, call: ast.Call) -> Optional[tuple[str, ...]]:
        """The statically-visible retryable set of a ``backoff.retry``
        call: names from a Name/Attribute/Tuple argument; ``None`` for
        predicates (lambdas) — absorb nothing statically. No retryable
        argument at all means the default ``(Exception,)``."""
        expr: Optional[ast.AST] = None
        for kw in call.keywords:
            if kw.arg == "retryable":
                expr = kw.value
        if expr is None and len(call.args) > 1:
            expr = call.args[1]
        if expr is None:
            return ("Exception",)
        elts = expr.elts if isinstance(expr, ast.Tuple) else [expr]
        names: list[str] = []
        for e in elts:
            chain = _attr_chain(e)
            if not chain or (
                chain[-1] not in self.hierarchy
                and chain[-1] not in ("Exception", "BaseException")
            ):
                return None
            names.append(chain[-1])
        return tuple(names)

    def _api_verb(self, call: ast.Call) -> Optional[str]:
        chain = _attr_chain(call.func)
        if len(chain) < 2 or chain[-1] not in VERB_RAISES:
            return None
        for part in chain[:-1]:
            p = part.lower().strip("_")
            if p in _CLIENTISH or p.endswith(
                ("api", "client", "store", "replica")
            ):
                return chain[-1]
        # `paged_list_all(api, ...)` is a module function taking the
        # client as an argument
        return None

    def _resolve(self, call: ast.Call, fn: FuncInfo) -> tuple[str, ...]:
        targets = self.program.resolve(call, fn)
        if targets:
            return targets
        f = call.func
        rel = fn.src.rel
        if isinstance(f, ast.Name):
            inits = self._class_inits.get(f.id)
            if inits:
                return tuple(sorted(inits))
            imported = self.program._from_imports.get(rel, {}).get(f.id)
            if imported is not None:
                init = f"{imported[0]}::{imported[1]}.__init__"
                if init in self._funcs:
                    return (init,)
            return ()
        chain = _attr_chain(f)
        if not chain or len(chain) < 2:
            return ()
        if chain[0] in self.program._foreign_roots.get(rel, ()):
            return ()
        terminal = chain[-1]
        if terminal in callgraph._BUILTIN_METHODS:
            return ()
        candidates = self.program._methods.get(terminal, [])
        if 1 <= len(candidates) <= EXC_AMBIG_LIMIT:
            return tuple(sorted(candidates))
        return ()

    def _call_is_harmless(self, call: ast.Call, fn: FuncInfo) -> bool:
        """Whether an otherwise-unresolved call provably cannot raise a
        platform error (foreign module, python builtin, container
        method, logging/metrics plumbing, known no-__init__ class)."""
        f = call.func
        rel = fn.src.rel
        if isinstance(f, ast.Name):
            if hasattr(builtins, f.id):
                return True
            if f.id in self._known_classes:
                return True  # no __init__ in the table → nothing to raise
            imported = self.program._from_imports.get(rel, {}).get(f.id)
            if imported is not None and imported[1] in self._known_classes:
                return True
            return False
        chain = _attr_chain(f)
        if not chain:
            return False
        if chain[0] in self.program._foreign_roots.get(rel, ()):
            return True
        terminal = chain[-1]
        if terminal in VERB_RAISES:
            # an API-verb name on a receiver we could not classify:
            # `c.get(...)` may be a dict get OR a store read that
            # raises NotFound — never "harmless" for dead-except
            return False
        return (
            terminal in callgraph._BUILTIN_METHODS
            or terminal in _SAFE_TERMINALS
        )

    # -- per-function inference ----------------------------------------------

    def result_for(self, qual: str) -> _FnResult:
        res, _pending = self._result_rec(qual, set())
        return res

    def _result_rec(
        self, qual: str, stack: set[str]
    ) -> tuple[_FnResult, set[str]]:
        """SCC-aware memoized DFS, same discipline as
        ``callgraph._reach_rec``: summaries computed while a call cycle
        is open are only cached at the cycle's DFS root."""
        if qual in self._memo:
            return self._memo[qual], set()
        if qual in stack:
            return _EMPTY, {qual}
        fn = self._funcs.get(qual)
        if fn is None:
            self._memo[qual] = _EMPTY
            return _EMPTY, set()
        stack.add(qual)
        body = fn.node.body if hasattr(fn.node, "body") else []
        sites, complete, pending = self._collect(fn, body, (), stack)
        stack.discard(qual)
        pending.discard(qual)
        res = _FnResult(sites, complete)
        if not pending:
            self._memo[qual] = res
        return res, pending

    def _collect(
        self,
        fn: FuncInfo,
        stmts: list,
        guards: tuple,
        stack: set[str],
    ) -> tuple[list[_SiteRow], bool, set[str]]:
        """Walk ``stmts`` as executed inside ``fn`` under the given
        enclosing-handler ``guards``; return the escaping site rows,
        body completeness, and pending (open-cycle) callees."""
        sites: list[_SiteRow] = []
        state = {"complete": True}
        pending: set[str] = set()

        def escapes(err: str, g: tuple) -> bool:
            for handlers in reversed(g):
                for h in handlers:
                    if self.catches(h.names, err):
                        if h.passthrough:
                            break  # re-raised: keeps propagating out
                        return False
                    # only the FIRST matching clause runs
            return True

        def add(err: str, site: Site, in_can: bool, in_esc: bool, g: tuple):
            if not escapes(err, g):
                return
            # `# contract-ok: <reason>` on the site certifies the escape
            # as deliberately handled by an outer mechanism — cleared
            # from the contract view HERE so the certification holds
            # through every caller chain, not just the entry function
            if in_esc and _marked(fn.src, site.node, "contract-ok"):
                in_esc = False
            sites.append((err, site, in_can, in_esc))

        def visit_call(call: ast.Call, g: tuple) -> None:
            label = ".".join(_attr_chain(call.func)) or "<call>"
            if self._is_retry_call(call, fn):
                absorbed = self._retry_absorbed_names(call)
                rows, wrapped_complete = self._wrapped_rows(
                    call, fn, stack, pending
                )
                state["complete"] &= wrapped_complete
                for err, site, inner_esc in rows:
                    contract_ok = absorbed is not None and any(
                        self.catches((a,), err) for a in absorbed
                    )
                    add(err, site, True, inner_esc and not contract_ok, g)
                return
            verb = self._api_verb(call)
            if verb is not None:
                for err in sorted(VERB_RAISES[verb]):
                    site = Site(
                        (
                            Step(
                                fn.short,
                                fn.src.rel,
                                call.lineno,
                                f"{label}() can raise {err}",
                            ),
                        ),
                        call,
                    )
                    add(err, site, True, not self._anchor_absorbed(verb, err), g)
                return
            targets = self._resolve(call, fn)
            if targets:
                for target in sorted(targets):
                    if target == fn.qual:
                        continue
                    sub, sub_pending = self._result_rec(target, stack)
                    pending.update(sub_pending)
                    state["complete"] &= sub.complete
                    head = Step(fn.short, fn.src.rel, call.lineno, label)
                    seen: set[tuple[str, bool]] = set()
                    for err, site, in_can, in_esc in sub.sites:
                        key = (err, in_esc)
                        if key in seen:
                            continue
                        seen.add(key)
                        add(
                            err,
                            Site((head,) + site.chain, call),
                            in_can,
                            in_esc,
                            g,
                        )
                return
            if not self._call_is_harmless(call, fn):
                state["complete"] = False

        def visit(node: ast.AST, g: tuple, bound: frozenset) -> None:
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                return  # runs later, outside these guards
            if isinstance(node, ast.Try):
                handlers = tuple(
                    self.handler_spec(fn.src, h) for h in node.handlers
                )
                for s in node.body:
                    visit(s, g + (handlers,), bound)
                for h in node.handlers:
                    # the handler's bound name re-raised inside its body
                    # is the pass-through handler_spec already models —
                    # not an unknown variable raise
                    inner = bound | {h.name} if h.name else bound
                    for s in h.body:
                        visit(s, g, inner)
                for s in node.orelse:  # not guarded by this try's handlers
                    visit(s, g, bound)
                for s in node.finalbody:
                    visit(s, g, bound)
                return
            if isinstance(node, ast.Raise) and node.exc is not None:
                target = (
                    node.exc.func if isinstance(node.exc, ast.Call) else node.exc
                )
                chain = _attr_chain(target)
                if chain and chain[-1] in self.hierarchy:
                    err = chain[-1]
                    site = Site(
                        (
                            Step(
                                fn.short,
                                fn.src.rel,
                                node.lineno,
                                f"raise {err}",
                            ),
                        ),
                        node,
                    )
                    add(err, site, True, True, g)
                elif (
                    isinstance(node.exc, ast.Name) and node.exc.id in bound
                ):
                    pass  # `raise e` of a handler's bound name: passthrough
                elif isinstance(node.exc, ast.Call) and (
                    chain and chain[-1][:1].isupper()
                ):
                    pass  # a non-platform exception class constructor
                else:
                    # `raise err` through a variable (or a factory call):
                    # it COULD hold any platform error the inference
                    # cannot see — poison completeness so dead-except
                    # never calls a live handler dead over it
                    state["complete"] = False
            if isinstance(node, ast.Call):
                visit_call(node, g)
                if self._is_retry_call(node, fn):
                    return  # the wrapped thunk was analyzed specially
            for child in ast.iter_child_nodes(node):
                visit(child, g, bound)

        for stmt in stmts:
            visit(stmt, guards, frozenset())
        return sites, state["complete"], pending

    def _wrapped_rows(
        self, call: ast.Call, fn: FuncInfo, stack: set[str], pending: set[str]
    ) -> tuple[list[tuple[str, Site]], bool]:
        """Raise rows of the thunk handed to ``backoff.retry`` — a
        lambda body analyzed inline, or a function reference resolved
        like a call — plus a completeness verdict (an unresolvable
        thunk, e.g. a nested def, yields no rows and must poison
        dead-except completeness rather than read as raise-free).
        Sites anchor on the retry call statement."""
        if not call.args:
            return [], True
        thunk = call.args[0]
        # (err, witness, inner contract-escape) — the inner view
        # survives so an anchor-absorbed error (the client's 429
        # policy) does not reappear just because a retry wraps the call
        rows: list[tuple[str, Site, bool]] = []
        if isinstance(thunk, ast.Lambda):
            sub_sites, sub_complete, sub_pending = self._collect(
                fn, [ast.Expr(value=thunk.body)], (), stack
            )
            pending.update(sub_pending)
            seen: dict[str, int] = {}
            for err, site, in_can, in_esc in sub_sites:
                if not in_can:
                    continue
                if err not in seen:
                    seen[err] = len(rows)
                    rows.append((err, Site(site.chain, call), in_esc))
                elif in_esc and not rows[seen[err]][2]:
                    rows[seen[err]] = (err, Site(site.chain, call), True)
            return rows, sub_complete
        pseudo = ast.Call(func=thunk, args=[], keywords=[])
        ast.copy_location(pseudo, call)
        ast.fix_missing_locations(pseudo)
        label = ".".join(_attr_chain(thunk)) or "<thunk>"
        targets = sorted(self._resolve(pseudo, fn))
        if not targets:
            return [], False
        complete = True
        seen = {}
        for target in targets:
            sub, sub_pending = self._result_rec(target, stack)
            pending.update(sub_pending)
            complete &= sub.complete
            head = Step(fn.short, fn.src.rel, call.lineno, f"retry({label})")
            for err, site, in_can, in_esc in sub.sites:
                if not in_can:
                    continue
                witness = Site((head,) + site.chain, call)
                if err not in seen:
                    seen[err] = len(rows)
                    rows.append((err, witness, in_esc))
                elif in_esc and not rows[seen[err]][2]:
                    rows[seen[err]] = (err, witness, True)
        return rows, complete

    # -- entry points --------------------------------------------------------

    _RECONCILE_SECTIONS = ("controllers", "scheduling", "sessions")
    _RECONCILE_NAMES = ("reconcile", "reconcile_notebook", "reconcile_all")
    _PROMOTER_FILES = ("machinery/promoter.py",)

    def contract_entries(self) -> Iterator[ContractEntry]:
        for qual, fn in sorted(self.program.functions.items()):
            name = fn.short.rsplit(".", 1)[-1]
            if (
                fn.src.section in self._RECONCILE_SECTIONS
                and name in self._RECONCILE_NAMES
                and fn.cls is not None
            ):
                yield ContractEntry("reconcile", qual, fn)
            elif (
                fn.src.rel in self._PROMOTER_FILES
                and name == "step"
                and fn.cls is not None
            ):
                yield ContractEntry("promoter step", qual, fn)
        for fn in self.route_handlers:
            yield ContractEntry("web handler", fn.qual, fn)

    def entry_sites(self, fn: FuncInfo) -> list[_SiteRow]:
        """Every escaping site of an entry-point body — unlike the
        memoized single-witness summaries, the contract rule reports
        each offending site so per-site ``# contract-ok`` markers work
        and fixing one site surfaces the next deterministically."""
        body = fn.node.body if hasattr(fn.node, "body") else []
        sites, _complete, _pending = self._collect(fn, body, (), set())
        return sites


# ---------------------------------------------------------------------------
# helpers


def _live_walk(stmts) -> Iterator[ast.AST]:
    """All descendants executing in the enclosing function — nested
    defs/lambdas pruned."""
    stack = list(stmts)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _marked(src: SourceFile, node: ast.AST, marker: str) -> bool:
    start = getattr(node, "lineno", 1)
    end = getattr(node, "end_lineno", None) or start
    return any(marker in src.line(n) for n in range(start, end + 1))


# ---------------------------------------------------------------------------
# error-contract


@register
class ErrorContractRule(ProgramRule):
    """The declarative contract table (reconcile bodies, web handlers,
    the scheduler cycle, SessionManager, promotion watchdog): every
    site where a retryable error — ``Conflict``, ``Expired``,
    ``TooManyRequests`` — can surface must handle it, route it through
    ``backoff.retry``, or carry ``# contract-ok: <reason>`` naming the
    outer mechanism relied on (level-triggered requeue, the kube 410
    pagination contract). Also verifies the declared retry-policy
    anchors still wrap their API call in ``backoff.retry`` — reverting
    the PR-5 client policy reports the anchor AND re-surfaces every
    absorbed escape with entry-point → raise witness chains."""

    id = "error-contract"
    description = (
        "retryable error (Conflict/Expired/429) escaping a contract "
        "entry point unhandled, with witness chain"
    )

    def check_program(self, program) -> Iterator[Finding]:
        ea = ExceptionAnalysis.of(program)
        for anchor in POLICY_ANCHORS:
            if ea.anchor_status.get(anchor) != "missing":
                continue
            src = program.sources[anchor.file]
            fn = program.functions.get(anchor.qual)
            node = fn.node if fn is not None else src.tree
            yield self.finding(
                src,
                node,
                f"retry-policy anchor {anchor.func} no longer routes "
                f"through machinery.backoff.retry ({anchor.description});"
                f" restore the retry wrap or update POLICY_ANCHORS — "
                f"until then {'/'.join(sorted(anchor.absorbs))} escapes "
                "every caller",
            )
        for entry in ea.contract_entries():
            reported: set[tuple[int, str]] = set()
            for err, site, _in_can, in_esc in ea.entry_sites(entry.fn):
                if not in_esc or err not in RETRYABLE:
                    continue
                key = (site.node.lineno, err)
                if key in reported:
                    continue
                reported.add(key)
                if _marked(entry.fn.src, site.node, "contract-ok"):
                    continue
                yield self.finding(
                    entry.fn.src,
                    site.node,
                    f"{entry.kind} {entry.fn.short} lets retryable "
                    f"{err} escape: {render_chain(site.chain)}; handle "
                    "it at this site, route it through backoff.retry, "
                    "or annotate with `# contract-ok: <reason>`",
                )


# ---------------------------------------------------------------------------
# handler-masks-fencing


# a handler body counts as aborting/recording the deposition when it
# re-raises, calls a stand-down-ish method (incl. fail-stop paths like
# the committer's _commit_failed), or records the fenced state
_ABORTISH_PARTS = (
    "stop", "stand", "shutdown", "abort", "exit", "depose", "kill", "fail",
)
_FENCED_STATE_ATTRS = ("fenced", "deposed", "stopped")


@register
class HandlerMasksFencingRule(ProgramRule):
    """``FencedOut``/``NotLeader`` mean this replica's authority is
    GONE — acting on the error by logging and carrying on is how a
    deposed leader keeps writing (the PR-8 TOCTOU the fencing tokens
    exist to close). Flags an ``except`` clause that catches a fencing
    error — named directly, or via a broad ``APIError``/``Exception``
    clause the inference proves a fencing error can actually reach —
    and neither re-raises, nor calls a stand-down path, nor records the
    deposition. ``# fencing-ok: <reason>`` marks deliberate handlers
    (e.g. a drill harness)."""

    id = "handler-masks-fencing"
    description = (
        "except clause swallows FencedOut/NotLeader and continues "
        "instead of standing down"
    )

    _SECTIONS = ("controllers", "machinery", "scheduling", "sessions")

    def _aborts(self, handler: ast.ExceptHandler) -> bool:
        for node in _live_walk(handler.body):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call):
                chain = _attr_chain(node.func)
                if any(
                    part_l in p.lower()
                    for p in chain
                    for part_l in _ABORTISH_PARTS
                ):
                    return True
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    tchain = _attr_chain(target)
                    if tchain and any(
                        marker in tchain[-1].lower()
                        for marker in _FENCED_STATE_ATTRS
                    ):
                        return True
        return False

    def check_program(self, program) -> Iterator[Finding]:
        ea = ExceptionAnalysis.of(program)
        for qual, fn in sorted(ea._funcs.items()):
            if fn.src.section not in self._SECTIONS:
                continue
            body = fn.node.body if hasattr(fn.node, "body") else []
            for node in _live_walk(body):
                if not isinstance(node, ast.Try):
                    continue
                yield from self._check_try(ea, fn, node)

    def _check_try(self, ea: ExceptionAnalysis, fn, node: ast.Try):
        handlers = [ea.handler_spec(fn.src, h) for h in node.handlers]
        # which fencing errors the try body can actually raise (for the
        # broad-clause half); witness for the message
        body_sites, _complete, _pending = ea._collect(fn, node.body, (), set())
        reach: dict[str, Site] = {}
        for err, site, in_can, _in_esc in body_sites:
            if in_can and err in FENCING:
                reach.setdefault(err, site)
        remaining = set(reach)
        for spec in handlers:
            h = spec.node
            direct = [n for n in spec.names if n in FENCING]
            caught_here = {e for e in remaining if ea.catches(spec.names, e)}
            if spec.passthrough or self._aborts(h):
                remaining -= caught_here
                continue
            if _marked(fn.src, h, "fencing-ok"):
                remaining -= caught_here
                continue
            if direct:
                yield self.finding(
                    fn.src,
                    h,
                    f"handler catches {'/'.join(sorted(set(direct)))} and "
                    "continues; a fenced replica must stand down — "
                    "re-raise, stop the component, or record the "
                    "deposition (`# fencing-ok: <reason>` if deliberate)",
                )
            elif caught_here:
                err = sorted(caught_here)[0]
                yield self.finding(
                    fn.src,
                    h,
                    f"broad handler absorbs {err} raised in its try "
                    f"body ({render_chain(reach[err].chain)}) and "
                    "continues; catch the fencing error first and "
                    "stand down, or annotate with "
                    "`# fencing-ok: <reason>`",
                )
            remaining -= caught_here


# ---------------------------------------------------------------------------
# dead-except


@register
class DeadExceptRule(ProgramRule):
    """Refactor drift: an ``except <PlatformError>:`` whose try body —
    proven fully analyzable, every call resolved/verb-modeled/foreign —
    cannot raise anything the clause catches. The handler is dead code
    that silently documents a failure mode that no longer exists (or
    worse, was moved out from under it). Conservative by construction:
    any call the inference cannot account for disables the check for
    that body."""

    id = "dead-except"
    description = (
        "except clause catching a platform error its try body provably "
        "cannot raise"
    )

    _SECTIONS = (
        "controllers",
        "machinery",
        "scheduling",
        "sessions",
        "web",
        "webhooks",
    )
    _NEVER_DEAD = frozenset({"Exception", "BaseException"})

    def check_program(self, program) -> Iterator[Finding]:
        ea = ExceptionAnalysis.of(program)
        for qual, fn in sorted(ea._funcs.items()):
            if fn.src.section not in self._SECTIONS:
                continue
            body = fn.node.body if hasattr(fn.node, "body") else []
            for node in _live_walk(body):
                if not isinstance(node, ast.Try):
                    continue
                yield from self._check_try(ea, fn, node)

    def _check_try(self, ea: ExceptionAnalysis, fn, node: ast.Try):
        sites, complete, _pending = ea._collect(fn, node.body, (), set())
        if not complete:
            return
        raisable = {err for err, _site, in_can, _in_esc in sites if in_can}
        absorbed: set[str] = set()
        for handler in node.handlers:
            spec = ea.handler_spec(fn.src, handler)
            if not spec.names or any(
                n not in ea.hierarchy or n in self._NEVER_DEAD
                for n in spec.names
            ):
                # broad / non-platform clauses: other rules' turf; they
                # still absorb for later clauses
                absorbed |= {e for e in raisable if ea.catches(spec.names, e)}
                continue
            live = raisable - absorbed
            if not any(ea.catches(spec.names, e) for e in live):
                yield self.finding(
                    fn.src,
                    handler,
                    f"except {'/'.join(spec.names)} is dead: no "
                    "reachable operation in the try body can raise it "
                    "(every call resolved); remove the handler or the "
                    "drift that orphaned it",
                )
            absorbed |= {e for e in raisable if ea.catches(spec.names, e)}
