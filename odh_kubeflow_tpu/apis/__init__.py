"""CRD schemas for the TPU notebook platform.

Mirrors the reference's API groups (SURVEY.md §1 L1):
- ``Notebook``    kubeflow.org/v1beta1, namespaced
  (reference: components/notebook-controller/api/v1beta1/notebook_types.go:27-63)
- ``Profile``     kubeflow.org/v1, cluster-scoped
  (reference: components/profile-controller/api/v1/profile_types.go:36-60)
- ``Tensorboard`` tensorboard.kubeflow.org/v1alpha1, namespaced
  (reference: components/tensorboard-controller/api/v1alpha1/tensorboard_types.go:28-32)
- ``PodDefault``  kubeflow.org/v1alpha1, namespaced
  (reference: components/admission-webhook/pkg/apis/settings/v1alpha1/poddefault_types.go:27-78)

Objects are dict-shaped; this package contributes registration,
validation (as validating-admission hooks), and typed builders.
"""

from odh_kubeflow_tpu.machinery.store import APIServer, Denied, Invalid

GROUP = "kubeflow.org"

NOTEBOOK_API_VERSION = f"{GROUP}/v1beta1"
PROFILE_API_VERSION = f"{GROUP}/v1"
TENSORBOARD_API_VERSION = f"tensorboard.{GROUP}/v1alpha1"
PODDEFAULT_API_VERSION = f"{GROUP}/v1alpha1"

# annotations shared across controllers (reference: culler.go:40-41,
# notebook_controller.go:420-422, jwa patch.py:18-75)
STOP_ANNOTATION = "kubeflow-resource-stopped"
LAST_ACTIVITY_ANNOTATION = "notebooks.kubeflow.org/last-activity"
LAST_ACTIVITY_CHECK_ANNOTATION = (
    "notebooks.kubeflow.org/last_activity_check_timestamp"
)

# suspend-to-checkpoint contract (sessions/ subsystem, NotebookOS-style,
# arXiv 2503.20591): SUSPENDED_AT alongside STOP_ANNOTATION means
# "suspended, resumable" — the session manager checkpoints kernel state
# before the slice is released, and JWA offers resume instead of a cold
# start. RESUME_REQUESTED stamps when the user reopened, feeding the
# warm-resume latency histogram.
SUSPENDED_AT_ANNOTATION = "notebooks.kubeflow.org/suspended-at"
SUSPEND_REASON_ANNOTATION = "notebooks.kubeflow.org/suspend-reason"
RESUME_REQUESTED_ANNOTATION = "notebooks.kubeflow.org/resume-requested-at"
# audit trail for duty-cycle-aware culling: the duty sample the culler
# observed last (value + probe timestamp), stamped every probe so a
# cull/keep decision is explainable after the fact
TPU_DUTY_CYCLE_ANNOTATION = "notebooks.kubeflow.org/last-observed-duty-cycle"

# TPU scheduling contract (replaces the reference's nvidia.com/gpu path,
# BASELINE.json north star)
TPU_RESOURCE = "google.com/tpu"
TPU_ACCELERATOR_ANNOTATION = "notebooks.kubeflow.org/tpu-accelerator"
TPU_TOPOLOGY_ANNOTATION = "notebooks.kubeflow.org/tpu-topology"
TPU_ACCEL_NODE_LABEL = "cloud.google.com/gke-tpu-accelerator"
TPU_TOPO_NODE_LABEL = "cloud.google.com/gke-tpu-topology"
# pod-label opt-in for the TPU-runtime PodDefault (webhooks/poddefault
# injects libtpu/XLA env into pods carrying it; JWA and the warm-pool
# controller stamp it on TPU-flavored notebooks)
TPU_RUNTIME_LABEL = "tpu-runtime"


def notebook_agent_url(
    notebook, cluster_domain: str = "cluster.local", port: int = 8890
) -> str:
    """Base URL of the in-pod agent sidecar family behind the notebook
    Service (tpu-activity probe, session snapshot/restore hooks) — ONE
    addressing convention, shared by the culler and the session
    manager so the two can't drift."""
    from odh_kubeflow_tpu.machinery import objects as obj_util

    name = obj_util.name_of(notebook)
    ns = obj_util.namespace_of(notebook)
    return f"http://{name}.{ns}.svc.{cluster_domain}:{port}"


def pod_spec_tpu_chips(pod_spec) -> float:
    """Summed ``google.com/tpu`` container limits of a pod spec — THE
    chip-accounting primitive (kubelet ledger, scheduler snapshots,
    workload derivation all count the same way)."""
    from odh_kubeflow_tpu.machinery import objects as obj_util

    total = 0.0
    for c in (pod_spec or {}).get("containers") or []:
        limits = obj_util.get_path(c, "resources", "limits", default={}) or {}
        total += obj_util.parse_quantity(limits.get(TPU_RESOURCE, 0))
    return total


def pod_tpu_chips(pod) -> float:
    return pod_spec_tpu_chips((pod or {}).get("spec"))


def _validate_notebook(req):
    if req.operation not in ("CREATE", "UPDATE"):
        return
    spec = req.obj.get("spec") or {}
    template = spec.get("template") or {}
    containers = (template.get("spec") or {}).get("containers")
    if not containers:
        raise Invalid("Notebook spec.template.spec.containers must be non-empty")


def _validate_profile(req):
    if req.operation not in ("CREATE", "UPDATE"):
        return
    owner = (req.obj.get("spec") or {}).get("owner") or {}
    if not owner.get("name"):
        raise Invalid("Profile spec.owner.name is required")


def _validate_tensorboard(req):
    if req.operation not in ("CREATE", "UPDATE"):
        return
    if not (req.obj.get("spec") or {}).get("logspath"):
        raise Invalid("Tensorboard spec.logspath is required")


def install_default_cluster_roles(api: APIServer) -> None:
    """The kubeflow-admin/edit/view ClusterRoles every profile
    RoleBinding references (the reference ships these via manifests;
    kfam maps its role names onto them, bindings.go:39-46). Idempotent."""
    kf_groups = [
        "kubeflow.org",
        "tensorboard.kubeflow.org",
        # sessions/: users see their own suspend/resume checkpoints
        "sessions.kubeflow.org",
        # warmup/: warm pools + compile-cache entries are visible so
        # the spawner can explain a warm (or cold) handout
        "warmup.kubeflow.org",
    ]
    kf_resources = [
        "notebooks",
        "poddefaults",
        "tensorboards",
        "profiles",
        "sessioncheckpoints",
        "warmpools",
        "compilecacheentries",
    ]
    core_resources = [
        "persistentvolumeclaims",
        "pods",
        "pods/log",
        "services",
        "events",
        "configmaps",
        "nodes",
        # the spawner shows used/hard TPU chips from kf-resource-quota
        "resourcequotas",
    ]
    # secrets deliberately excluded from view (upstream view roles do the
    # same: a read-only observer must not hold credentials)
    roles = {
        "kubeflow-admin": [
            {"apiGroups": kf_groups + [""],
             "resources": kf_resources + core_resources + ["secrets"],
             "verbs": ["*"]},
        ],
        "kubeflow-edit": [
            {"apiGroups": kf_groups, "resources": kf_resources, "verbs": ["*"]},
            {"apiGroups": [""], "resources": core_resources + ["secrets"],
             "verbs": ["get", "list", "watch", "create", "update", "patch", "delete"]},
        ],
        "kubeflow-view": [
            {"apiGroups": kf_groups + [""], "resources": kf_resources + core_resources,
             "verbs": ["get", "list", "watch"]},
        ],
    }
    for name, rules in roles.items():
        api.create_or_get(
            {
                "apiVersion": "rbac.authorization.k8s.io/v1",
                "kind": "ClusterRole",
                "metadata": {"name": name},
                "rules": rules,
            }
        )


def register_crds(api: APIServer) -> None:
    api.register_kind(NOTEBOOK_API_VERSION, "Notebook", "notebooks", True)
    api.register_kind(PROFILE_API_VERSION, "Profile", "profiles", False)
    api.register_kind(TENSORBOARD_API_VERSION, "Tensorboard", "tensorboards", True)
    api.register_kind(PODDEFAULT_API_VERSION, "PodDefault", "poddefaults", True)
    api.register_admission_hook(
        {"Notebook"}, _validate_notebook, mutating=False, name="validate-notebook"
    )
    api.register_admission_hook(
        {"Profile"}, _validate_profile, mutating=False, name="validate-profile"
    )
    api.register_admission_hook(
        {"Tensorboard"},
        _validate_tensorboard,
        mutating=False,
        name="validate-tensorboard",
    )
