"""Pipeline parallelism: a GPipe-style combinator over the ``pipe``
mesh axis.

The reference platform has no parallelism layer (SURVEY.md §2.4); this
module completes the rebuild's dp/fsdp/ep/cp/tp/pp axis set. Design is
the standard JAX/TPU pipelining pattern ("How to Scale Your Model"):

- layer-stacked parameters ([L, ...] leaves) are sharded over ``pipe``
  on their leading dim — device p holds layers [p·L/S, (p+1)·L/S), its
  stage, with no data movement;
- ``shard_map`` runs **manual over the pipe axis only**
  (``axis_names={'pipe'}``): the schedule below is hand-written, while
  fsdp/tensor/expert shardings inside each stage stay under GSPMD
  exactly as in non-pipelined execution — the two compose;
- the batch is split into M microbatches; one ``lax.scan`` over M+S-1
  ticks runs each device's stage on the microbatch it holds and passes
  the activation to the next stage with a single ``ppermute`` hop
  (point-to-point, DCN-tolerant — pipeline stages are the natural
  cross-slice axis);
- schedule bubble = (S-1)/(M+S-1), the GPipe trade; gradients flow
  through scan + ppermute (whose transpose is the reverse ppermute),
  so ``jax.grad`` of a pipelined forward needs no hand-written
  backward schedule.

Constraints (by design, to stay XLA-friendly): the stage function is
shape-preserving on the microbatch ([mb, ...] in = out, true of
transformer blocks), every stage runs the same ``stage_fn`` over its
own layer slice, and the layer count and batch must divide by the
stage count and microbatch count respectively.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from odh_kubeflow_tpu.parallel.mesh import AXIS_PIPE

Params = Any


def pipeline_apply(
    stage_fn: Callable,
    layer_params: Params,  # leaves [L, ...], dim0 sharded over `axis`
    x: jnp.ndarray,  # [B, ...] (replicated over `axis`)
    *,
    num_microbatches: int,
    aux: Optional[Params] = None,  # leaves [M, ...]: per-microbatch consts
    axis: str = AXIS_PIPE,
    with_aux_out: bool = False,
) -> "jnp.ndarray | tuple[jnp.ndarray, jnp.ndarray]":
    """Run ``x`` through the pipelined layer stack; returns [B, ...]
    (or ``(y, aux_sum)`` with ``with_aux_out=True``).

    ``stage_fn(stage_layers, x_mb)`` — or ``stage_fn(stage_layers,
    x_mb, aux_mb)`` when ``aux`` is given — receives this device's
    layer slice (leading dim L/S) and must preserve the microbatch
    shape. ``aux`` carries per-microbatch constants (segment ids, loss
    masks) that follow their microbatch through the pipeline. Call
    under ``jax.set_mesh`` of a mesh containing ``axis``;
    differentiable.

    ``with_aux_out=True``: ``stage_fn`` additionally returns a scalar
    per call (e.g. the MoE router load-balancing loss); returns
    ``(y, aux_sum)`` where ``aux_sum`` totals the scalar over every
    (stage, microbatch) pair — bubble ticks, whose activations are
    garbage, are excluded from the sum. Divide by ``num_microbatches``
    for a per-batch quantity comparable to the unpipelined forward.
    """
    mesh = jax.sharding.get_abstract_mesh()
    if axis not in mesh.axis_names:
        raise ValueError(f"mesh has no {axis!r} axis: {mesh.axis_names}")
    S = mesh.shape[axis]
    for leaf in jax.tree_util.tree_leaves(layer_params):
        if leaf.shape[0] % S:
            raise ValueError(
                f"layer dim {leaf.shape[0]} does not divide into {S} stages"
            )
    B = x.shape[0]
    M = num_microbatches
    if B % M:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    mb = B // M
    xm = x.reshape(M, mb, *x.shape[1:])

    # XLA's CPU backend aborts ("Invalid binary instruction opcode
    # copy") on bf16 ppermute/psum under a partial-manual shard_map —
    # minimal repro: scan+ppermute+psum of a bf16 carry. Work around it
    # on CPU (tests / dryrun) by carrying activations between stages in
    # f32: stages still compute in the model dtype, and since each
    # stage's outputs are already bf16-rounded values, the up/down
    # casts are bit-exact. Real TPU backends keep native bf16 transit.
    transit_f32 = (
        x.dtype == jnp.bfloat16 and jax.default_backend() == "cpu"
    )
    stage_dtype = x.dtype
    if transit_f32:
        # cast OUTSIDE the shard_map: a bf16 input would psum its bf16
        # cotangent at the manual boundary in the backward — the same
        # crashing pattern
        xm = xm.astype(jnp.float32)

    param_specs = jax.tree_util.tree_map(lambda _l: P(axis), layer_params)
    aux_specs = jax.tree_util.tree_map(lambda _l: P(), aux)

    out_specs = (P(), P()) if with_aux_out else P()

    @partial(
        shard_map,
        mesh=mesh,
        axis_names=frozenset({axis}),  # manual over pipe ONLY: fsdp/
        # tensor/expert shardings inside the stage stay under GSPMD
        in_specs=(param_specs, P(), aux_specs),
        out_specs=out_specs,
        check_vma=False,
    )
    def run(stage_layers, xm, aux):
        idx = jax.lax.axis_index(axis)
        perm = [(i, (i + 1) % S) for i in range(S)]

        y0 = jnp.zeros_like(xm)
        state0 = jnp.zeros_like(xm[0])
        aux_acc0 = jnp.zeros((), jnp.float32)

        def tick(carry, t):
            state, y, aux_acc = carry
            # stage 0 ingests microbatch t while t < M
            x_t = jax.lax.dynamic_index_in_dim(
                xm, jnp.clip(t, 0, M - 1), 0, keepdims=False
            )
            take_input = (idx == 0) & (t < M)
            state = jnp.where(take_input, x_t, state)
            state_in = state.astype(stage_dtype) if transit_f32 else state
            if aux is None:
                out = stage_fn(stage_layers, state_in)
            else:
                # stage idx processes microbatch t - idx at tick t
                mb_idx = jnp.clip(t - idx, 0, M - 1)
                aux_t = jax.tree_util.tree_map(
                    lambda a: jax.lax.dynamic_index_in_dim(
                        a, mb_idx, 0, keepdims=False
                    ),
                    aux,
                )
                out = stage_fn(stage_layers, state_in, aux_t)
            if with_aux_out:
                out, aux_s = out
                # bubble ticks run on garbage activations: only ticks
                # where this stage holds a real microbatch contribute
                valid = (t >= idx) & (t - idx < M)
                aux_acc = aux_acc + jnp.where(
                    valid, aux_s.astype(jnp.float32), 0.0
                )
            if transit_f32:
                out = out.astype(jnp.float32)
            # the last stage owns microbatch t-(S-1)'s final activation
            write_t = t - (S - 1)
            write = (idx == S - 1) & (write_t >= 0)
            y = jnp.where(
                write,
                jax.lax.dynamic_update_index_in_dim(
                    y, out, jnp.clip(write_t, 0, M - 1), 0
                ),
                y,
            )
            # hand the activation to the next stage (single p2p hop)
            state = jax.lax.ppermute(out, axis, perm)
            return (state, y, aux_acc), None

        (_, y, aux_acc), _ = jax.lax.scan(
            tick, (state0, y0, aux_acc0), jnp.arange(M + S - 1)
        )
        # y is populated only on the last stage; psum replicates it
        # (every other stage contributes zeros)
        y = jax.lax.psum(jnp.where(idx == S - 1, y, jnp.zeros_like(y)), axis)
        if with_aux_out:
            return y, jax.lax.psum(aux_acc, axis)
        return y

    out = run(layer_params, xm, aux)
    if with_aux_out:
        y, aux_sum = out
        return y.reshape(B, *x.shape[1:]).astype(x.dtype), aux_sum
    return out.reshape(B, *x.shape[1:]).astype(x.dtype)
