"""Pipeline parallelism: a GPipe-style combinator over the ``pipe``
mesh axis.

The reference platform has no parallelism layer (SURVEY.md §2.4); this
module completes the rebuild's dp/fsdp/ep/cp/tp/pp axis set. Design is
the standard JAX/TPU pipelining pattern ("How to Scale Your Model"):

- the layer stack is pre-split into S equal stages whose parameters
  carry a leading stage dim sharded over ``pipe`` — ``shard_map``
  hands each device exactly its stage's weights, nothing moves;
- the batch is split into M microbatches; inside one ``lax.scan`` over
  M+S-1 ticks, every device runs its stage on the microbatch it holds
  and passes the activation to the next stage with a single
  ``ppermute`` hop (point-to-point, ICI/DCN-friendly);
- schedule bubble = (S-1)/(M+S-1), the GPipe trade; gradients flow
  through the scan + ppermute (whose transpose is the reverse
  ppermute), so ``jax.grad`` of a pipelined forward just works — no
  hand-written backward schedule.

Constraints (by design, to stay XLA-friendly): the stage function must
be shape-preserving ([mb, ...] in = out, true of transformer blocks),
every stage runs the same ``stage_fn`` over its own weights, and
M % microbatches must divide the batch.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from odh_kubeflow_tpu.parallel.mesh import AXIS_PIPE

Params = Any


def stack_stages(layer_params: Params, num_stages: int) -> Params:
    """[L, ...] layer-stacked params → [S, L/S, ...] stage-stacked."""

    def split(leaf):
        L = leaf.shape[0]
        if L % num_stages:
            raise ValueError(f"{L} layers do not split into {num_stages} stages")
        return leaf.reshape(num_stages, L // num_stages, *leaf.shape[1:])

    return jax.tree_util.tree_map(split, layer_params)


def pipeline_apply(
    stage_fn: Callable[[Params, jnp.ndarray], jnp.ndarray],
    stage_params: Params,  # leaves [S, ...], S = mesh extent of `pipe`
    x: jnp.ndarray,  # [B, ...] (replicated over `pipe`)
    *,
    num_microbatches: int,
    axis: str = AXIS_PIPE,
) -> jnp.ndarray:
    """Run ``x`` through S pipeline stages; returns [B, ...].

    ``stage_fn(params_for_one_stage, x_mb) -> y_mb`` must preserve the
    microbatch shape. Call under ``jax.set_mesh`` of a mesh containing
    ``axis``; differentiable.
    """
    mesh = jax.sharding.get_abstract_mesh()
    if axis not in mesh.axis_names:
        raise ValueError(f"mesh has no {axis!r} axis: {mesh.axis_names}")
    S = mesh.shape[axis]
    B = x.shape[0]
    M = num_microbatches
    if B % M:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    mb = B // M
    xm = x.reshape(M, mb, *x.shape[1:])

    param_specs = jax.tree_util.tree_map(
        lambda _leaf: P(axis), stage_params
    )

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        check_vma=False,
    )
    def run(stage_params_local, xm):
        # shard_map hands this device leaves of shape [1, ...]: its stage
        my_params = jax.tree_util.tree_map(lambda a: a[0], stage_params_local)
        idx = jax.lax.axis_index(axis)
        perm = [(i, (i + 1) % S) for i in range(S)]

        y0 = jnp.zeros_like(xm)
        state0 = jnp.zeros_like(xm[0])

        def tick(carry, t):
            state, y = carry
            # stage 0 ingests microbatch t while t < M
            x_t = jax.lax.dynamic_index_in_dim(
                xm, jnp.clip(t, 0, M - 1), 0, keepdims=False
            )
            take_input = (idx == 0) & (t < M)
            state = jnp.where(take_input, x_t, state)
            out = stage_fn(my_params, state)
            # the last stage owns microbatch t-(S-1)'s final activation
            write_t = t - (S - 1)
            write = (idx == S - 1) & (write_t >= 0)
            y = jnp.where(
                write,
                jax.lax.dynamic_update_index_in_dim(
                    y, out, jnp.clip(write_t, 0, M - 1), 0
                ),
                y,
            )
            # hand the activation to the next stage (single p2p hop)
            state = jax.lax.ppermute(out, axis, perm)
            return (state, y), None

        (_, y), _ = jax.lax.scan(
            tick, (state0, y0), jnp.arange(M + S - 1)
        )
        # y is populated only on the last stage; psum replicates it
        # (every other stage contributes zeros)
        return jax.lax.psum(jnp.where(idx == S - 1, y, jnp.zeros_like(y)), axis)

    y = run(stage_params, xm)
    return y.reshape(B, *x.shape[1:])
