"""Pipeline parallelism: a GPipe-style combinator over the ``pipe``
mesh axis.

The reference platform has no parallelism layer (SURVEY.md §2.4); this
module completes the rebuild's dp/fsdp/ep/cp/tp/pp axis set. Design is
the standard JAX/TPU pipelining pattern ("How to Scale Your Model"):

- layer-stacked parameters ([L, ...] leaves) are sharded over ``pipe``
  on their leading dim — device p holds layers [p·L/S, (p+1)·L/S), its
  stage, with no data movement;
- ``shard_map`` runs **manual over the pipe axis only**
  (``axis_names={'pipe'}``): the schedule below is hand-written, while
  fsdp/tensor/expert shardings inside each stage stay under GSPMD
  exactly as in non-pipelined execution — the two compose;
- the batch is split into M microbatches; one ``lax.scan`` over M+S-1
  ticks runs each device's stage on the microbatch it holds and passes
  the activation to the next stage with a single ``ppermute`` hop
  (point-to-point, DCN-tolerant — pipeline stages are the natural
  cross-slice axis);
- schedule bubble = (S-1)/(M+S-1), the GPipe trade; gradients flow
  through scan + ppermute (whose transpose is the reverse ppermute),
  so ``jax.grad`` of a pipelined forward needs no hand-written
  backward schedule.

Constraints (by design, to stay XLA-friendly): the stage function is
shape-preserving on the microbatch ([mb, ...] in = out, true of
transformer blocks), every stage runs the same ``stage_fn`` over its
own layer slice, and the layer count and batch must divide by the
stage count and microbatch count respectively.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from odh_kubeflow_tpu.parallel.mesh import AXIS_PIPE

Params = Any


def pipeline_apply(
    stage_fn: Callable,
    layer_params: Params,  # leaves [L, ...], dim0 sharded over `axis`
    x: jnp.ndarray,  # [B, ...] (replicated over `axis`)
    *,
    num_microbatches: int,
    aux: Optional[Params] = None,  # leaves [M, ...]: per-microbatch consts
    axis: str = AXIS_PIPE,
    with_aux_out: bool = False,
) -> "jnp.ndarray | tuple[jnp.ndarray, jnp.ndarray]":
    """Run ``x`` through the pipelined layer stack; returns [B, ...]
    (or ``(y, aux_sum)`` with ``with_aux_out=True``).

    ``stage_fn(stage_layers, x_mb)`` — or ``stage_fn(stage_layers,
    x_mb, aux_mb)`` when ``aux`` is given — receives this device's
    layer slice (leading dim L/S) and must preserve the microbatch
    shape. ``aux`` carries per-microbatch constants (segment ids, loss
    masks) that follow their microbatch through the pipeline. Call
    under ``jax.set_mesh`` of a mesh containing ``axis``;
    differentiable.

    ``with_aux_out=True``: ``stage_fn`` additionally returns a scalar
    per call (e.g. the MoE router load-balancing loss); returns
    ``(y, aux_sum)`` where ``aux_sum`` totals the scalar over every
    (stage, microbatch) pair — bubble ticks, whose activations are
    garbage, are excluded from the sum. Divide by ``num_microbatches``
    for a per-batch quantity comparable to the unpipelined forward.
    """
    mesh = jax.sharding.get_abstract_mesh()
    if axis not in mesh.axis_names:
        raise ValueError(f"mesh has no {axis!r} axis: {mesh.axis_names}")
    S = mesh.shape[axis]
    for leaf in jax.tree_util.tree_leaves(layer_params):
        if leaf.shape[0] % S:
            raise ValueError(
                f"layer dim {leaf.shape[0]} does not divide into {S} stages"
            )
    B = x.shape[0]
    M = num_microbatches
    if B % M:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    mb = B // M
    xm = x.reshape(M, mb, *x.shape[1:])

    # XLA's CPU backend aborts ("Invalid binary instruction opcode
    # copy") on bf16 ppermute/psum under a partial-manual shard_map —
    # minimal repro: scan+ppermute+psum of a bf16 carry. Work around it
    # on CPU (tests / dryrun) by carrying activations between stages in
    # f32: stages still compute in the model dtype, and since each
    # stage's outputs are already bf16-rounded values, the up/down
    # casts are bit-exact. Real TPU backends keep native bf16 transit.
    transit_f32 = (
        x.dtype == jnp.bfloat16 and jax.default_backend() == "cpu"
    )
    stage_dtype = x.dtype
    if transit_f32:
        # cast OUTSIDE the shard_map: a bf16 input would psum its bf16
        # cotangent at the manual boundary in the backward — the same
        # crashing pattern
        xm = xm.astype(jnp.float32)

    param_specs = jax.tree_util.tree_map(lambda _l: P(axis), layer_params)
    aux_specs = jax.tree_util.tree_map(lambda _l: P(), aux)

    out_specs = (P(), P()) if with_aux_out else P()

    @partial(
        shard_map,
        mesh=mesh,
        axis_names=frozenset({axis}),  # manual over pipe ONLY: fsdp/
        # tensor/expert shardings inside the stage stay under GSPMD
        in_specs=(param_specs, P(), aux_specs),
        out_specs=out_specs,
        check_vma=False,
    )
    def run(stage_layers, xm, aux):
        idx = jax.lax.axis_index(axis)
        perm = [(i, (i + 1) % S) for i in range(S)]

        y0 = jnp.zeros_like(xm)
        state0 = jnp.zeros_like(xm[0])
        aux_acc0 = jnp.zeros((), jnp.float32)

        def tick(carry, t):
            state, y, aux_acc = carry
            # stage 0 ingests microbatch t while t < M
            x_t = jax.lax.dynamic_index_in_dim(
                xm, jnp.clip(t, 0, M - 1), 0, keepdims=False
            )
            take_input = (idx == 0) & (t < M)
            state = jnp.where(take_input, x_t, state)
            state_in = state.astype(stage_dtype) if transit_f32 else state
            if aux is None:
                out = stage_fn(stage_layers, state_in)
            else:
                # stage idx processes microbatch t - idx at tick t
                mb_idx = jnp.clip(t - idx, 0, M - 1)
                aux_t = jax.tree_util.tree_map(
                    lambda a: jax.lax.dynamic_index_in_dim(
                        a, mb_idx, 0, keepdims=False
                    ),
                    aux,
                )
                out = stage_fn(stage_layers, state_in, aux_t)
            if with_aux_out:
                out, aux_s = out
                # bubble ticks run on garbage activations: only ticks
                # where this stage holds a real microbatch contribute
                valid = (t >= idx) & (t - idx < M)
                aux_acc = aux_acc + jnp.where(
                    valid, aux_s.astype(jnp.float32), 0.0
                )
            if transit_f32:
                out = out.astype(jnp.float32)
            # the last stage owns microbatch t-(S-1)'s final activation
            write_t = t - (S - 1)
            write = (idx == S - 1) & (write_t >= 0)
            y = jnp.where(
                write,
                jax.lax.dynamic_update_index_in_dim(
                    y, out, jnp.clip(write_t, 0, M - 1), 0
                ),
                y,
            )
            # hand the activation to the next stage (single p2p hop)
            state = jax.lax.ppermute(out, axis, perm)
            return (state, y, aux_acc), None

        (_, y, aux_acc), _ = jax.lax.scan(
            tick, (state0, y0, aux_acc0), jnp.arange(M + S - 1)
        )
        # y is populated only on the last stage; psum replicates it
        # (every other stage contributes zeros)
        y = jax.lax.psum(jnp.where(idx == S - 1, y, jnp.zeros_like(y)), axis)
        if with_aux_out:
            return y, jax.lax.psum(aux_acc, axis)
        return y

    out = run(layer_params, xm, aux)
    if with_aux_out:
        y, aux_sum = out
        return y.reshape(B, *x.shape[1:]).astype(x.dtype), aux_sum
    return out.reshape(B, *x.shape[1:]).astype(x.dtype)


def pipeline_train_1f1b(
    stage_fn: Callable,
    head_fn: Callable,
    layer_params: Params,  # leaves [L, ...], dim0 sharded over `axis`
    head_params: Params,
    x: jnp.ndarray,  # [B, ...]
    *,
    num_microbatches: int,
    axis: str = AXIS_PIPE,
):
    """One fused forward+backward pass under the 1F1B schedule.

    GPipe (``pipeline_apply`` + ``jax.grad``) runs all M forwards, then
    all M backwards: every stage holds **M** in-flight microbatch
    inputs. 1F1B interleaves — device s runs F(m) at tick ``s + 2m``
    and B(m) at tick ``2S-1-s+2m``, so an input is freed S-s ticks
    after it is stored and peak residency is **min(S, M)** inputs, at
    the same bubble 2(S-1)/(2(M+S-1)-1). The schedule cannot be
    reached through ``jax.grad`` of a forward-only combinator (the
    backward would only start after the last forward), so this is a
    hand-written fused loop; it requires the LOSS to be computable per
    microbatch at the last stage — ``head_fn(head_params, y_mb)`` →
    scalar — which is also what lets B(m) begin one tick after F(m).

    Returns ``(loss_mean, dlayer_params, dhead_params, dx)`` with
    ``dlayer_params`` stage-sharded like ``layer_params``, so the
    result plugs into the same optimizer update as the GPipe+autodiff
    path (equivalence is pinned by tests/test_pipeline.py).

    Each tick runs at most one of {F, B} per device (``lax.cond`` — no
    double compute) plus two point-to-point hops (activations down,
    cotangents up). The backward recomputes the stage forward from the
    stored input (remat-style ``jax.vjp``), matching the GPipe path's
    per-layer remat cost.
    """
    mesh = jax.sharding.get_abstract_mesh()
    if axis not in mesh.axis_names:
        raise ValueError(f"mesh has no {axis!r} axis: {mesh.axis_names}")
    S = mesh.shape[axis]
    B = x.shape[0]
    M = num_microbatches
    if B % M:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    mb = B // M
    xm = x.reshape(M, mb, *x.shape[1:])

    transit_f32 = (
        x.dtype == jnp.bfloat16 and jax.default_backend() == "cpu"
    )
    stage_dtype = x.dtype
    if transit_f32:
        xm = xm.astype(jnp.float32)
    carry_dtype = xm.dtype

    param_specs = jax.tree_util.tree_map(lambda _l: P(axis), layer_params)
    head_specs = jax.tree_util.tree_map(lambda _l: P(), head_params)
    # last tick is stage 0's B(M-1) at 2S-1+2(M-1) = 2(M+S)-3,
    # so the schedule spans 2(M+S-1) ticks
    T = 2 * (M + S - 1)
    depth = min(S, M)  # in-flight input ring — the 1F1B memory bound

    @partial(
        shard_map,
        mesh=mesh,
        axis_names=frozenset({axis}),
        in_specs=(param_specs, head_specs, P()),
        out_specs=(P(), param_specs, P(), P()),
        check_vma=False,
    )
    def run(stage_layers, head_params, xm):
        idx = jax.lax.axis_index(axis)
        down = [(i, (i + 1) % S) for i in range(S)]
        up = [((i + 1) % S, i) for i in range(S)]

        def fwd_stage(layers, x_in):
            x_c = x_in.astype(stage_dtype) if transit_f32 else x_in
            out = stage_fn(layers, x_c)
            return out.astype(carry_dtype) if transit_f32 else out

        def head_loss(hp, y_mb):
            y_c = y_mb.astype(stage_dtype) if transit_f32 else y_mb
            return head_fn(hp, y_c)

        zero_grads = jax.tree_util.tree_map(
            lambda a: jnp.zeros(a.shape, jnp.float32), stage_layers
        )
        zero_hgrads = jax.tree_util.tree_map(
            lambda a: jnp.zeros(a.shape, jnp.float32), head_params
        )
        carry0 = dict(
            act_in=jnp.zeros_like(xm[0]),  # from previous stage
            grad_in=jnp.zeros_like(xm[0]),  # from next stage
            dy_pending=jnp.zeros_like(xm[0]),  # last stage: F→B handoff
            stack=jnp.zeros((depth, *xm.shape[1:]), carry_dtype),
            dxm=jnp.zeros_like(xm),  # stage 0: input cotangents
            grads=zero_grads,
            hgrads=zero_hgrads,
            loss=jnp.zeros((), jnp.float32),
        )

        def tick(carry, t):
            s = idx
            f_off = t - s
            is_f = (f_off >= 0) & (f_off % 2 == 0) & (f_off < 2 * M)
            f_m = jnp.clip(f_off // 2, 0, M - 1)
            b_off = t - (2 * S - 1 - s)
            is_b = (b_off >= 0) & (b_off % 2 == 0) & (b_off < 2 * M)
            b_m = jnp.clip(b_off // 2, 0, M - 1)

            def do_f(c):
                x_t = jax.lax.dynamic_index_in_dim(
                    xm, f_m, 0, keepdims=False
                )
                x_in = jnp.where(s == 0, x_t, c["act_in"])
                out = fwd_stage(stage_layers, x_in)
                stack = jax.lax.dynamic_update_index_in_dim(
                    c["stack"], x_in, f_m % depth, 0
                )
                # last stage: per-microbatch loss, its activation
                # cotangent (so B(m) runs on the very next tick), and
                # the head-param grads — one vjp, no recompute
                def last(c):
                    loss_m, (dh, dy) = jax.value_and_grad(
                        head_loss, argnums=(0, 1)
                    )(head_params, out)
                    dy = dy.astype(carry_dtype) if transit_f32 else dy
                    return dict(
                        c,
                        dy_pending=dy,
                        loss=c["loss"] + loss_m.astype(jnp.float32),
                        hgrads=jax.tree_util.tree_map(
                            lambda acc, d: acc + d.astype(jnp.float32),
                            c["hgrads"],
                            dh,
                        ),
                    )

                c = dict(c, stack=stack)
                c = jax.lax.cond(s == S - 1, last, lambda c: c, c)
                return c, out

            def skip_f(c):
                return c, jnp.zeros_like(c["act_in"])

            carry, f_out = jax.lax.cond(is_f, do_f, skip_f, carry)

            def do_b(c):
                x_saved = jax.lax.dynamic_index_in_dim(
                    c["stack"], b_m % depth, 0, keepdims=False
                )
                g_in = jnp.where(
                    s == S - 1, c["dy_pending"], c["grad_in"]
                )
                _, pullback = jax.vjp(
                    fwd_stage, stage_layers, x_saved
                )
                dlayers, dx = pullback(g_in)
                grads = jax.tree_util.tree_map(
                    lambda acc, d: acc + d.astype(jnp.float32),
                    c["grads"],
                    dlayers,
                )
                dxm = jnp.where(
                    s == 0,
                    jax.lax.dynamic_update_index_in_dim(
                        c["dxm"], dx, b_m, 0
                    ),
                    c["dxm"],
                )
                return dict(c, grads=grads, dxm=dxm), dx

            def skip_b(c):
                return c, jnp.zeros_like(c["grad_in"])

            carry, b_dx = jax.lax.cond(is_b, do_b, skip_b, carry)

            carry = dict(
                carry,
                act_in=jax.lax.ppermute(f_out, axis, down),
                grad_in=jax.lax.ppermute(b_dx, axis, up),
            )
            return carry, None

        carry, _ = jax.lax.scan(tick, carry0, jnp.arange(T))
        loss = jax.lax.psum(
            jnp.where(idx == S - 1, carry["loss"], 0.0), axis
        )
        hgrads = jax.tree_util.tree_map(
            lambda g: jax.lax.psum(
                jnp.where(idx == S - 1, g, jnp.zeros_like(g)), axis
            ),
            carry["hgrads"],
        )
        dxm = jax.lax.psum(
            jnp.where(idx == 0, carry["dxm"], jnp.zeros_like(carry["dxm"])),
            axis,
        )
        return loss, carry["grads"], hgrads, dxm

    # always trace under jit: jax's EAGER partial-manual shard_map impl
    # re-enters shard_map with an all-axes out spec (_unmatch with
    # check_vma=False) and rejects itself; under jit the path is sound
    loss, dlayers, dhead, dxm = jax.jit(run)(layer_params, head_params, xm)
    # everything reported against the MEAN microbatch loss (what the
    # unpipelined trainer optimizes): per-microbatch cotangents were
    # seeded with 1, so scale the accumulated grads by 1/M too
    inv_m = 1.0 / M
    dlayers = jax.tree_util.tree_map(lambda g: g * inv_m, dlayers)
    dhead = jax.tree_util.tree_map(lambda g: g * inv_m, dhead)
    dx = (dxm * inv_m).reshape(B, *x.shape[1:]).astype(x.dtype)
    return loss / M, dlayers, dhead, dx
