"""Ring attention: context-parallel attention over the ``context`` mesh axis.

Long-context is first-class in the TPU rebuild (the reference platform has
no attention code at all — SURVEY.md §5 "long-context" entry documents its
absence and assigns the capability to this layer). A sequence sharded
across the ``context`` axis never materialises more than a
``[S/C, S/C]`` score block per device:

- each device holds its local Q block permanently;
- K/V blocks rotate around the ring via ``lax.ppermute`` (one ICI
  neighbour hop per step — the same collective pattern the bidirectional
  ICI torus is built for);
- partial attention outputs merge with the online-softmax rescaling used
  by flash attention (running max / numerator / denominator in float32).

The permute for step t+1 is issued *before* the block-t compute, so
XLA's latency-hiding scheduler overlaps the collective-permute with the
two matmuls of the current block.

Causality makes plain ring layouts unbalanced (device ``i`` attends
``i+1`` of ``C`` blocks). Fully-masked blocks are skipped with a
``lax.cond`` so they cost a predicated branch, not matmuls; the
load-balanced zigzag layout is provided by ``zigzag_permute`` /
``zigzag_unpermute`` which callers apply to tokens before/after the
model (each device then owns one chunk from the front and one mirrored
chunk from the back of the sequence — uniform work per device).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from odh_kubeflow_tpu.ops.attention import dense_attention
from odh_kubeflow_tpu.parallel.mesh import (
    AXIS_CONTEXT,
    AXIS_DATA,
    AXIS_FSDP,
    AXIS_TENSOR,
)

_NEG_INF = jnp.float32(-1e30)


def _block_positions(block_idx, block_len: int, layout: str, num_blocks: int):
    """Global token positions covered by ring block ``block_idx``.

    ``plain``  — contiguous chunks: block i owns [i*L, (i+1)*L).
    ``zigzag`` — block i owns chunk i's first half from the sequence
    front and the mirrored half from the back (chunks i and 2C-1-i of
    half-block length), which equalises causal work across the ring.
    """
    if layout == "plain":
        return block_idx * block_len + jnp.arange(block_len)
    half = block_len // 2
    front = block_idx * half + jnp.arange(half)
    back = (2 * num_blocks - 1 - block_idx) * half + jnp.arange(half)
    return jnp.concatenate([front, back])


def _zigzag_index(S: int, num_blocks: int) -> jnp.ndarray:
    """Permutation mapping natural order → zigzag shard order, built
    from the same ``_block_positions`` the in-ring causal mask uses (one
    source of truth for the layout)."""
    assert S % (2 * num_blocks) == 0, (S, num_blocks)
    block_len = S // num_blocks
    return jnp.concatenate(
        [
            _block_positions(i, block_len, "zigzag", num_blocks)
            for i in range(num_blocks)
        ]
    )


def zigzag_permute(x: jnp.ndarray, num_blocks: int, axis: int = 1) -> jnp.ndarray:
    """Reorder a sequence axis so contiguous context shards hold the
    zigzag (front-chunk + mirrored back-chunk) layout. Apply to tokens,
    targets, loss masks, and segment ids before a ``layout='zigzag'``
    ring-attention model; invert with ``zigzag_unpermute``."""
    idx = _zigzag_index(x.shape[axis], num_blocks)
    return jnp.take(x, idx, axis=axis)


def zigzag_unpermute(x: jnp.ndarray, num_blocks: int, axis: int = 1) -> jnp.ndarray:
    S = x.shape[axis]
    idx = _zigzag_index(S, num_blocks)
    inv = jnp.zeros((S,), jnp.int32).at[idx].set(jnp.arange(S, dtype=jnp.int32))
    return jnp.take(x, inv, axis=axis)


def _ring_body(
    q: jnp.ndarray,  # [B, Sq, Hkv, G, hd] local query block (GQA grouped)
    seg_q,  # [B, Sq] or None
    *,
    causal: bool,
    axis_name: str,
    layout: str,
):
    """Returns the scanned ring loop: per-device flash-style accumulation
    of attention over rotating K/V blocks."""
    C = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % C) for i in range(C)]
    scale = q.shape[-1] ** -0.5
    q_pos = None
    if causal:
        q_pos = _block_positions(my, q.shape[1], layout, C)

    def attend(carry_stats, k_blk, v_blk, seg_blk, kv_idx):
        num, den, mx = carry_stats
        scores = jnp.einsum(
            "bqhgd,bkhd->bhgqk", q, k_blk, preferred_element_type=jnp.float32
        )
        scores = scores * scale
        mask = None
        if causal:
            k_pos = _block_positions(kv_idx, k_blk.shape[1], layout, C)
            mask = (q_pos[:, None] >= k_pos[None, :])[None, None, None]
        if seg_q is not None:
            seg = (seg_q[:, :, None] == seg_blk[:, None, :])[:, None, None]
            mask = seg if mask is None else jnp.logical_and(mask, seg)
        if mask is not None:
            scores = jnp.where(mask, scores, _NEG_INF)
        bmax = jnp.max(scores, axis=-1)
        new_mx = jnp.maximum(mx, bmax)
        p = jnp.exp(scores - new_mx[..., None])
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(mx - new_mx)
        den = den * corr + jnp.sum(p, axis=-1)
        # p→bf16 for the PV matmul (MXU path); accumulate f32.
        pv = jnp.einsum(
            "bhgqk,bkhd->bqhgd",
            p.astype(v_blk.dtype),
            v_blk,
            preferred_element_type=jnp.float32,
        )
        num = num * corr.transpose(0, 3, 1, 2)[..., None] + pv
        return num, den, new_mx

    def body(carry, t):
        k_blk, v_blk, seg_blk, stats = carry
        # Issue next-step permutes first: independent of this block's
        # matmuls, so the scheduler overlaps ICI with MXU.
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        seg_nxt = (
            lax.ppermute(seg_blk, axis_name, perm) if seg_blk is not None else None
        )
        kv_idx = (my - t) % C
        if causal and layout == "plain":
            # Blocks strictly in the future are fully masked — skip
            # both matmuls with a predicated branch.
            stats = lax.cond(
                kv_idx > my,
                lambda s: s,
                lambda s: attend(s, k_blk, v_blk, seg_blk, kv_idx),
                stats,
            )
        else:
            stats = attend(stats, k_blk, v_blk, seg_blk, kv_idx)
        return (k_nxt, v_nxt, seg_nxt, stats), None

    return body, C


def _ring_attention_local(
    q: jnp.ndarray,  # [B, Sq, Hq, hd] (per-device block)
    k: jnp.ndarray,  # [B, Sk, Hkv, hd]
    v: jnp.ndarray,
    seg: Optional[jnp.ndarray],  # [B, S] per-device block
    *,
    causal: bool,
    axis_name: str,
    layout: str,
) -> jnp.ndarray:
    B, Sq, Hq, hd = q.shape
    _, Sk, Hkv, _ = k.shape
    assert Hq % Hkv == 0, (Hq, Hkv)
    G = Hq // Hkv
    qg = q.reshape(B, Sq, Hkv, G, hd)

    num = jnp.zeros((B, Sq, Hkv, G, hd), jnp.float32)
    den = jnp.zeros((B, Hkv, G, Sq), jnp.float32)
    mx = jnp.full((B, Hkv, G, Sq), _NEG_INF)

    body, C = _ring_body(
        qg, seg, causal=causal, axis_name=axis_name, layout=layout
    )
    (_, _, _, (num, den, mx)), _ = lax.scan(
        body, (k, v, seg, (num, den, mx)), jnp.arange(C)
    )
    out = num / jnp.maximum(den, 1e-30).transpose(0, 3, 1, 2)[..., None]
    return out.astype(q.dtype).reshape(B, Sq, Hq, hd)


def ring_attention(
    q: jnp.ndarray,  # [B, S, Hq, hd], sequence sharded on `context`
    k: jnp.ndarray,  # [B, S, Hkv, hd]
    v: jnp.ndarray,
    *,
    causal: bool = True,
    segment_ids: Optional[jnp.ndarray] = None,  # [B, S]
    axis_name: str = AXIS_CONTEXT,
    layout: str = "plain",  # or "zigzag" (caller pre-permutes tokens)
) -> jnp.ndarray:
    """Drop-in for ``ops.attention.dense_attention`` under a mesh whose
    ``context`` axis is >1. Degrades to dense attention when no mesh is
    active or the context axis is trivial (so the same model code runs
    single-chip and context-parallel unchanged)."""
    am = jax.sharding.get_abstract_mesh()
    if (
        am.empty
        or axis_name not in am.axis_names
        or am.shape[axis_name] == 1
    ):
        return dense_attention(q, k, v, causal=causal, segment_ids=segment_ids)

    if layout not in ("plain", "zigzag"):
        raise ValueError(f"unknown ring layout {layout!r}")
    C = am.shape[axis_name]
    S = q.shape[1]
    if S % C or (layout == "zigzag" and (S // C) % 2):
        raise ValueError(f"seq len {S} not tileable over context={C} ({layout})")

    names = set(am.axis_names)
    batch_ax = tuple(a for a in (AXIS_DATA, AXIS_FSDP) if a in names) or None
    # Heads ride the tensor axis when it divides the KV-head count
    # (keeps tensor parallelism inside the shard_map); otherwise heads
    # replicate across tensor and XLA all-gathers them at the boundary.
    t = am.shape.get(AXIS_TENSOR, 1) if AXIS_TENSOR in names else 1
    head_ax = AXIS_TENSOR if (t > 1 and k.shape[2] % t == 0) else None

    qkv_spec = P(batch_ax, axis_name, head_ax, None)
    seg_spec = P(batch_ax, axis_name)
    fn = partial(
        _ring_attention_local, causal=causal, axis_name=axis_name, layout=layout
    )

    if segment_ids is None:
        sharded = jax.shard_map(
            lambda q_, k_, v_: fn(q_, k_, v_, None),
            mesh=am,
            in_specs=(qkv_spec, qkv_spec, qkv_spec),
            out_specs=qkv_spec,
            check_vma=False,
        )
        return sharded(q, k, v)
    sharded = jax.shard_map(
        fn,
        mesh=am,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, seg_spec),
        out_specs=qkv_spec,
        check_vma=False,
    )
    return sharded(q, k, v, segment_ids)
