"""Device-mesh construction for TPU slices.

The platform half of this repo schedules a notebook pod onto a TPU slice
(see ``controllers/notebook.py``); *this* module is what user code inside
that notebook uses to turn the slice into a ``jax.sharding.Mesh``.

Axis convention (the "How to Scale Your Model" recipe):

- ``data``    — pure data parallelism (gradients all-reduced). On
  multi-slice/multi-host deployments this is the axis that rides DCN.
- ``fsdp``    — data parallelism with parameters/optimizer sharded
  (ZeRO-3 style); XLA inserts all-gather on use, reduce-scatter on grads.
- ``tensor``  — megatron-style tensor parallelism inside a layer; the
  highest-bandwidth (ICI-neighbour) axis.
- ``context`` — sequence/context parallelism (ring attention over
  ``ppermute``, see ``parallel/ring_attention.py``).

The reference platform has no parallelism layer at all (SURVEY.md §2.4:
distribution there is one-StatefulSet-pod-per-notebook); for the TPU
rebuild the mesh is a first-class runtime component.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_DATA = "data"
AXIS_FSDP = "fsdp"
AXIS_TENSOR = "tensor"
AXIS_CONTEXT = "context"
AXIS_EXPERT = "expert"
AXIS_PIPE = "pipe"

# Order matters: earlier axes change slowest across the physical device
# grid, so put the bandwidth-hungry axes (tensor, context) last — they
# land on ICI-adjacent chips, and `data` (the gradient all-reduce that
# can tolerate DCN latency) lands across hosts/slices. `pipe` comes
# right after data: stage-to-stage transfers are point-to-point and
# latency-tolerant (the GPipe bubble hides them), so pipeline stages
# are the natural thing to spread across slices. `expert` sits in the
# middle: its all-to-all wants ICI but tolerates more hops than
# tensor-parallel all-reduces.
AXIS_ORDER = (
    AXIS_DATA,
    AXIS_PIPE,
    AXIS_FSDP,
    AXIS_EXPERT,
    AXIS_CONTEXT,
    AXIS_TENSOR,
)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Logical mesh shape. Product must equal the device count."""

    data: int = 1
    pipe: int = 1
    fsdp: int = 1
    expert: int = 1
    context: int = 1
    tensor: int = 1

    @property
    def shape(self) -> tuple[int, ...]:
        return (
            self.data,
            self.pipe,
            self.fsdp,
            self.expert,
            self.context,
            self.tensor,
        )

    @property
    def num_devices(self) -> int:
        return math.prod(self.shape)

    def validate(self, n_devices: int) -> None:
        if self.num_devices != n_devices:
            raise ValueError(
                f"mesh shape {self.shape} = {self.num_devices} devices, "
                f"but {n_devices} devices are available"
            )


def local_mesh_config(devices: Optional[Sequence[jax.Device]] = None) -> MeshConfig:
    """Default mesh for whatever is attached: everything on fsdp.

    FSDP is the right single-axis default for fine-tuning: parameters and
    optimizer state shard across the slice, and XLA overlaps the
    all-gathers with compute.
    """
    n = len(devices if devices is not None else jax.devices())
    return MeshConfig(fsdp=n)


def build_mesh(
    config: Optional[MeshConfig] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    if config is None:
        config = local_mesh_config(devices)
    config.validate(len(devices))
    if len(devices) == 1:
        device_grid = np.array(devices).reshape(config.shape)
    else:
        try:
            device_grid = mesh_utils.create_device_mesh(
                config.shape, devices=devices
            )
        except (ValueError, AssertionError):
            # CPU / virtual device fallback: topology-aware assignment is
            # a TPU-only concern; any assignment is functionally correct.
            device_grid = np.array(devices).reshape(config.shape)
    return Mesh(device_grid, AXIS_ORDER)


def build_hybrid_mesh(
    ici: MeshConfig,
    dcn: MeshConfig,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Multi-slice mesh: ``ici`` factors live inside a slice (fast
    ICI), ``dcn`` factors span slices (data-center network). The per-
    axis extents multiply — e.g. ici=(fsdp=8) × dcn=(data=4) is four
    v5e-8 slices doing FSDP inside each slice and gradient all-reduce
    across slices, the standard multislice recipe. On real TPU
    multislice the topology-aware assignment keeps DCN axes on slice
    boundaries; virtual/CPU devices fall back to a plain reshape
    (functionally identical)."""
    devices = list(devices if devices is not None else jax.devices())
    shape = tuple(i * d for i, d in zip(ici.shape, dcn.shape))
    if math.prod(shape) != len(devices):
        raise ValueError(
            f"ici {ici.shape} × dcn {dcn.shape} = {math.prod(shape)} devices, "
            f"but {len(devices)} are available"
        )
    try:
        device_grid = mesh_utils.create_hybrid_device_mesh(
            ici.shape, dcn.shape, devices=devices
        )
    except (ValueError, AssertionError, KeyError):
        device_grid = np.array(devices).reshape(shape)
    return Mesh(device_grid, AXIS_ORDER)


def batch_spec() -> P:
    """PartitionSpec for a [batch, seq] token batch.

    The expert axis doubles as a data axis for non-MoE computation
    (the standard MoE-training layout): dense layers see it as more
    batch shards, and the MoE dispatch einsum turns it into the
    token⇄expert all-to-all."""
    return P((AXIS_DATA, AXIS_FSDP, AXIS_EXPERT), AXIS_CONTEXT)


def constrain(x, spec: P):
    """``with_sharding_constraint`` that degrades to a no-op when no mesh
    is active (single-device eager use), and drops spec axes the active
    mesh doesn't define (partial meshes in tests) or that are Manual
    (inside ``shard_map`` — e.g. model code running under the pipeline
    combinator — constraints may only name Auto axes)."""
    am = jax.sharding.get_abstract_mesh()
    if am.empty:
        return x
    names = {
        name
        for name, t in zip(am.axis_names, am.axis_types)
        if t == jax.sharding.AxisType.Auto
    }
    if not names:
        return x

    def keep(entry):
        if entry is None:
            return None
        if isinstance(entry, (tuple, list)):
            kept = tuple(a for a in entry if a in names)
            return kept if kept else None
        return entry if entry in names else None

    filtered = P(*(keep(e) for e in spec))
    return jax.lax.with_sharding_constraint(x, filtered)


def named_sharding(mesh: Mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)


def shard_tree(tree, mesh: Mesh, spec_tree):
    """Device-put a pytree according to a matching tree of PartitionSpecs."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree, spec_tree
    )
