from odh_kubeflow_tpu.parallel.mesh import (  # noqa: F401
    AXIS_CONTEXT,
    AXIS_DATA,
    AXIS_FSDP,
    AXIS_TENSOR,
    MeshConfig,
    batch_spec,
    build_mesh,
    local_mesh_config,
)
from odh_kubeflow_tpu.parallel.ring_attention import (  # noqa: F401
    ring_attention,
    zigzag_permute,
    zigzag_unpermute,
)
