"""Fleet-wide overload defense: deadlines, retry budgets, breakers,
and priority levels.

Four cooperating mechanisms keep the control plane metastable-failure
free when offered load exceeds capacity (docs/GUIDE.md "Overload
defense"):

- **End-to-end deadlines** — a request's remaining time budget rides a
  contextvar exactly like the fencing token does: web apps and
  controllers stamp it (``REQUEST_DEADLINE_DEFAULT``), ``client.py``
  propagates the *remaining* seconds in ``X-Request-Deadline`` (delta
  form, so clock skew between hosts cannot corrupt it), and the serving
  side re-derives an absolute deadline against its own monotonic clock.
  Every stage sheds expired work with 504 *before* doing it — admission,
  worker-pool dequeue, the group-commit ack wait, scatter-gather legs —
  because work a client has already abandoned is pure amplification.

- **Retry budgets** — a per-process token bucket (the gRPC/Envoy retry-
  throttling shape): successes refill ``RETRY_BUDGET_RATIO`` tokens,
  each retry spends one. When the bucket runs dry the caller surfaces
  the error instead of retrying, so fleet-wide attempts-per-logical-
  request is bounded by construction (~``1 + ratio`` in steady state)
  no matter how many layers stack their retry loops.

- **Circuit breakers** — a per-endpoint rolling error/latency window
  with the classic closed → open → half-open machine. An endpoint
  past ``BREAKER_FAILURE_THRESHOLD`` sheds calls locally for
  ``BREAKER_COOLDOWN_SECONDS`` and is then *probed* by exactly one
  trial request rather than hammered by every caller at once.

- **Priority levels** — APF-style classes (system > controller > user
  web > background) with cumulative concurrency ceilings
  (``APF_LEVEL_*``, percent of ``APF_INFLIGHT_LIMIT``): lower-priority
  traffic can only ever fill part of the inflight pool, so lease
  renewals, fencing checks, and replication control frames always have
  admission headroom — a user-load flood cannot starve the traffic
  that keeps the fleet consistent.

This module is dependency-free within the package (stdlib +
``utils.prometheus`` only): ``store``, ``client``, ``httpapi``,
``eventloop``, and ``backoff`` all import it without cycles. The
:class:`~odh_kubeflow_tpu.machinery.store.DeadlineExceeded` error
itself lives in ``store.py`` with the rest of the API error hierarchy.
"""

from __future__ import annotations

import contextvars
import os
import threading
import time
from collections import deque
from typing import Any, Optional

from odh_kubeflow_tpu.utils import prometheus

# ---------------------------------------------------------------------------
# knobs


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


# default end-to-end deadline (seconds) web apps and controllers stamp
# on work that arrives without one; 0 disables stamping
DEADLINE_DEFAULT_ENV = "REQUEST_DEADLINE_DEFAULT"
# tokens refilled into the retry budget per SUCCESSFUL request; each
# retry spends 1, so steady-state amplification is bounded by 1 + ratio
BUDGET_RATIO_ENV = "RETRY_BUDGET_RATIO"

# wire header: REMAINING delta-seconds (gRPC ``grpc-timeout`` posture —
# absolute wall-clock deadlines would be corrupted by clock skew)
DEADLINE_HEADER = "X-Request-Deadline"
PRIORITY_HEADER = "X-Priority-Level"


def default_deadline_seconds() -> float:
    """``REQUEST_DEADLINE_DEFAULT`` (seconds; 0 disables stamping)."""
    return _env_float(DEADLINE_DEFAULT_ENV, 30.0)


# ---------------------------------------------------------------------------
# end-to-end deadlines (contextvar, the fencing-token propagation shape)

# the calling context's absolute deadline on THIS host's monotonic
# clock — None means the request has no time bound
_DEADLINE: contextvars.ContextVar[Optional[float]] = contextvars.ContextVar(
    "odh_deadline", default=None
)


def current_deadline() -> Optional[float]:
    """The calling context's absolute ``time.monotonic()`` deadline,
    or None when the work is unbounded."""
    return _DEADLINE.get()


def set_deadline(deadline: Optional[float]):
    """Install an absolute monotonic deadline on the calling context;
    returns the reset token for ``ContextVar.reset``."""
    return _DEADLINE.set(deadline)


def reset_deadline(token) -> None:
    _DEADLINE.reset(token)


def remaining() -> Optional[float]:
    """Seconds left before the ambient deadline (may be <= 0), or None
    when the context carries no deadline."""
    d = _DEADLINE.get()
    return None if d is None else d - time.monotonic()


def expired() -> bool:
    """True when the ambient deadline has passed."""
    d = _DEADLINE.get()
    return d is not None and d <= time.monotonic()


def header_value() -> Optional[str]:
    """The ``X-Request-Deadline`` value for an outbound hop: remaining
    delta-seconds (clamped at 0 — the server sheds it immediately),
    or None when the context has no deadline to propagate."""
    rem = remaining()
    return None if rem is None else f"{max(rem, 0.0):.3f}"


def environ_deadline(environ: dict) -> Optional[float]:
    """Absolute monotonic deadline for an inbound WSGI request, parsed
    from its ``X-Request-Deadline`` delta-seconds header. Anchored to
    the connection's arrival stamp when the front end recorded one
    (``odh.request.arrival``, the event-loop server) so queue time
    spent before dispatch counts against the budget; arrival-less
    requests anchor at now. Raises ``ValueError`` on a malformed value
    (callers answer 400, the fencing-header posture)."""
    raw = environ.get("HTTP_" + DEADLINE_HEADER.upper().replace("-", "_"), "")
    if not raw:
        return None
    delta = float(raw)  # ValueError propagates to the caller's 400
    base = environ.get("odh.request.arrival")
    if not isinstance(base, (int, float)):
        base = time.monotonic()
    return base + delta


class deadline_scope:
    """Context manager installing a deadline ``seconds`` from entry —
    the stamp web apps put around request handling and controllers put
    around one reconcile. Never *loosens* an inherited deadline: when
    the ambient one is already tighter, it stays. ``seconds`` <= 0 (the
    knob's off position) installs nothing."""

    def __init__(self, seconds: Optional[float] = None):
        self.seconds = (
            default_deadline_seconds() if seconds is None else seconds
        )
        self._token = None

    def __enter__(self):
        if self.seconds and self.seconds > 0:
            mine = time.monotonic() + self.seconds
            ambient = _DEADLINE.get()
            if ambient is None or mine < ambient:
                self._token = _DEADLINE.set(mine)
        return self

    def __exit__(self, *exc) -> None:
        if self._token is not None:
            _DEADLINE.reset(self._token)
            self._token = None


# ---------------------------------------------------------------------------
# retry budget (gRPC retry-throttling / Envoy retry-budget shape)


class RetryBudget:
    """Per-process retry token bucket: each retry spends one token,
    each success refills ``ratio`` (``RETRY_BUDGET_RATIO``). A dry
    bucket means the fleet is retrying more than ``ratio`` per
    successful request — amplification territory — so ``try_spend``
    answers False and the caller surfaces its error instead of piling
    on. The bucket starts full (``cap``) so a cold process can absorb
    a genuine transient blip before the ratio governs."""

    def __init__(
        self,
        ratio: Optional[float] = None,
        cap: float = 100.0,
        registry: Optional[prometheus.Registry] = None,
    ):
        self.ratio = (
            _env_float(BUDGET_RATIO_ENV, 0.1) if ratio is None else ratio
        )
        self.cap = cap
        self._tokens = cap
        self._lock = threading.Lock()
        reg = registry or prometheus.default_registry
        self._m_spent = reg.counter(
            "retry_budget_spent_total",
            "Retry tokens spent (each token is one retry attempt)",
        )
        self._m_exhausted = reg.counter(
            "retry_budget_exhausted_total",
            "Retries suppressed because the per-process retry budget "
            "was exhausted",
        )

    def tokens(self) -> float:
        with self._lock:
            return self._tokens

    def on_success(self) -> None:
        with self._lock:
            self._tokens = min(self.cap, self._tokens + self.ratio)

    def try_spend(self) -> bool:
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                spent = True
            else:
                spent = False
        if spent:
            self._m_spent.inc()
        else:
            self._m_exhausted.inc()
        return spent


_shared_budget: Optional[RetryBudget] = None
_shared_lock = threading.Lock()


def shared_budget() -> RetryBudget:
    """The process-wide budget every API-path retrier threads
    (``backoff.retry(..., budget=...)``; the ``unbudgeted-retry`` lint
    holds machinery/web retry sites to it) — ONE bucket per process so
    stacked retry layers share one amplification bound."""
    global _shared_budget
    with _shared_lock:
        if _shared_budget is None:
            _shared_budget = RetryBudget()
        return _shared_budget


def _reset_shared_budget_for_tests() -> RetryBudget:
    global _shared_budget
    with _shared_lock:
        _shared_budget = RetryBudget(registry=prometheus.Registry())
        return _shared_budget


# ---------------------------------------------------------------------------
# circuit breaker


class CircuitBreaker:
    """Per-endpoint breaker over a rolling error/latency window.

    closed → (failure ratio over the window >= ``threshold`` with at
    least ``min_requests`` samples) → open → (after ``cooldown``) →
    half-open: exactly ONE probe call is admitted; its success closes
    the breaker (window cleared), its failure re-opens the cooldown.
    A call slower than ``slow_seconds`` counts as a failure even when
    it succeeded — a drowning endpoint that still answers eventually
    ties up inflight slots just like a dead one.

    Knobs: ``BREAKER_WINDOW_SECONDS`` / ``BREAKER_FAILURE_THRESHOLD`` /
    ``BREAKER_MIN_REQUESTS`` / ``BREAKER_COOLDOWN_SECONDS`` /
    ``BREAKER_SLOW_SECONDS``. ``clock`` is injectable for tests."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half-open"

    def __init__(
        self,
        window: Optional[float] = None,
        threshold: Optional[float] = None,
        min_requests: Optional[int] = None,
        cooldown: Optional[float] = None,
        slow_seconds: Optional[float] = None,
        clock: Any = time.monotonic,
    ):
        self.window = (
            _env_float("BREAKER_WINDOW_SECONDS", 10.0)
            if window is None
            else window
        )
        self.threshold = (
            _env_float("BREAKER_FAILURE_THRESHOLD", 0.5)
            if threshold is None
            else threshold
        )
        self.min_requests = (
            int(_env_float("BREAKER_MIN_REQUESTS", 10))
            if min_requests is None
            else min_requests
        )
        self.cooldown = (
            _env_float("BREAKER_COOLDOWN_SECONDS", 1.0)
            if cooldown is None
            else cooldown
        )
        self.slow_seconds = (
            _env_float("BREAKER_SLOW_SECONDS", 5.0)
            if slow_seconds is None
            else slow_seconds
        )
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._open_until = 0.0
        self._probing = False
        # rolling (timestamp, failed) samples, pruned to the window
        self._samples: deque[tuple[float, bool]] = deque()

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def blocking(self) -> bool:
        """True while the breaker would reject a call RIGHT NOW —
        pure (no half-open transition), for health ranking."""
        with self._lock:
            return (
                self._state == self.OPEN
                and self._clock() < self._open_until
            ) or (self._state == self.HALF_OPEN and self._probing)

    def retry_after(self) -> float:
        """Seconds until the next probe slot — the Retry-After hint a
        shed caller gets."""
        with self._lock:
            if self._state == self.OPEN:
                return max(self._open_until - self._clock(), 0.0)
            return 0.0

    def allow(self) -> bool:
        """May a call proceed? Open sheds until the cooldown elapses,
        then admits a single half-open probe."""
        with self._lock:
            if self._state == self.CLOSED:
                return True
            now = self._clock()
            if self._state == self.OPEN:
                if now < self._open_until:
                    return False
                self._state = self.HALF_OPEN
                self._probing = True
                return True
            # half-open: one outstanding probe at a time
            if self._probing:
                return False
            self._probing = True
            return True

    def record(self, ok: bool, latency: float = 0.0) -> None:
        """Report a call outcome. ``ok=False`` or a slow success feeds
        the failure side of the window."""
        failed = (not ok) or latency >= self.slow_seconds
        with self._lock:
            now = self._clock()
            if self._state == self.HALF_OPEN:
                self._probing = False
                if failed:
                    self._state = self.OPEN
                    self._open_until = now + self.cooldown
                else:
                    self._state = self.CLOSED
                    self._samples.clear()
                return
            self._samples.append((now, failed))
            horizon = now - self.window
            while self._samples and self._samples[0][0] < horizon:
                self._samples.popleft()
            if self._state != self.CLOSED or len(self._samples) < max(
                self.min_requests, 1
            ):
                return
            failures = sum(1 for _, f in self._samples if f)
            if failures / len(self._samples) >= self.threshold:
                self._state = self.OPEN
                self._open_until = now + self.cooldown
                self._samples.clear()


# ---------------------------------------------------------------------------
# priority levels (APF-style)

LEVEL_SYSTEM = 0  # lease renew / fencing / replication / usage flush
LEVEL_CONTROLLER = 1  # reconcile traffic
LEVEL_USER = 2  # interactive web requests
LEVEL_BACKGROUND = 3  # warm-pool backfill and other deferrable work

LEVEL_NAMES = ("system", "controller", "user", "background")
_LEVEL_BY_NAME = {name: i for i, name in enumerate(LEVEL_NAMES)}

def level_ceilings(limit: int) -> tuple[int, ...]:
    """Absolute per-level inflight ceilings for a pool of ``limit``
    seats: cumulative PERCENT of the pool each level's traffic may
    fill (``APF_LEVEL_*``), so everything above a level keeps
    guaranteed admission headroom — system is 100 by definition
    (nothing outranks it). Each ceiling is at least 1 so no level can
    be configured fully off."""
    pcts = (
        _env_float("APF_LEVEL_SYSTEM", 100.0),
        _env_float("APF_LEVEL_CONTROLLER", 90.0),
        _env_float("APF_LEVEL_USER", 75.0),
        _env_float("APF_LEVEL_BACKGROUND", 50.0),
    )
    return tuple(max(1, int(limit * p / 100.0)) for p in pcts)


def classify(
    kind: Optional[str] = None,
    path: str = "",
    header: Optional[str] = None,
    controller: bool = False,
) -> int:
    """Priority level for one inbound request. An explicit
    ``X-Priority-Level`` header wins (internal callers self-declare:
    warm-pool backfill marks itself background); otherwise traffic the
    fleet's own consistency machinery generates — Lease renewals
    (fencing heartbeats) and the replication surface — is system,
    reconcile-originated calls (the tracestate marker) are controller,
    and everything else is interactive user traffic."""
    if header:
        lvl = _LEVEL_BY_NAME.get(header.strip().lower())
        if lvl is not None:
            return lvl
    if kind == "Lease" or path.startswith("/replication/"):
        return LEVEL_SYSTEM
    if controller:
        return LEVEL_CONTROLLER
    return LEVEL_USER
