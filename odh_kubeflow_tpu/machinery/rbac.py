"""RBAC evaluation over stored (Cluster)Role / (Cluster)RoleBinding objects.

The reference platform delegates authorization to the Kubernetes
SubjectAccessReview API (crud_backend authz, SURVEY.md §2.2; kfam's
owner/admin gate uses informer-cached RoleBindings). Here the evaluator
is embedded: ``can(user, verb, resource, namespace)`` answers the same
question against the APIServer's RBAC objects, and the web layer's
``@needs_authorization`` decorator calls it exactly where the reference
posts a SubjectAccessReview.
"""

from __future__ import annotations

from typing import Optional

from odh_kubeflow_tpu.machinery.store import APIServer, NotFound


def _rule_matches(
    rule: dict, verb: str, api_group: str, resource: str, name: Optional[str]
) -> bool:
    def _match(allowed, value) -> bool:
        allowed = allowed or []
        return "*" in allowed or value in allowed

    if not _match(rule.get("verbs"), verb):
        return False
    if not _match(rule.get("apiGroups"), api_group):
        return False
    # k8s RBAC requires subresources ("notebooks/status") to be listed
    # explicitly — a grant on the base resource does NOT cover them
    resources = rule.get("resources") or []
    if "*" not in resources and resource not in resources:
        return False
    if name and rule.get("resourceNames"):
        return name in rule["resourceNames"]
    return True


def _subject_matches(subject: dict, user: str, groups: list[str]) -> bool:
    kind = subject.get("kind", "")
    if kind == "User":
        return subject.get("name") == user
    if kind == "Group":
        return subject.get("name") in groups
    if kind == "ServiceAccount":
        sa_user = (
            f"system:serviceaccount:{subject.get('namespace', '')}:"
            f"{subject.get('name', '')}"
        )
        return sa_user == user
    return False


class RBACEvaluator:
    def __init__(self, api: APIServer):
        self.api = api

    def _role_rules(self, ref: dict, binding_ns: Optional[str]) -> list[dict]:
        kind = ref.get("kind", "Role")
        name = ref.get("name", "")
        try:
            if kind == "ClusterRole":
                role = self.api.get("ClusterRole", name)
            else:
                role = self.api.get("Role", name, binding_ns)
        except NotFound:
            # a binding to a deleted role grants nothing (k8s behaviour)
            return []
        return role.get("rules") or []

    def can(
        self,
        user: str,
        verb: str,
        resource: str,
        namespace: Optional[str] = None,
        api_group: str = "",
        name: Optional[str] = None,
        groups: Optional[list[str]] = None,
    ) -> bool:
        """SubjectAccessReview semantics: cluster bindings grant
        everywhere; namespaced bindings grant within their namespace."""
        groups = groups or []
        for binding in self.api.list("ClusterRoleBinding"):
            if any(
                _subject_matches(s, user, groups)
                for s in binding.get("subjects") or []
            ):
                for rule in self._role_rules(binding.get("roleRef", {}), None):
                    if _rule_matches(rule, verb, api_group, resource, name):
                        return True
        if namespace:
            for binding in self.api.list("RoleBinding", namespace=namespace):
                if any(
                    _subject_matches(s, user, groups)
                    for s in binding.get("subjects") or []
                ):
                    for rule in self._role_rules(
                        binding.get("roleRef", {}), namespace
                    ):
                        if _rule_matches(rule, verb, api_group, resource, name):
                            return True
        return False
