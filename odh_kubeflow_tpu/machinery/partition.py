"""Partitioned write path: the store sharded by namespace into N
independent leaders behind one coherent client-facing contract.

Reads scale out with replicas (machinery/replica.py); every mutation
still funnels through ONE leader's group-commit pipeline — the hard
ceiling between here and fleet scale. This module shards the WRITE
path kube-style, by namespace (all platform CRs are namespaced):

- **assignment** (:func:`partition_of`, :class:`PartitionMap`):
  rendezvous (HRW) hashing of namespaces over partition ids — the
  PR-8 ``ShardMembership`` discipline extended to store partitions.
  Resizing from N to N+1 partitions moves only the ~1/(N+1) slice the
  new partition wins; every other namespace stays put. Cluster-scoped
  kinds (``PriorityClass``, ``CompileCacheEntry``, Leases' cluster
  peers…) pin to partition 0, the meta partition.
- **routing** (:class:`PartitionRouter`): a stateless ``APIServer``
  duck that maps every namespaced verb to its owning partition. Each
  partition is a full WAL + group-commit + read-replica stack with
  its own fencing epoch, rv space, and compaction window. A mutation
  for a partition this router does not lead answers with the existing
  ``NotLeader`` 307 contract (``leader_url`` = that partition's
  advertised URL). Cluster-spanning lists and watches are
  scatter-gather merges over the PR-10 pagination contract: composite
  continue tokens pin a per-partition rv vector, one partition's 410
  restarts only that partition's walk, and merged watch streams
  preserve per-partition rv order while re-framing CONTROL heartbeats
  with their partition of origin.
- **live moves** (:class:`PartitionMover`): a namespace ships between
  partitions with the PR-13 snapshot/catch-up protocol as the data
  plane — consistent cut, tail replay from the source's replication
  feed, a bounded freeze window behind a fencing bump, takeover under
  a fresh destination epoch. Zero lost acks: every acked write is in
  the cut, the tail, or lands after retargeting; writes inside the
  freeze window are refused with a retryable 429 and were never
  acked.

rv spaces are per-partition. A composite resume/continue token
therefore carries a ``{partition: rv}`` vector, never one scalar —
the same reason the PR-13 promotion drill needed epochs, applied
fleet-wide.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
import weakref
from typing import Any, Callable, Optional

from odh_kubeflow_tpu.analysis import schedule as _schedule
from odh_kubeflow_tpu.machinery import objects as obj_util, overload
from odh_kubeflow_tpu.machinery.leader import (
    _hrw_weight,
    fenced,
    lease_expired,
)
from odh_kubeflow_tpu.machinery.store import (
    APIError,
    APIServer,
    BadRequest,
    DeadlineExceeded,
    Expired,
    FencedOut,
    Invalid,
    NotFound,
    NotLeader,
    TooManyRequests,
    Watch,
    current_fence,
    decode_continue,
    encode_continue,
    reset_fence,
    set_fence,
)

Obj = dict[str, Any]

log = logging.getLogger("machinery.partition")


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def partitions_from_env() -> int:
    """``STORE_PARTITIONS``: how many write partitions the platform
    runs (1 = the classic single-leader store, no router)."""
    return max(1, _env_int("STORE_PARTITIONS", 1))


# ---------------------------------------------------------------------------
# assignment


def partition_of(namespace: str, n_partitions: int) -> int:
    """The partition that owns ``namespace``: the highest-random-weight
    winner among partition ids, scored with the same keyed blake2b the
    PR-8 shard membership ranks controller replicas with. Stable
    across processes, and minimal movement on resize — growing from N
    to N+1 partitions reassigns only the namespaces the new id wins
    (~1/(N+1) of them)."""
    if n_partitions <= 1:
        return 0
    return max(
        range(n_partitions),
        key=lambda p: _hrw_weight(f"partition-{p}", namespace),
    )


class PartitionMap:
    """Live namespace→partition assignment: HRW by default, plus the
    explicit overrides a :class:`PartitionMover` records when it ships
    a namespace away from its hash-assigned home. Reads are lock-free
    (overrides is replaced, never mutated in place)."""

    def __init__(
        self, n_partitions: int, overrides: Optional[dict[str, int]] = None
    ):
        self.n = max(1, int(n_partitions))
        self._overrides: dict[str, int] = dict(overrides or {})

    def owner_of(self, namespace: str) -> int:
        p = self._overrides.get(namespace)
        if p is not None:
            return p
        return partition_of(namespace, self.n)

    def override(self, namespace: str, partition: int) -> None:
        if not 0 <= partition < self.n:
            raise Invalid(
                f"partition {partition} out of range (0..{self.n - 1})"
            )
        fresh = dict(self._overrides)
        if partition_of(namespace, self.n) == partition:
            fresh.pop(namespace, None)  # moved back home: no override
        else:
            fresh[namespace] = partition
        self._overrides = fresh

    def overrides(self) -> dict[str, int]:
        return dict(self._overrides)


# ---------------------------------------------------------------------------
# composite tokens
#
# Same wire shape as the PR-10 continue tokens (urlsafe-b64 JSON via
# encode_continue/decode_continue) so they travel every surface plain
# tokens already do — HTTP query params, the web tier, the client's
# paged walks — but the payload pins a PER-PARTITION rv vector and a
# per-partition cursor, because one scalar rv cannot describe N
# independent histories.

_FLEET = "fleet"


def is_composite_token(token: str) -> bool:
    try:
        return bool(decode_continue(token).get(_FLEET))
    except BadRequest:  # foreign/opaque token shapes are not fleet tokens
        return False


def encode_fleet_rvs(kind: str, rvs: dict[int, int]) -> str:
    """A merged watch's resume token: the per-partition rv vector the
    stream has delivered through."""
    return encode_continue(
        {_FLEET: 1, "kind": kind, "rv": {str(p): int(v) for p, v in rvs.items()}}
    )


def decode_fleet_rvs(token: str, kind: str) -> dict[int, int]:
    payload = decode_continue(token)
    if not payload.get(_FLEET):
        raise BadRequest("not a fleet resume token")
    if payload.get("kind") not in (None, kind):
        raise BadRequest(
            f"fleet resume token is for kind {payload.get('kind')!r}, "
            f"not {kind!r}"
        )
    return {int(p): int(v) for p, v in (payload.get("rv") or {}).items()}


# ---------------------------------------------------------------------------
# merged watch


class MergedWatch(Watch):
    """A cluster-spanning watch assembled from one leg per partition.

    Legs pump into the merged queue from their own notify hooks (the
    enqueuing thread — mutator or dispatcher — drives the pump, same
    zero-extra-threads posture as the event-loop server). A small pump
    lock serializes legs, so each partition's events land in ITS rv
    order; no global order across partitions exists or is promised.

    CONTROL frames are re-framed with their partition of origin, and
    two partition-local conditions become CONTROL frames instead of
    stream death:

    - a leg that 410s (resume below that partition's compaction floor,
      or a mid-stream eviction) surfaces as ``{"partition": p,
      "expired": True}`` — the consumer relists THAT partition;
      the other legs keep streaming (one partition's 410 must not
      poison the merged stream);
    - a namespace move surfaces as ``{"partition": dst, "moved": ns}``
      at takeover — event-level continuity across a move is by relist,
      not by replaying the handover's internal writes.

    ``resume_rvs()``/``resume_token()`` expose the delivered-through
    per-partition rv vector for composite resumes."""

    def __init__(
        self,
        router: "PartitionRouter",
        kind: Optional[str],
        namespace: Optional[str],
    ):
        super().__init__(router, kind, namespace)
        self._legs: dict[int, Watch] = {}
        self._pump_lock = threading.Lock()
        self._last_rvs: dict[int, int] = {}
        self._leg_closed: set[int] = set()
        self.expired_partitions: set[int] = set()

    def attach_leg(self, partition: int, leg: Watch) -> None:
        self._legs[partition] = leg
        self._last_rvs.setdefault(partition, 0)
        leg.set_notify(lambda p=partition: self._pump(p))

    def mark_expired(self, partition: int, reason: str) -> None:
        """A leg that could not even open (resume below that
        partition's floor): surfaced as a CONTROL frame, stream lives."""
        with self._pump_lock:
            self._note_expired(partition, reason)

    def control(self, frame: Obj) -> None:
        """Router-injected CONTROL (move takeover, epoch bumps)."""
        with self._pump_lock:
            self._enqueue(("CONTROL", dict(frame)))

    def _note_expired(self, partition: int, reason: str) -> None:
        if partition in self.expired_partitions:
            return
        self.expired_partitions.add(partition)
        self._leg_closed.add(partition)
        self._enqueue(
            (
                "CONTROL",
                {
                    "partition": partition,
                    "expired": True,
                    "reason": reason,
                    "rv": self._last_rvs.get(partition, 0),
                },
            )
        )

    def _pump(self, partition: int) -> None:
        with self._pump_lock:
            leg = self._legs.get(partition)
            if leg is None or self._stopped:
                return
            owner_of = self._server._map.owner_of
            while True:
                item = leg.try_get()
                if item is None:
                    break
                etype, obj = item
                if etype == "CONTROL":
                    obj = dict(obj)
                    obj["partition"] = partition
                else:
                    meta = obj.get("metadata", {})
                    ns = meta.get("namespace")
                    try:
                        rv = int(meta.get("resourceVersion", 0) or 0)
                    except (TypeError, ValueError):
                        rv = 0
                    if rv > self._last_rvs.get(partition, 0):
                        self._last_rvs[partition] = rv
                    # ownership filter at delivery time: a partition
                    # only contributes events for namespaces it OWNS —
                    # mid-move imports and post-move source garbage
                    # never leak into the merged stream
                    if ns and owner_of(ns) != partition:
                        continue
                self._enqueue((etype, obj))
            if (leg.ended or leg.evicted) and partition not in self._leg_closed:
                if isinstance(leg.error, Expired):
                    self._note_expired(partition, str(leg.error))
                else:
                    self._leg_closed.add(partition)
                    if self._leg_closed >= set(self._legs):
                        self.ended = True
                        self._q.put(None)
                        self._wake()

    def resume_rvs(self) -> dict[int, int]:
        with self._pump_lock:
            return dict(self._last_rvs)

    def resume_token(self) -> str:
        return encode_fleet_rvs(self.kind or "", self.resume_rvs())

    def stop(self) -> None:
        for leg in self._legs.values():
            try:
                leg.stop()
            except Exception:  # noqa: BLE001 — teardown is best-effort
                log.debug("merged watch: leg stop failed", exc_info=True)
        super().stop()


# ---------------------------------------------------------------------------
# router


class PartitionRouter:
    """Stateless namespace→partition request router, ``APIServer``
    duck (the same duck ``ReadSplitAPI`` plays, so the REST façade,
    clients, controllers, and the informer cache work unchanged).

    ``backends`` maps partition id → an APIServer duck (an in-process
    store, a :class:`~odh_kubeflow_tpu.machinery.replica.ReplicaStore`,
    or a remote client). ``owned`` names the partitions THIS process
    leads — a mutation routed to any other partition raises
    :class:`NotLeader` carrying that partition's ``urls`` entry, the
    existing 307 redirect contract. The default (owned = everything)
    is the single-process in-memory fleet the tests and platform run.
    """

    LIST_DEFAULT_LIMIT = APIServer.LIST_DEFAULT_LIMIT

    # per-partition page size for scatter-gather merges (0 = the
    # request's own limit). Smaller pages trade merge over-fetch for
    # per-call latency.
    MERGE_PAGE_LIMIT = _env_int("PARTITION_MERGE_PAGE_LIMIT", 0)
    # Retry-After (seconds) on writes refused inside a move's freeze
    # window — the client-visible cost of a live partition move.
    MOVE_RETRY_AFTER = _env_float("PARTITION_MOVE_RETRY_AFTER", 0.05)

    def __init__(
        self,
        backends: dict[int, Any] | list[Any],
        pmap: Optional[PartitionMap] = None,
        owned: Optional[set[int]] = None,
        urls: Optional[dict[int, str]] = None,
    ):
        if isinstance(backends, list):
            backends = dict(enumerate(backends))
        if 0 not in backends:
            raise Invalid("partition 0 (the meta partition) is required")
        self.backends = dict(backends)
        self._map = pmap or PartitionMap(len(self.backends))
        self.owned = set(self.backends) if owned is None else set(owned)
        self.urls = dict(urls or {})
        self._frozen: set[str] = set()
        self._freeze_lock = threading.Lock()
        # per-namespace in-flight mutation counts: registered BEFORE
        # the frozen check, so freeze + quiesce_writes is a real
        # barrier — after it returns, every ack the namespace will
        # ever get is already in its source store's applied horizon
        self._inflight: dict[str, int] = {}
        self._inflight_cv = threading.Condition()
        self._merged: "weakref.WeakSet[MergedWatch]" = weakref.WeakSet()
        self.merge_page_limit = self.MERGE_PAGE_LIMIT
        self.move_retry_after = self.MOVE_RETRY_AFTER
        # one circuit breaker per partition (machinery.overload): a
        # sick partition sheds fast instead of dragging every
        # scatter-gather merge and routed write down with it
        self._breakers: dict[int, overload.CircuitBreaker] = {
            p: overload.CircuitBreaker() for p in self.backends
        }

    # -- assignment surface --------------------------------------------------

    @property
    def partition_count(self) -> int:
        return self._map.n

    def owner_of(self, namespace: str) -> int:
        return self._map.owner_of(namespace)

    def backend(self, partition: int) -> Any:
        try:
            return self.backends[partition]
        except KeyError:
            raise NotFound(f"no partition {partition}") from None

    partition_backend = backend  # the REST façade's ?partition= hook

    def retarget(self, namespace: str, partition: int) -> None:
        """Point ``namespace`` at ``partition`` (the mover's takeover
        step) and tell every merged stream to relist it."""
        self._map.override(namespace, partition)
        for w in list(self._merged):
            w.control({"partition": partition, "moved": namespace})

    # -- freeze window -------------------------------------------------------

    def freeze(self, namespace: str) -> None:
        with self._freeze_lock:
            self._frozen = self._frozen | {namespace}

    def unfreeze(self, namespace: str) -> None:
        with self._freeze_lock:
            self._frozen = self._frozen - {namespace}

    def _check_frozen(self, namespace: Optional[str]) -> None:
        if namespace and namespace in self._frozen:
            raise TooManyRequests(
                f"namespace {namespace} is mid-move between partitions; "
                "retry after the handover window",
                retry_after=self.move_retry_after,
            )

    def quiesce_writes(self, namespace: str, timeout: float = 1.0) -> bool:
        """Wait until no mutation for ``namespace`` is in flight.
        Called AFTER :meth:`freeze`: a mutation that slipped past the
        frozen check before the freeze landed is still counted here,
        so once this returns True every ack the namespace will ever
        get is covered by the source's applied horizon."""
        deadline = time.monotonic() + timeout
        with self._inflight_cv:
            while self._inflight.get(namespace, 0):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._inflight_cv.wait(remaining)
        return True

    # -- routing helpers -----------------------------------------------------

    def type_info(self, kind: str):
        return self.backends[0].type_info(kind)

    def kind_for_plural(self, plural: str) -> str:
        return self.backends[0].kind_for_plural(plural)

    def _ns_of_obj(self, obj: Obj) -> Optional[str]:
        info = self.type_info(obj.get("kind", ""))
        if not info.namespaced:
            return None
        return (obj.get("metadata") or {}).get("namespace")

    def _route(self, namespace: Optional[str]) -> int:
        # cluster-scoped objects (namespace None) live on the meta
        # partition; namespaced ones go to their HRW/override owner
        return self._map.owner_of(namespace) if namespace else 0

    # -- overload defense ----------------------------------------------------

    @staticmethod
    def _shed_expired(stage: str) -> None:
        if overload.expired():
            raise DeadlineExceeded(
                f"request deadline expired before {stage}"
            )

    def _breaker_for(self, p: int) -> overload.CircuitBreaker:
        try:
            return self._breakers[p]
        except KeyError:
            return self._breakers.setdefault(p, overload.CircuitBreaker())

    def _call_backend(self, p: int, call: Callable[[Any], Any]):
        """One breaker-guarded backend call. An open breaker sheds
        with a retryable 429 before touching the partition; outcomes
        and latency feed the rolling window. Expected client errors
        (4xx) and the caller's own expired deadline (504) are not
        endpoint sickness."""
        breaker = self._breaker_for(p)
        if not breaker.allow():
            raise TooManyRequests(
                f"partition {p} circuit breaker open; shedding until the "
                "endpoint proves healthy",
                retry_after=max(breaker.retry_after(), 0.01),
            )
        healthy = True
        t0 = time.monotonic()
        try:
            return call(self.backends[p])
        except DeadlineExceeded:
            raise
        except APIError as e:
            healthy = e.code < 500
            raise
        except Exception:
            healthy = False
            raise
        finally:
            breaker.record(healthy, time.monotonic() - t0)

    # -- cross-partition fencing --------------------------------------------
    #
    # A fencing Lease lives in ONE partition (its namespace's owner).
    # A fenced write landing in the SAME partition keeps the store's
    # atomic under-the-lock check. A fenced write to ANOTHER partition
    # would spuriously FencedOut (that store has no copy of the
    # lease), so the router validates the fence against the lease's
    # owning partition FIRST, then forwards the write unfenced —
    # check-then-act, the documented weakening for cross-partition
    # writes (docs/GUIDE.md "Partitioned write path").

    def _validate_fence_at_owner(self, fence: tuple[str, str, int]) -> None:
        ns, name, token = fence
        owner = self.backends[self._route(ns)]
        try:
            lease = owner.get("Lease", name, ns)
        except NotFound:
            raise FencedOut(
                f"fencing lease {ns}/{name} no longer exists; epoch "
                f"{token} is deposed"
            ) from None
        spec = lease.get("spec") or {}
        try:
            current = int(spec.get("fencingToken", -1))
        except (TypeError, ValueError):
            current = -1
        if current != int(token):
            raise FencedOut(
                f"fencing token {token} for lease {ns}/{name} is stale "
                f"(current epoch {current}); the holder was deposed"
            )
        now_fn = getattr(owner, "fence_now_fn", time.time)
        if lease_expired(lease, now_fn(), default_duration=0) and spec.get(
            "leaseDurationSeconds"
        ):
            raise FencedOut(
                f"fencing lease {ns}/{name} expired; epoch {token} may "
                "not write until it re-acquires"
            )

    def _fence_for(self, partition: int):
        """Context manager preparing the calling context's fence for a
        write to ``partition``: same-partition fences pass through
        untouched (atomic store-side check), cross-partition fences
        are validated at the lease's owner here and CLEARED for the
        downstream call."""
        fence = current_fence()
        if fence is None or self._route(fence[0]) == partition:
            return contextlib.nullcontext()
        self._validate_fence_at_owner(fence)

        @contextlib.contextmanager
        def cleared():
            tok = set_fence(None)
            try:
                yield
            finally:
                reset_fence(tok)

        return cleared()

    # -- mutations (routed, 307 on the wrong leader) -------------------------

    def _mutate(self, namespace: Optional[str], call: Callable[[Any], Any]):
        # an already-expired deadline sheds before ANY bookkeeping —
        # the caller gave up, so the cheapest outcome is no work at all
        self._shed_expired("partition write dispatch")
        # register in flight BEFORE the frozen check: quiesce_writes
        # sees this mutation even if it races the freeze, closing the
        # acked-but-unshipped window in the move protocol
        if namespace:
            with self._inflight_cv:
                self._inflight[namespace] = (
                    self._inflight.get(namespace, 0) + 1
                )
        try:
            self._check_frozen(namespace)
            p = self._route(namespace)
            if p not in self.owned:
                raise NotLeader(
                    f"partition {p} (namespace {namespace or '<cluster>'}) "
                    "is led elsewhere",
                    leader_url=self.urls.get(p, ""),
                )
            with self._fence_for(p):
                return self._call_backend(p, call)
        finally:
            if namespace:
                with self._inflight_cv:
                    n = self._inflight.get(namespace, 1) - 1
                    if n:
                        self._inflight[namespace] = n
                    else:
                        self._inflight.pop(namespace, None)
                    self._inflight_cv.notify_all()

    def _ns_of(self, kind: str, namespace: Optional[str]) -> Optional[str]:
        return namespace if self.type_info(kind).namespaced else None

    def create(self, obj: Obj, dry_run: bool = False) -> Obj:
        return self._mutate(
            self._ns_of_obj(obj), lambda b: b.create(obj, dry_run=dry_run)
        )

    def update(self, obj: Obj) -> Obj:
        return self._mutate(self._ns_of_obj(obj), lambda b: b.update(obj))

    def update_status(self, obj: Obj) -> Obj:
        return self._mutate(
            self._ns_of_obj(obj), lambda b: b.update_status(obj)
        )

    def patch(
        self,
        kind: str,
        name: str,
        patch: Obj,
        namespace: Optional[str] = None,
    ) -> Obj:
        return self._mutate(
            self._ns_of(kind, namespace),
            lambda b: b.patch(kind, name, patch, namespace=namespace),
        )

    def delete(
        self, kind: str, name: str, namespace: Optional[str] = None
    ) -> None:
        return self._mutate(
            self._ns_of(kind, namespace),
            lambda b: b.delete(kind, name, namespace=namespace),
        )

    def create_or_get(self, obj: Obj) -> Obj:
        return self._mutate(
            self._ns_of_obj(obj), lambda b: b.create_or_get(obj)
        )

    def emit_event(
        self,
        involved: Obj,
        reason: str,
        message: str,
        event_type: str = "Normal",
        component: str = "",
    ) -> Obj:
        ns = (involved.get("metadata") or {}).get("namespace") or "default"
        return self._mutate(
            ns,
            lambda b: b.emit_event(
                involved,
                reason,
                message,
                event_type=event_type,
                component=component,
            ),
        )

    def import_object(self, obj: Obj) -> Obj:
        return self._mutate(
            self._ns_of_obj(obj), lambda b: b.import_object(obj)
        )

    def purge_object(
        self, kind: str, name: str, namespace: Optional[str] = None
    ) -> bool:
        return self._mutate(
            self._ns_of(kind, namespace),
            lambda b: b.purge_object(kind, name, namespace=namespace),
        )

    # -- registry / admission (broadcast: every partition serves every
    #    kind, exactly like every kube apiserver replica serves every
    #    resource) ----------------------------------------------------------

    def register_kind(
        self,
        api_version: str,
        kind: str,
        plural: str,
        namespaced: bool = True,
    ) -> None:
        for b in self.backends.values():
            b.register_kind(api_version, kind, plural, namespaced)

    def register_admission_hook(
        self, kinds, fn, mutating: bool = True, name: str = ""
    ) -> None:
        for b in self.backends.values():
            b.register_admission_hook(kinds, fn, mutating=mutating, name=name)

    # -- reads ---------------------------------------------------------------

    def get(self, kind: str, name: str, namespace: Optional[str] = None) -> Obj:
        self._shed_expired("partition read dispatch")
        info = self.type_info(kind)
        p = self._route(namespace if info.namespaced else None)
        return self._call_backend(
            p, lambda b: b.get(kind, name, namespace=namespace)
        )

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Obj] = None,
        field_matches: Optional[dict[str, Any]] = None,
        limit: Optional[int] = None,
    ) -> list[Obj]:
        info = self.type_info(kind)
        if not info.namespaced or namespace:
            p = self._route(namespace if info.namespaced else None)
            return self.backends[p].list(
                kind,
                namespace=namespace,
                label_selector=label_selector,
                field_matches=field_matches,
                limit=limit,
            )
        if limit:
            items, _ = self.list_chunk(
                kind,
                label_selector=label_selector,
                field_matches=field_matches,
                limit=limit,
            )
            return items
        # cluster-spanning gather, ownership-filtered and re-merged
        # into the single-store (namespace, name) order
        rows: list[tuple[tuple[str, str], Obj]] = []
        for p, b in self.backends.items():
            for item in b.list(  # unbounded-ok: mirrors APIServer.list's unpaginated contract; bounded callers pass limit= and take the paged path above
                kind,
                label_selector=label_selector,
                field_matches=field_matches,
            ):
                ns = item.get("metadata", {}).get("namespace", "")
                if self._map.owner_of(ns) != p:
                    continue
                rows.append(((ns, item["metadata"].get("name", "")), item))
        rows.sort(key=lambda kv: kv[0])
        return [item for _, item in rows]

    # -- scatter-gather pagination ------------------------------------------

    def list_chunk(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Obj] = None,
        field_matches: Optional[dict[str, Any]] = None,
        limit: Optional[int] = None,
        continue_token: Optional[str] = None,
    ) -> tuple[list[Obj], str]:
        """One page of a paginated list. Namespaced walks route to the
        owning partition and carry that partition's own tokens
        untouched. Cluster-spanning walks are a k-way merge: each
        merged page holds the globally smallest (namespace, name) keys
        across every partition's cursor, and the composite token pins
        each partition's rv and cursor independently — so one
        partition compacting past its pin 410s ONLY that partition's
        leg, which restarts at a fresh rv pin from its saved cursor
        (kube's inconsistent-continuation semantics, applied
        per-partition) while every other leg resumes exactly where it
        stood."""
        info = self.type_info(kind)
        if info.namespaced and namespace:
            p = self._route(namespace)
            return self.backends[p].list_chunk(
                kind,
                namespace=namespace,
                label_selector=label_selector,
                field_matches=field_matches,
                limit=limit,
                continue_token=continue_token,
            )
        if not info.namespaced:
            return self.backends[0].list_chunk(
                kind,
                namespace=namespace,
                label_selector=label_selector,
                field_matches=field_matches,
                limit=limit,
                continue_token=continue_token,
            )
        return self._merged_list_chunk(
            kind, label_selector, field_matches, limit, continue_token
        )

    def _merged_list_chunk(
        self,
        kind: str,
        label_selector: Optional[Obj],
        field_matches: Optional[dict[str, Any]],
        limit: Optional[int],
        continue_token: Optional[str],
    ) -> tuple[list[Obj], str]:
        limit = max(int(limit) if limit else self.LIST_DEFAULT_LIMIT, 1)
        per_page = self.merge_page_limit or limit
        parts = sorted(self.backends)
        # cross-call walk state is ONLY the per-partition (rv pin,
        # cursor) vector. The cursor is the last key this walk
        # CONSUMED from that partition — emitted or ownership-filtered;
        # rows fetched but not emitted before the merged page filled
        # are simply refetched next call. "done" is call-local: a
        # partition whose cursor sits at its last key answers the next
        # call with one cheap empty page.
        rvs: dict[int, int] = {}
        cursors: dict[int, Optional[list[str]]] = {p: None for p in parts}
        done: set[int] = set()
        if continue_token:
            payload = decode_continue(continue_token)
            if not payload.get(_FLEET):
                raise BadRequest(
                    "continue token is not a fleet token; it belongs to a "
                    "single-partition walk"
                )
            if payload.get("kind") != kind or payload.get("ns", ""):
                raise BadRequest(
                    "fleet continue token does not match this list's kind"
                )
            rvs = {int(p): int(v) for p, v in (payload.get("rv") or {}).items()}
            for p, k in (payload.get("k") or {}).items():
                cursors[int(p)] = list(k) if k else None

        def fetch(p: int) -> list[Obj]:
            """One ownership-filtered page from partition ``p``'s
            cursor; advances the cursor past filtered rows and 410s by
            re-pinning ONLY this partition (partial restart)."""
            b = self.backends[p]
            while True:
                # every leg of the scatter-gather re-checks the
                # deadline: a merge over N partitions must not keep
                # paging N-1 healthy legs after the caller gave up
                self._shed_expired(f"the partition {p} merge leg")
                if p not in rvs:
                    # a remote backend reports None before its first
                    # response carried X-Served-RV; pin 0 and let the
                    # first page's serve establish the horizon
                    rvs[p] = int(b.applied_rv() or 0)
                ptoken = None
                if cursors[p] is not None:
                    ptoken = encode_continue(
                        {"rv": rvs[p], "kind": kind, "ns": "", "k": cursors[p]}
                    )
                try:
                    items, _ = self._call_backend(
                        p,
                        lambda b: b.list_chunk(
                            kind,
                            label_selector=label_selector,
                            field_matches=field_matches,
                            limit=per_page,
                            continue_token=ptoken,
                        ),
                    )
                except Expired:
                    # partial restart: fresh rv pin, SAME cursor — the
                    # other partitions' legs are untouched
                    del rvs[p]
                    continue
                if len(items) < per_page:
                    done.add(p)
                keep: list[Obj] = []
                for item in items:
                    meta = item.get("metadata", {})
                    key = [meta.get("namespace", ""), meta.get("name", "")]
                    if self._map.owner_of(key[0]) != p:
                        cursors[p] = key  # never emitted: skip past it
                        continue
                    keep.append(item)
                if keep or p in done:
                    return keep
                # a full page of not-owned rows (mid-move garbage):
                # cursor advanced a page, fetch the next one

        heads: dict[int, list[Obj]] = {}
        out: list[Obj] = []

        def key_of(item: Obj) -> tuple[str, str]:
            meta = item.get("metadata", {})
            return (meta.get("namespace", ""), meta.get("name", ""))

        while len(out) < limit:
            for p in parts:
                if p not in heads and p not in done:
                    heads[p] = fetch(p)
                if p in heads and not heads[p]:
                    if p in done:
                        del heads[p]
                    else:
                        heads[p] = fetch(p)
                        if not heads[p]:
                            del heads[p]
            live = {p: h for p, h in heads.items() if h}
            if not live:
                break
            p_min = min(live, key=lambda p: key_of(live[p][0]))
            item = heads[p_min].pop(0)
            meta = item.get("metadata", {})
            cursors[p_min] = [meta.get("namespace", ""), meta.get("name", "")]
            out.append(item)

        exhausted = all(
            p in done and not heads.get(p) for p in parts
        )
        token = ""
        if not exhausted:
            token = encode_continue(
                {
                    _FLEET: 1,
                    "kind": kind,
                    "ns": "",
                    "rv": {str(p): rvs[p] for p in parts if p in rvs},
                    "k": {str(p): cursors[p] for p in parts},
                }
            )
        return out, token

    # -- watches -------------------------------------------------------------

    @staticmethod
    def _leg_watch(b: Any, **kw: Any) -> Watch:
        # in-process APIServer/ReplicaStore take an ``inline`` kwarg;
        # RemoteAPIServer (HTTP legs under the bench/runner) does not —
        # it always pumps via a reader thread
        try:
            return b.watch(**kw)
        except TypeError:
            kw.pop("inline", None)
            return b.watch(**kw)

    def watch(
        self,
        kind: str,
        namespace: Optional[str] = None,
        send_initial: bool = True,
        resource_version: Optional[str] = None,
        inline: bool = True,
    ) -> Watch:
        info = self.type_info(kind)
        if info.namespaced and namespace:
            return self._leg_watch(
                self.backends[self._route(namespace)],
                kind=kind,
                namespace=namespace,
                send_initial=send_initial,
                resource_version=resource_version,
                inline=inline,
            )
        if not info.namespaced:
            return self._leg_watch(
                self.backends[0],
                kind=kind,
                send_initial=send_initial,
                resource_version=resource_version,
                inline=inline,
            )
        # cluster-spanning merged stream, one leg per partition
        rvs: dict[int, int] = {}
        if resource_version is not None:
            if is_composite_token(str(resource_version)):
                rvs = decode_fleet_rvs(str(resource_version), kind)
            else:
                raise Invalid(
                    "a cluster-spanning watch on a partitioned store "
                    "resumes with a composite fleet token "
                    "(MergedWatch.resume_token()), not a scalar rv — "
                    "per-partition rv spaces are independent"
                )
        w = MergedWatch(self, kind, namespace)
        for p, b in sorted(self.backends.items()):
            try:
                leg = self._leg_watch(
                    b,
                    kind=kind,
                    send_initial=(send_initial and resource_version is None),
                    resource_version=(
                        str(rvs[p]) if p in rvs else None
                    ),
                    inline=inline,
                )
            except Expired as e:
                w.mark_expired(p, str(e))
                continue
            w.attach_leg(p, leg)
        # the slow-consumer bound covers the LIVE backlog on top of the
        # merged initial dump the legs just pumped in (same posture as
        # APIServer.watch — the dump must not evict its own consumer)
        w.maxsize = w._q.qsize() + getattr(
            self.backends[0], "WATCH_CACHE_SIZE", APIServer.WATCH_CACHE_SIZE
        )
        self._merged.add(w)
        return w

    # MergedWatch's Watch plumbing calls back into its "server"
    def _remove_watch(self, w: Watch) -> None:
        self._merged.discard(w)  # legs are stopped by MergedWatch.stop

    def _evict_watch(self, w: Watch) -> None:
        self._merged.discard(w)
        if isinstance(w, MergedWatch):
            for leg in w._legs.values():
                try:
                    leg.stop()
                except Exception:  # noqa: BLE001 — eviction is best-effort
                    log.debug(
                        "merged watch: leg stop failed on evict",
                        exc_info=True,
                    )

    # -- fleet surfaces ------------------------------------------------------

    def applied_rv(self) -> int:
        """Monotone fleet horizon: the SUM of per-partition applied
        rvs (each is monotone, so the sum is). A staleness surface,
        not a resume point — resumes carry the per-partition vector."""
        return sum(int(b.applied_rv() or 0) for b in self.backends.values())

    def applied_rvs(self) -> dict[int, int]:
        return {p: int(b.applied_rv() or 0) for p, b in self.backends.items()}

    def kind_version(self, kind: str) -> int:
        return sum(int(b.kind_version(kind) or 0) for b in self.backends.values())

    def state_digest(self) -> str:
        """The fleet digest: per-partition digests composed as sorted
        ``(partition, digest, rv)`` tuples (satellite of the PR-13
        bit-identity drill, fleet-wide)."""
        return APIServer.compose_digests(self.partition_digests())

    def partition_digests(self) -> list[tuple[int, str, int]]:
        return [
            (p, b.state_digest(), int(b.applied_rv() or 0))
            for p, b in sorted(self.backends.items())
        ]

    def replication_cut(self) -> Obj:
        raise Invalid(
            "a partitioned store replicates PER PARTITION (rv spaces "
            "are independent); scope the pull with ?partition=<i> / "
            "partition_backend(i)"
        )

    def replication_watch(self, *args, **kwargs) -> Watch:
        raise Invalid(
            "a partitioned store replicates PER PARTITION (rv spaces "
            "are independent); scope the pull with ?partition=<i> / "
            "partition_backend(i)"
        )

    def replication_control(self) -> Obj:
        """The merged stream's CONTROL heartbeat: per-partition
        (rv, epoch) vector instead of one scalar horizon."""
        return {
            "type": "CONTROL",
            "partitions": [
                {
                    "partition": p,
                    "rv": int(b.applied_rv() or 0),
                    "epoch": getattr(b, "replication_epoch", 0),
                }
                for p, b in sorted(self.backends.items())
            ],
            "ts": time.time(),
        }

    def debug_queues(self) -> Obj:
        return {
            str(p): b.debug_queues()
            for p, b in sorted(self.backends.items())
            if hasattr(b, "debug_queues")
        }

    def snapshot_now(self) -> None:
        for b in self.backends.values():
            if getattr(b, "_wal", None) is not None:
                b.snapshot_now()

    def close(self) -> None:
        for b in self.backends.values():
            if hasattr(b, "close"):
                b.close()

    def attach_metrics(self, registry) -> None:
        for b in self.backends.values():
            if hasattr(b, "attach_metrics"):
                b.attach_metrics(registry)

    def __getattr__(self, name: str):
        # everything else (fence clocks, watch-eviction counters, …)
        # falls through to the meta partition, the ReadSplitAPI move
        return getattr(self.backends[0], name)


# ---------------------------------------------------------------------------
# live partition move


MOVE_LEASE_NS = "kube-system"


class PartitionMover:
    """Ship one namespace between partitions, live, with zero lost
    acks — the PR-13 snapshot/catch-up protocol as the data plane.

    Protocol (sched_point-marked for the schedule explorer):

    1. **cut** — a consistent ``replication_cut`` of the source and a
       tail feed (``replication_watch``) opened AT the cut's rv, while
       writes keep flowing.
    2. **ship** — the cut's objects for the moving namespace are
       ``import_object``-ed into the destination (identity preserved,
       fresh local rvs), under the move lease's fencing token: a
       second mover racing this one is FencedOut atomically with its
       first apply.
    3. **tail** — the feed's records for the namespace replay onto the
       destination until the backlog is small, still live.
    4. **freeze** — the router refuses new writes for the namespace
       (retryable 429; never acked, so never lost) while the last tail
       records drain up to the source's frozen horizon.
    5. **takeover** — the destination's fencing epoch bumps past the
       source's, the router retargets the namespace (merged streams
       get a CONTROL ``moved`` frame), and the freeze lifts.
    6. **scrub** — the source's now-unowned copies are purged (WAL'd
       DELETEs; ownership filtering already hides them from every
       merged read, so the scrub is garbage collection, not
       correctness).

    ``run()`` is idempotent: a crash at ANY point (the kill-point
    drills sweep the destination's WAL ops) re-runs to completion —
    imports upsert, purges tolerate absence, and the router override
    is recorded only at takeover."""

    # seconds the freeze window may wait for the frozen tail to drain
    QUIESCE_TIMEOUT = _env_float("PARTITION_MOVE_QUIESCE_TIMEOUT", 5.0)
    # records applied per live catch-up round before re-checking the
    # backlog (bounds the time the feed is drained without yielding)
    TAIL_BUDGET = _env_int("PARTITION_MOVE_TAIL_BUDGET", 10000)
    # live catch-up stops chasing when the un-drained backlog is below
    # this many records — small enough to drain inside the freeze
    FREEZE_BACKLOG = _env_int("PARTITION_MOVE_FREEZE_BACKLOG", 64)

    def __init__(
        self,
        router: PartitionRouter,
        namespace: str,
        destination: int,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.router = router
        self.namespace = namespace
        self.destination = int(destination)
        self.source = router.owner_of(namespace)
        self.clock = clock
        self.lease_name = f"partition-move-{namespace}"
        self.stats: Obj = {}

    # -- fencing -------------------------------------------------------------

    def _acquire_move_token(self, dst: Any) -> int:
        """Create-or-bump the move Lease IN THE DESTINATION partition
        (the partition the handover writes land in, so the fence check
        is atomic with each apply) and return the fresh epoch."""
        try:
            lease = dst.get("Lease", self.lease_name, MOVE_LEASE_NS)
        except NotFound:
            lease = dst.create(  # unfenced-ok: creates the fencing lease itself (Lease writes are fence-exempt, like the elector's)
                {
                    "apiVersion": "coordination.k8s.io/v1",
                    "kind": "Lease",
                    "metadata": {
                        "name": self.lease_name,
                        "namespace": MOVE_LEASE_NS,
                    },
                    "spec": {"fencingToken": 0},
                }
            )
        spec = lease.setdefault("spec", {})
        token = int(spec.get("fencingToken", 0) or 0) + 1
        spec["fencingToken"] = token
        spec["holderIdentity"] = f"mover-{self.source}-{self.destination}"
        dst.update(lease)  # unfenced-ok: the epoch bump that CREATES the new fence; serialized by optimistic concurrency
        return token

    # -- data plane ----------------------------------------------------------

    def _in_namespace(self, obj: Obj) -> bool:
        return (obj.get("metadata") or {}).get("namespace") == self.namespace

    def _apply(self, dst: Any, etype: str, obj: Obj) -> None:
        meta = obj.get("metadata", {})
        if etype == "DELETED":
            dst.purge_object(
                obj.get("kind", ""), meta.get("name", ""), self.namespace
            )
        else:  # ADDED / MODIFIED — identity-preserving upsert
            dst.import_object(obj)

    def _drain_tail(
        self, feed: Watch, dst: Any, budget: int, block: bool
    ) -> tuple[int, int]:
        """Apply up to ``budget`` namespace records from the feed;
        returns (applied, last rv seen — any namespace)."""
        applied, last_rv = 0, 0
        while applied < budget:
            item = feed.get(timeout=0.05) if block else feed.try_get()
            if item is None:
                break
            etype, obj = item
            if etype in ("REGISTER", "CONTROL"):
                continue
            try:
                last_rv = int(
                    obj.get("metadata", {}).get("resourceVersion", 0) or 0
                )
            except (TypeError, ValueError):
                pass
            if self._in_namespace(obj):
                self._apply(dst, etype, obj)
                applied += 1
        return applied, last_rv

    # -- protocol ------------------------------------------------------------

    def run(self) -> Obj:
        if self.destination == self.source:
            return {"moved": 0, "noop": True}
        src = self.router.backend(self.source)
        dst = self.router.backend(self.destination)
        token = self._acquire_move_token(dst)
        t_start = self.clock()

        _schedule.sched_point("partition.move.cut")
        cut = src.replication_cut()
        cut_rv = int(cut.get("rv", 0))
        feed = src.replication_watch(from_rv=cut_rv, inline=True)
        moving = [o for o in cut.get("objects", []) if self._in_namespace(o)]

        shipped = tailed = 0
        frozen_ms = 0.0
        try:
            with fenced(MOVE_LEASE_NS, self.lease_name, token):
                _schedule.sched_point("partition.move.ship")
                for obj in moving:
                    dst.import_object(obj)
                    shipped += 1

                # live catch-up: chase the tail until the backlog is
                # small enough to drain inside the freeze window
                last_rv = cut_rv
                while True:
                    horizon = int(src.applied_rv())
                    if horizon - last_rv <= self.FREEZE_BACKLOG:
                        break
                    n, rv = self._drain_tail(
                        feed, dst, self.TAIL_BUDGET, block=False
                    )
                    tailed += n
                    last_rv = max(last_rv, rv)
                    if n == 0:
                        # feed is drained but the horizon moved: the
                        # gap is non-namespace traffic already seen
                        if rv == 0:
                            break

                _schedule.sched_point("partition.move.freeze")
                self.router.freeze(self.namespace)
                t_freeze = self.clock()
                try:
                    # barrier: writes that slipped past the frozen
                    # check before the freeze landed must commit (or
                    # reject) before the horizon below is trustworthy
                    if not self.router.quiesce_writes(
                        self.namespace, timeout=self.QUIESCE_TIMEOUT
                    ):
                        raise TooManyRequests(
                            f"partition move of {self.namespace}: in-"
                            "flight writes did not quiesce inside "
                            f"{self.QUIESCE_TIMEOUT}s; aborted before "
                            "takeover — retry",
                            retry_after=1.0,
                        )
                    # frozen horizon: nothing new for the namespace can
                    # be acked past this; drain the feed up to it
                    horizon = int(src.applied_rv())
                    deadline = self.clock() + self.QUIESCE_TIMEOUT
                    _schedule.sched_point("partition.move.tail")
                    while last_rv < horizon and self.clock() < deadline:
                        n, rv = self._drain_tail(
                            feed, dst, self.TAIL_BUDGET, block=True
                        )
                        tailed += n
                        last_rv = max(last_rv, rv)
                    if last_rv < horizon:
                        raise TooManyRequests(
                            f"partition move of {self.namespace} could not "
                            f"quiesce inside {self.QUIESCE_TIMEOUT}s "
                            f"(tail at rv {last_rv}, horizon {horizon}); "
                            "aborted before takeover — retry",
                            retry_after=1.0,
                        )
                    _schedule.sched_point("partition.move.takeover")
                    dst.replication_epoch = (
                        max(
                            int(getattr(dst, "replication_epoch", 0)),
                            int(getattr(src, "replication_epoch", 0)),
                        )
                        + 1
                    )
                    self.router.retarget(self.namespace, self.destination)
                finally:
                    self.router.unfreeze(self.namespace)
                    frozen_ms = (self.clock() - t_freeze) * 1000.0
                    _schedule.sched_point("partition.move.unfreeze")
        finally:
            feed.stop()

        scrubbed = self._scrub(src)
        self.stats = {
            "namespace": self.namespace,
            "from": self.source,
            "to": self.destination,
            "token": token,
            "shipped": shipped,
            "tailed": tailed,
            "scrubbed": scrubbed,
            "frozen_ms": round(frozen_ms, 3),
            "total_ms": round((self.clock() - t_start) * 1000.0, 3),
        }
        return self.stats

    def _scrub(self, src: Any) -> int:
        """Post-takeover garbage collection of the source's copies.
        Ownership filtering already hides them from every merged read
        and stream, so a crash mid-scrub leaves garbage, not
        incorrectness; the purge goes through the source's WAL so its
        own read replicas converge too."""
        scrubbed = 0
        for kind in list(getattr(src, "_store", {})):
            info = src.type_info(kind)
            if not info.namespaced:
                continue
            for obj in src.list(kind, namespace=self.namespace):  # unbounded-ok: post-takeover scrub of one namespace bucket, off every serving path
                # direct source access: the router now routes this
                # namespace to the destination, and the move lease
                # lives there — the scrub is the one deliberately
                # unfenced leg (see GUIDE: move protocol)
                if src.purge_object(  # unfenced-ok: source-side GC after takeover; the namespace is already unowned and invisible
                    kind, obj["metadata"]["name"], self.namespace
                ):
                    scrubbed += 1
        return scrubbed


# ---------------------------------------------------------------------------
# fleet assembly


def build_partitions(
    n: int,
    wal_dir: str = "",
    wal_factory: Optional[Callable[[int], Any]] = None,
    **apiserver_kwargs,
) -> PartitionRouter:
    """N in-process partitions behind a router — the platform's
    ``STORE_PARTITIONS`` shape. With ``wal_dir`` set each partition
    recovers from (or creates) its own WAL under ``<wal_dir>/p<i>``;
    ``wal_factory(i)`` overrides WAL construction (the drills inject
    fault IO per partition)."""
    from odh_kubeflow_tpu.machinery.wal import WriteAheadLog

    backends: dict[int, APIServer] = {}
    for i in range(max(1, int(n))):
        if wal_factory is not None:
            backends[i] = APIServer.recover(wal_factory(i), **apiserver_kwargs)
        elif wal_dir:
            backends[i] = APIServer.recover(
                WriteAheadLog(os.path.join(wal_dir, f"p{i}")),
                **apiserver_kwargs,
            )
        else:
            backends[i] = APIServer(**apiserver_kwargs)
    return PartitionRouter(backends)


def replica_router_from_env() -> Optional[tuple[Any, list[Any]]]:
    """Partition-aware ``REPLICA_OF``: run one follower ReplicaStore
    per partition behind a reads-only router (reads merge fleet-wide;
    mutations 307 to the owning partition's leader). Two shapes:

    - ``REPLICA_OF=<url0>,<url1>,…`` — one URL per partition leader
      (partition i replicates from url i);
    - ``REPLICA_OF=<router-url>`` + ``STORE_PARTITIONS=N`` — every
      partition replicates through ONE router-fronted endpoint,
      scoping each pull with ``?partition=<i>``.

    Returns (router, replication clients), or None when ``REPLICA_OF``
    is a single URL with no partitioning (the classic follower path).
    """
    raw = os.environ.get("REPLICA_OF", "")
    n_env = partitions_from_env()
    if "," not in raw and n_env <= 1:
        return None
    from odh_kubeflow_tpu.machinery.replica import (
        ReplicaStore,
        ReplicationClient,
    )

    urls = [u.strip() for u in raw.split(",") if u.strip()]
    backends: dict[int, Any] = {}
    clients: list[Any] = []
    if len(urls) > 1:
        for i, url in enumerate(urls):
            rep = ReplicaStore(url)
            backends[i] = rep
            clients.append(ReplicationClient(rep).start())
        router_urls = dict(enumerate(urls))
    else:
        for i in range(n_env):
            rep = ReplicaStore(urls[0])
            backends[i] = rep
            clients.append(ReplicationClient(rep, partition=i).start())
        router_urls = {i: urls[0] for i in range(n_env)}
    router = PartitionRouter(
        backends,
        owned=set(),  # a follower fleet leads nothing: every write 307s
        urls=router_urls,
    )
    return router, clients
