from odh_kubeflow_tpu.machinery.store import (  # noqa: F401
    APIServer,
    Conflict,
    Denied,
    NotFound,
    AlreadyExists,
    Invalid,
)
