"""Shared process entrypoint plumbing for split-process components.

Every `python -m odh_kubeflow_tpu.<component>` command line in the
manifests boots the same way: attach to $KUBE_API_URL, build the
component, serve/reconcile forever. One implementation here so the
contract (env names, banner, lifecycle) can't drift across the eight
entrypoints.
"""

from __future__ import annotations

import os
import time
from typing import Callable


def _partition_leaders(api):
    """``PARTITION_LEADERS=<url0>,<url1>,…``: client-side routing for
    a partitioned write path (machinery.partition) — one remote client
    per partition leader behind a PartitionRouter, so every mutation
    goes STRAIGHT to its namespace's owning leader instead of paying a
    307 redirect hop, and cluster-spanning lists/watches merge
    client-side with composite continue tokens. Unset = the single
    ``KUBE_API_URL`` endpoint, exactly the old wiring."""
    raw = os.environ.get("PARTITION_LEADERS", "")
    if not raw:
        return api
    from odh_kubeflow_tpu.machinery.client import api_from_env
    from odh_kubeflow_tpu.machinery.partition import PartitionRouter

    urls = [u.strip().rstrip("/") for u in raw.split(",") if u.strip()]
    backends = {i: api_from_env(url=u) for i, u in enumerate(urls)}
    # owned = every partition: the client routes writes itself; each
    # backend is that partition's own leader, no redirect needed
    return PartitionRouter(backends, urls=dict(enumerate(urls)))


def _split_reads(api):
    """``READ_FROM_REPLICA=<url>[,<url>…]``: serve this component's
    reads — lists, watches (so the informer cache feeds off the
    replica), and gets — from follower replicas, writes from the
    leader as before. A comma-separated list spreads reads across N
    replicas (round-robin, rendezvous-sticky watches) with
    per-endpoint failure fallback to the next replica. The replica's
    bounded-staleness contract (X-Served-RV horizon, wait-or-410 on
    pinned rvs) rides along; unset = everything to the leader,
    exactly the old wiring."""
    read_url = os.environ.get("READ_FROM_REPLICA", "")
    if not read_url:
        return api
    from odh_kubeflow_tpu.machinery.client import api_from_env
    from odh_kubeflow_tpu.machinery.replica import ReadSplitAPI

    return ReadSplitAPI(api, api_from_env(url=read_url))


def _wrap_cached(api):
    """Front the remote api with the informer-backed shared cache
    (reads become watch-fed, indexed, zero-copy; writes pass through).
    ``INFORMER_CACHE=false`` opts out — e.g. a debug run against an
    apiserver whose watch path is suspect."""
    if os.environ.get("INFORMER_CACHE", "true").lower() != "true":
        return api, None
    from odh_kubeflow_tpu.machinery.cache import (
        CachedClient,
        InformerCache,
        register_platform_indexers,
    )

    # only cache kinds the remote registry knows (CRDs were registered
    # by api_from_env; a kind the server rejects would fail the watch)
    from odh_kubeflow_tpu.machinery.cache import DEFAULT_CACHED_KINDS

    from odh_kubeflow_tpu.machinery.store import NotFound

    kinds = []
    for kind in DEFAULT_CACHED_KINDS:
        try:
            api.type_info(kind)
            kinds.append(kind)
        except NotFound:  # kind not registered with this server → skip
            continue
    cache = InformerCache(api, kinds=kinds)
    register_platform_indexers(cache)
    return CachedClient(api, cache), cache


def _install_span_exporter(api) -> None:
    """Ship finished spans to the apiserver's ``/debug/traces/ingest``
    so split-process hops (webhook→store→reconcile→scheduler→kubelet)
    assemble into ONE trace on its /debug/traces zpage. TRACE_EXPORT=
    false opts out; no endpoint (embedded api) is a no-op."""
    if os.environ.get("TRACE_EXPORT", "true").lower() != "true":
        return
    base_url = getattr(api, "base_url", None)
    if not base_url:
        return
    from odh_kubeflow_tpu.utils import tracing

    tracing.RemoteSpanExporter(base_url).install()


def run_controller(name: str, register: Callable) -> None:
    """``register(api, mgr)`` wires controllers into the manager.

    LEADER_ELECT=true (flag parity: notebook-controller/main.go:56-70)
    gates reconciling on holding a coordination.k8s.io Lease named
    ``<name>-leader`` — replicas > 1 become an HA pair. Losing the
    lease exits the process (controller-runtime semantics: never keep
    reconciling without it)."""
    from odh_kubeflow_tpu.controllers.runtime import Manager
    from odh_kubeflow_tpu.machinery.client import api_from_env
    from odh_kubeflow_tpu.machinery.faults import maybe_wrap

    # GRAFT_CHAOS=<seed>: deterministic fault injection on the API path
    # (chaos soak runs); unset = the raw client, zero overhead
    raw = api_from_env()
    _install_span_exporter(raw)
    api = maybe_wrap(_partition_leaders(raw))
    api, cache = _wrap_cached(_split_reads(api))

    elector = None
    shard = None
    if os.environ.get("LEADER_ELECT", "").lower() == "true":
        from odh_kubeflow_tpu.machinery.leader import LeaderElector

        elector = LeaderElector(
            api,
            os.environ.get("LEADER_ELECTION_ID", f"{name}-leader"),
            namespace=os.environ.get("LEADER_ELECTION_NAMESPACE", "kubeflow"),
            lease_duration=float(os.environ.get("LEASE_DURATION", "15")),
        )
        print(f"{name}: waiting for leader lease…", flush=True)
        elector.acquire()

        def lost():
            print(f"{name}: leader lease lost; exiting", flush=True)
            os._exit(1)

        elector.run(on_lost=lost)

    # SHARD_GROUP=<group>: horizontally-replicated manager — this
    # replica joins the shard group and reconciles only the namespaces
    # it owns under rendezvous hashing; its writes carry the membership
    # lease's fencing token. Losing the membership heartbeat exits the
    # process (peers already resharded our slice).
    if os.environ.get("SHARD_GROUP", ""):
        from odh_kubeflow_tpu.machinery.leader import ShardMembership

        shard = ShardMembership(
            api,
            os.environ["SHARD_GROUP"],
            identity=os.environ.get("SHARD_IDENTITY") or None,
            namespace=os.environ.get("LEADER_ELECTION_NAMESPACE", "kubeflow"),
            lease_duration=float(os.environ.get("LEASE_DURATION", "15")),
        )
        shard.join()

        def shard_lost():
            print(f"{name}: shard membership lost; exiting", flush=True)
            os._exit(1)

        shard.run(on_lost=shard_lost)
        print(
            f"{name}: shard member {shard.identity} of "
            f"{shard.group} (epoch {shard.token})",
            flush=True,
        )

    mgr = Manager(api, cache=cache, elector=elector, shard=shard)
    register(api, mgr)
    mgr.start()  # includes the informer start/sync barrier

    # controller-runtime's --metrics-bind-address: every split-process
    # controller serves its manager registry on its own port.
    # METRICS_PORT=0 disables (e.g. sidecar-less debug runs).
    metrics_port = int(os.environ.get("METRICS_PORT", "8080"))
    if metrics_port:
        from odh_kubeflow_tpu.utils import prometheus

        _, bound, _ = prometheus.serve_metrics(
            mgr.metrics_registry,
            os.environ.get("METRICS_HOST", "0.0.0.0"),
            metrics_port,
        )
        print(f"{name} metrics on :{bound}/metrics", flush=True)
    print(f"{name} running", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        mgr.stop()
        if elector is not None:
            elector.release()
        if shard is not None:
            shard.leave()


def run_replica(name: str = "replica") -> None:
    """``REPLICA_OF=<leader-url>``: run a follower read replica — WAL
    stream pulled from the leader, list/watch served locally, writes
    307'd back at the leader. Deployment shape: one leader + N of
    these behind a read load balancer, with controllers/web apps
    pointed at them via ``READ_FROM_REPLICA``."""
    from odh_kubeflow_tpu.machinery.replica import serve_replica

    serve_replica()


def run_web(name: str, default_port: int, build: Callable) -> None:
    """``build(api)`` returns an object exposing a ``.app`` WSGI app."""
    from odh_kubeflow_tpu.machinery.client import api_from_env
    from odh_kubeflow_tpu.machinery.faults import maybe_wrap

    raw = api_from_env()
    _install_span_exporter(raw)
    api, cache = _wrap_cached(
        _split_reads(maybe_wrap(_partition_leaders(raw)))
    )
    if cache is not None:
        cache.start(live=True)
        cache.wait_for_sync()
    backend = build(api)
    host = os.environ.get("HOST", "0.0.0.0")
    port = int(os.environ.get("PORT", str(default_port)))
    httpd = backend.app.serve(host, port)
    print(f"{name} on http://{host}:{httpd.server_address[1]}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        httpd.shutdown()
